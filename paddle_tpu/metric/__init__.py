"""paddle.metric (parity: python/paddle/metric/metrics.py:44,195)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        correct = idx == label_np[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct) if not isinstance(correct, np.ndarray) else correct
        accs = []
        for k in self.topk:
            num_corr = correct[..., :k].any(axis=-1).sum()
            total = correct.shape[0] if correct.ndim > 1 else correct.shape[0]
            total = int(np.prod(correct.shape[:-1]))
            self.total[self.topk.index(k)] += int(num_corr)
            self.count[self.topk.index(k)] += total
            accs.append(num_corr / max(total, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels)
        pred_pos = (preds > 0.5).astype(np.int64).reshape(-1)
        labels = labels.astype(np.int64).reshape(-1)
        self.tp += int(((pred_pos == 1) & (labels == 1)).sum())
        self.fp += int(((pred_pos == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels)
        pred_pos = (preds > 0.5).astype(np.int64).reshape(-1)
        labels = labels.astype(np.int64).reshape(-1)
        self.tp += int(((pred_pos == 1) & (labels == 1)).sum())
        self.fn += int(((pred_pos == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        bins = np.clip(
            (pos_prob * self.num_thresholds).astype(np.int64), 0, self.num_thresholds
        )
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # walk thresholds high→low accumulating trapezoids
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred_np = _np(input)
    label_np = _np(label)
    idx = np.argsort(-pred_np, axis=-1)[..., :k]
    if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
        label_np = label_np[..., 0]
    corr = (idx == label_np[..., None]).any(axis=-1).mean()
    import jax.numpy as jnp

    return Tensor(jnp.asarray(np.float32(corr)))
