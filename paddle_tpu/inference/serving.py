"""Continuous-batching LLM serving over paged KV caches.

Capability slot: the reference's LLM serving stack (the C++ side of
`block_multi_head_attention` + the fastdeploy/serving slot managers that
drive it). TPU-native design:

- KV lives in PAGES `[num_pages, Hkv, page_size, D]` per layer; a
  `PagePool` hands pages to sequences on admission and reclaims them on
  completion, so memory scales with live tokens, not max_seq * slots.
- `ContinuousBatchingEngine` drives the vLLM-style loop: admit waiting
  requests into free slots (prefill writes the prompt's KV into that
  sequence's pages), then run ONE batched decode step for every live
  slot per `step()` — new requests join mid-flight without stalling
  running ones, finished slots free their pages immediately.
- The decode step's attention is the pallas paged kernel
  (`ops/pallas/decode_attention.paged_attention`): block tables via
  scalar prefetch, so only the pages a sequence owns are fetched.

Greedy decoding; works with the GPT/LLaMA stacked-weights families
(anything exposing `_decode_params()` — llama.py:66).
"""
from __future__ import annotations

import math
from collections import deque

import numpy as np

__all__ = ["PagePool", "ContinuousBatchingEngine"]


class PagePool:
    """Free-list page allocator (the block manager)."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = deque(range(num_pages))

    def alloc(self, n: int):
        if n > len(self._free):
            raise MemoryError(
                f"PagePool: need {n} pages, {len(self._free)} free")
        return [self._free.popleft() for _ in range(n)]

    def free(self, pages):
        self._free.extend(pages)

    @property
    def available(self):
        return len(self._free)


class _Request:
    __slots__ = ("rid", "prompt", "generated", "length", "pages")

    def __init__(self, rid, prompt):
        self.rid = rid
        self.prompt = list(prompt)
        self.generated = []
        self.length = 0          # tokens currently in the kv pages
        self.pages = []


class ContinuousBatchingEngine:
    def __init__(self, model, max_slots=4, page_size=64, num_pages=None,
                 max_seq_len=None, max_new_tokens=32, eos_token_id=None):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        cfg = model.config
        self.cfg = cfg
        self.page = page_size
        self.max_seq = max_seq_len or cfg.max_seq_len
        self.pages_per_seq = (self.max_seq + page_size - 1) // page_size
        self.max_slots = max_slots
        self.max_new_tokens = max_new_tokens
        self.eos = eos_token_id
        num_pages = num_pages or (max_slots * self.pages_per_seq + 2)
        self.pool = PagePool(num_pages)

        hd = cfg.hidden_size // cfg.num_heads
        self.hd, self.hkv = hd, cfg.num_kv_heads

        # weights, flattened like llama.generate
        params = model._decode_params()
        self._lp = [tuple(lp[k]._data for k in
                          ("ln1", "wq", "wk", "wv", "wo", "ln2", "wg",
                           "wu", "wd")) for lp in params]
        self._embed = model.model.embed_tokens.weight._data
        self._fnorm = model.model.final_norm.weight._data
        self._head = (model.lm_head.weight._data
                      if model.lm_head is not None else None)

        # paged caches per layer, KERNEL layout [Hkv, num_pages, page, D]
        # (what paged_attention consumes — no per-step transposes)
        dt = self._embed.dtype
        self.kc = [jnp.zeros((self.hkv, num_pages, page_size, hd), dt)
                   for _ in range(cfg.num_layers)]
        self.vc = [jnp.zeros((self.hkv, num_pages, page_size, hd), dt)
                   for _ in range(cfg.num_layers)]

        self._slots: list[_Request | None] = [None] * max_slots
        self._waiting: deque[_Request] = deque()
        self._next_rid = 0
        self._decode_jit = jax.jit(self._decode_step,
                           donate_argnums=(3, 4))

    # -- model math ---------------------------------------------------------
    @staticmethod
    def _rope(x, pos):
        """Shared framework rope (models/gpt.py) — serving stays
        bit-identical to training/generate."""
        from ..models.gpt import _rope_at_positions

        return _rope_at_positions(x, pos)

    def _prefill(self, req: _Request):
        """Run the prompt, write its KV into the request's pages, return
        the next (greedy) token. Per-request; the decode path is batched.

        Runs eagerly: each page-cache write copies the pool once per
        layer, a per-ADMISSION cost (not per-token). Jitting would need
        per-prompt-length retraces (bucket lengths first if admission
        cost ever dominates — see jit.to_static bucket_dynamic_shapes)."""
        jax, jnp = self._jax, self._jnp
        from .. import models  # noqa: F401  (keep import surface warm)
        from ..models.gpt import _rms_pure

        ids = jnp.asarray(np.asarray(req.prompt)[None, :])   # [1, S]
        s = ids.shape[1]
        x = self._embed[ids]
        pos0 = jnp.zeros((1,), jnp.int32)
        page_ids = np.asarray(req.pages, np.int64)
        for li, lp in enumerate(self._lp):
            ln1, wq, wk, wv, wo, ln2, wg, wu, wd = lp
            h = _rms_pure(x, ln1)
            q = (h @ wq).reshape(1, s, self.cfg.num_heads, self.hd)
            k = (h @ wk).reshape(1, s, self.hkv, self.hd)
            v = (h @ wv).reshape(1, s, self.hkv, self.hd)
            q, k = self._rope(q, pos0), self._rope(k, pos0)
            # causal attention over the prompt itself (no history)
            scale = 1.0 / math.sqrt(self.hd)
            rep = self.cfg.num_heads // self.hkv
            ck = jnp.repeat(k, rep, 2) if rep > 1 else k
            cv = jnp.repeat(v, rep, 2) if rep > 1 else v
            logits = jnp.einsum("bthd,bshd->bhts",
                                (q * scale).astype(jnp.float32),
                                ck.astype(jnp.float32))
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask[None, None], logits, -1e30)
            probs = jax.nn.softmax(logits, -1)
            o = jnp.einsum("bhts,bshd->bthd", probs,
                           cv.astype(jnp.float32)).astype(x.dtype)
            x = x + o.reshape(1, s, -1) @ wo
            h2 = _rms_pure(x, ln2)
            x = x + (jax.nn.silu(h2 @ wg) * (h2 @ wu)) @ wd
            # scatter this layer's k/v into the owned pages; ADJACENT
            # advanced indices (axes 1,2) stay in place -> value layout
            # [Hkv, S, D]
            tok_pages = page_ids[np.arange(s) // self.page]
            offs = jnp.asarray(np.arange(s) % self.page)
            self.kc[li] = self.kc[li].at[:, tok_pages, offs, :].set(
                jnp.swapaxes(k[0], 0, 1).astype(self.kc[li].dtype))
            self.vc[li] = self.vc[li].at[:, tok_pages, offs, :].set(
                jnp.swapaxes(v[0], 0, 1).astype(self.vc[li].dtype))
        x = _rms_pure(x, self._fnorm)[:, -1]
        lg = x @ self._head if self._head is not None else x @ self._embed.T
        req.length = s
        return int(np.asarray(jnp.argmax(lg, -1))[0])

    def _decode_step(self, tokens, lens, tables, kc, vc):
        """ONE batched decode: tokens [B] (last emitted), lens [B] tokens
        already cached, tables [B, pages_per_seq]. Returns (next [B],
        new kc, new vc)."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure
        from ..ops.pallas.decode_attention import paged_attention

        b = tokens.shape[0]
        x = self._embed[tokens][:, None]                 # [B, 1, H]
        page_ids = tables[jnp.arange(b), lens // self.page]
        offs = lens % self.page
        for li, lp in enumerate(self._lp):
            ln1, wq, wk, wv, wo, ln2, wg, wu, wd = lp
            h = _rms_pure(x, ln1)
            q = (h @ wq).reshape(b, 1, self.cfg.num_heads, self.hd)
            k = (h @ wk).reshape(b, 1, self.hkv, self.hd)
            v = (h @ wv).reshape(b, 1, self.hkv, self.hd)
            q, k = self._rope(q, lens), self._rope(k, lens)
            kc_l = kc[li].at[:, page_ids, offs, :].set(
                jnp.swapaxes(k[:, 0], 0, 1).astype(kc[li].dtype))
            vc_l = vc[li].at[:, page_ids, offs, :].set(
                jnp.swapaxes(v[:, 0], 0, 1).astype(vc[li].dtype))
            kc[li], vc[li] = kc_l, vc_l
            o = paged_attention(q[:, 0], kc_l, vc_l, tables, lens + 1)
            x = x + o.reshape(b, 1, -1).astype(x.dtype) @ wo
            h2 = _rms_pure(x, ln2)
            x = x + (jax.nn.silu(h2 @ wg) * (h2 @ wu)) @ wd
        x = _rms_pure(x, self._fnorm)[:, 0]
        lg = x @ self._head if self._head is not None else x @ self._embed.T
        return jnp.argmax(lg, -1).astype(jnp.int32), kc, vc

    # -- engine surface -----------------------------------------------------
    def submit(self, prompt_ids) -> int:
        total = len(prompt_ids) + self.max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"request needs {total} tokens (prompt "
                f"{len(prompt_ids)} + max_new {self.max_new_tokens}) > "
                f"max_seq_len {self.max_seq}")
        need = (total + self.page - 1) // self.page
        if need > self.pool.num_pages:
            raise ValueError(
                f"request needs {need} pages > pool size "
                f"{self.pool.num_pages}")
        rid = self._next_rid
        self._next_rid += 1
        self._waiting.append(_Request(rid, [int(t) for t in prompt_ids]))
        return rid

    def _admit(self):
        for i in range(self.max_slots):
            if self._slots[i] is not None or not self._waiting:
                continue
            req = self._waiting[0]
            need = (len(req.prompt) + self.max_new_tokens
                    + self.page - 1) // self.page
            if need > self.pool.available:
                break  # head-of-line waits for pages
            self._waiting.popleft()
            req.pages = self.pool.alloc(need)
            first = self._prefill(req)
            req.generated.append(first)
            self._slots[i] = req

    def _retire(self, req: _Request):
        self.pool.free(req.pages)
        req.pages = []
        return req.prompt + req.generated

    def step(self):
        """Admit + one batched decode tick. Returns {rid: full_ids} for
        requests finishing THIS tick."""
        jnp = self._jnp
        newly = {}
        # retire FIRST: a finishing slot frees pages and a slot for this
        # very tick's admissions
        for i, r in enumerate(list(self._slots)):
            if r is not None and (
                    len(r.generated) >= self.max_new_tokens or (
                    self.eos is not None and r.generated
                    and r.generated[-1] == self.eos)):
                newly[r.rid] = self._retire(r)
                self._slots[i] = None
        self._admit()
        live = [(i, r) for i, r in enumerate(self._slots) if r is not None]
        if not live:
            return newly
        # fixed-width batch: pad with slot 0's state (results discarded)
        pad_to = self.max_slots
        rows = [r for _, r in live] + [live[0][1]] * (pad_to - len(live))
        tokens = jnp.asarray([r.generated[-1] for r in rows], jnp.int32)
        lens = jnp.asarray([r.length for r in rows], jnp.int32)
        table_rows = []
        for r in rows:
            row = list(r.pages) + [0] * (self.pages_per_seq - len(r.pages))
            table_rows.append(row[: self.pages_per_seq])
        tables = jnp.asarray(np.asarray(table_rows, np.int32))
        nxt, self.kc, self.vc = self._decode_jit(
            tokens, lens, tables, list(self.kc), list(self.vc))
        nxt = np.asarray(nxt)
        for j, (i, r) in enumerate(live):
            r.length += 1
            r.generated.append(int(nxt[j]))
        return newly

    def run_until_complete(self, max_ticks=10000):
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            if not self._waiting and all(s is None for s in self._slots):
                return done
        raise TimeoutError("serving loop did not drain")
