"""Continuous-batching LLM serving over paged KV caches.

Capability slot: the reference's LLM serving stack (the C++ side of
`block_multi_head_attention` + the fastdeploy/serving slot managers that
drive it). TPU-native design:

- KV lives in PAGES `[num_pages, Hkv, page_size, D]` per layer; a
  `PagePool` hands pages to sequences on admission and reclaims them on
  completion, so memory scales with live tokens, not max_seq * slots.
- `ContinuousBatchingEngine` drives the vLLM-style loop: admit waiting
  requests into free slots (prefill writes the prompts' KV into their
  pages), then run ONE batched decode step for every live slot per
  `step()` — new requests join mid-flight without stalling running ones,
  finished slots free their pages immediately.
- Admission prefills ALL newly admitted prompts as one padded batch —
  one pass over the weights per admission group, not per request.
- The decode step's attention is the pallas paged kernel
  (`ops/pallas/decode_attention.paged_attention`): block tables via
  scalar prefetch, so only the pages a sequence owns are fetched.
- Sampling runs inside the jitted decode step: per-request temperature /
  top-k / top-p (temperature 0 = greedy, the default). Per-token
  streaming callbacks fire as tokens are emitted.
- Admission reserves only prefill pages; decode pages are allocated as
  sequences grow. On pool exhaustion the youngest request is preempted:
  policy "recompute" (default) folds its tokens into the resume prompt,
  "swap" round-trips its KV through host memory (measured tradeoffs in
  docs/ROUND5_RESPONSE.md).
- `enable_prefix_cache=True` adds automatic prefix caching: pages are
  content-addressed by sha1 block-hash chains and reused read-only
  across requests sharing a prompt prefix (~2x TTFT on long shared
  system prompts, measured).

Weights are packed into an explicit pytree passed to the jitted step (not
closed-over constants), so `reload_weights()` on a live engine takes
effect without recompilation.

Works with the GPT/LLaMA stacked-weights families (anything exposing
`_decode_params()` — llama.py:66).
"""
from __future__ import annotations

import math
import os
import time
import warnings
from collections import deque

import numpy as np

from .. import telemetry as _telemetry
from ..telemetry import trace as _trace

__all__ = ["PagePool", "ContinuousBatchingEngine", "int8_kv_enabled"]

# serving metrics (names/labels contract: docs/TELEMETRY.md). Gauges are
# refreshed once per step(); counters tick at the event sites.
_TELEMETRY_REG = _telemetry.get_registry()
_QUEUE_DEPTH = _telemetry.gauge(
    "serving_queue_depth", "requests waiting for admission")
_SLOTS_OCCUPIED = _telemetry.gauge(
    "serving_slots_occupied", "engine slots holding a live request")
_BATCH_OCCUPANCY = _telemetry.histogram(
    "serving_batch_occupancy", "live slots / max_slots per decode tick",
    buckets=tuple(i / 8 for i in range(1, 9)))
_KV_UTIL = _telemetry.gauge(
    "serving_kv_page_utilization", "fraction of KV pages allocated")
_ADMISSIONS = _telemetry.counter(
    "serving_admissions_total", "requests admitted into slots",
    labelnames=("kind",))
_PREEMPTIONS = _telemetry.counter(
    "serving_preemptions_total", "requests evicted under page pressure",
    labelnames=("policy",))
_STEPS = _telemetry.counter(
    "serving_steps_total", "engine decode ticks")
_REQ_LATENCY = _telemetry.histogram(
    "serving_request_latency_seconds", "submit-to-completion wall time")
_TTFT = _telemetry.histogram(
    "serving_ttft_seconds", "submit-to-first-token wall time")
_REF_UNDERFLOWS = _telemetry.counter(
    "serving_page_ref_underflows_total",
    "KV page refcount decremented below zero (double-release bug)")
_CANCELLATIONS = _telemetry.counter(
    "serving_cancellations_total",
    "requests cancelled before completion (docs/SERVING.md)",
    labelnames=("reason",))
_SPEC_TICKS = _telemetry.counter(
    "serving_spec_ticks_total",
    "decode ticks under a draft model: 'spec' ran draft+verify, "
    "'fallback' took the plain single-token path (sampled rows live)",
    labelnames=("mode",))
_SPEC_DRAFTED = _telemetry.counter(
    "serving_spec_draft_tokens_total",
    "draft tokens proposed to the verifier")
_SPEC_ACCEPTED = _telemetry.counter(
    "serving_spec_accepted_tokens_total",
    "draft tokens accepted by the target verify pass")
_INT8_KV = _telemetry.gauge(
    "serving_int8_kv_active",
    "1 when the engine stores paged KV as blockwise int8 (+fp32 "
    "per-row scales in the page table) — docs/SERVING.md")
_WEIGHT_BYTES = _telemetry.gauge(
    "serving_weight_bytes",
    "resident packed decode-weight bytes per storage dtype "
    "(docs/QUANT.md: int8-packed replicas report the reduced footprint)",
    labelnames=("dtype",))


# ---------------------------------------------------------------- int8 KV
#: relative round-trip error the int8-KV parity probe tolerates
#: (PTPU_INT8_KV_TOL overrides). Row-absmax int8 holds ~1/254 of the
#: row range per element; 2% is an order of magnitude of headroom, so a
#: probe failure means the quantizer itself drifted, not noise.
KV_QUANT_TOL = 0.02


def _int8_kv_probe_ok():
    """Numeric parity probe over the REAL paged-KV quantization path
    (memory.quantize_rows_int8 / dequantize_rows_int8) on a skewed
    tensor with outlier rows — the int8-LM-head gate discipline: the
    probe exercises the same code every int8 cache write runs, so a
    monkeypatched/broken quantizer fails the gate instead of serving
    drifted KV."""
    import jax.numpy as jnp

    from ..memory import dequantize_rows_int8, quantize_rows_int8

    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 64)).astype(np.float32)
    x[0] *= 1e3        # large-magnitude row
    x[1] *= 1e-3       # tiny row (scale epsilon path)
    x[2, 5] = 400.0    # in-row outlier (worst case for absmax grids)
    q, s = quantize_rows_int8(jnp.asarray(x))
    rt = np.asarray(dequantize_rows_int8(q, s))
    absmax = np.maximum(np.abs(x).max(-1, keepdims=True), 1e-12)
    err = float(np.max(np.abs(rt - x) / absmax))
    tol = float(os.environ.get("PTPU_INT8_KV_TOL", KV_QUANT_TOL))
    return err <= tol


def int8_kv_enabled(requested=False):
    """Resolve the int8 paged-KV mode (docs/SERVING.md numerics
    contract). ``PTPU_INT8_KV`` forces: ``0`` is the exact escape hatch
    (bf16/f32 pages, bitwise the pre-int8 engine), ``1`` forces int8 on.
    Unset: the mode engages only when the constructor ``requested`` it
    AND the parity probe passes — a drifting quantizer defaults the
    engine OFF (loudly) instead of serving approximate KV."""
    env = os.environ.get("PTPU_INT8_KV", "").strip().lower()
    if env != "":
        return env not in ("0", "off", "false")
    if not requested:
        return False
    if _int8_kv_probe_ok():
        return True
    warnings.warn(
        "int8_kv requested but the paged-KV quantization parity probe "
        "FAILED its round-trip tolerance — serving with exact "
        f"(non-quantized) KV instead (tol {KV_QUANT_TOL}, "
        "PTPU_INT8_KV=1 forces; docs/SERVING.md)")
    return False


def _int8_paged_kernel_mode():
    """Resolve ``PTPU_PAGED_INT8_KERNEL`` — HOW an already-engaged int8
    paged cache is read (rides ON TOP of the ``int8_kv_enabled`` parity
    gate). Returns one of:

    - ``"kernel"``: the Pallas int8-page kernel
      (``ops/pallas/decode_attention.paged_attention_int8``);
    - ``"interpret"``: the same kernel forced through the Pallas
      interpreter (the CPU parity tests drive the real kernel code);
    - ``"off"``: the HBM gather+dequant reference path.

    Unset/``auto`` resolves to ``kernel`` on real TPU devices and
    ``off`` elsewhere (off-TPU the kernel would silently run in the
    interpreter — orders of magnitude slower). Unknown values are a
    hard error: a mistyped knob must not masquerade as a measured
    configuration (the ``_block_for`` discipline)."""
    env = os.environ.get("PTPU_PAGED_INT8_KERNEL", "").strip().lower()
    if env in ("0", "off", "false"):
        return "off"
    if env == "interpret":
        return "interpret"
    if env in ("", "auto"):
        from ..ops.pallas import on_tpu_device

        return "kernel" if on_tpu_device() else "off"
    raise ValueError(
        f"PTPU_PAGED_INT8_KERNEL={env!r}: expected auto|interpret|0 "
        "(docs/SERVING.md)")


def _int8_paged_kernel_active():
    return _int8_paged_kernel_mode() != "off"


# ------------------------------------------------------- KV cache helpers
# A cache is ONE stacked array [L, Hkv, num_pages+1, page, D] (exact
# mode) or a (codes int8 [L, Hkv, num_pages+1, page, D],
# scales f32 [L, Hkv, num_pages+1, page, 1]) pair (int8 mode) — the
# fp32 per-row scales ride NEXT TO the page payload, addressed by the
# same page table. The helpers below are tuple-aware so every cache
# consumer (decode, chunked prefill, swap, handoff) is written once.

def _kv_map(fn, c):
    return tuple(fn(x) for x in c) if isinstance(c, tuple) else fn(c)


def _kv_map2(fn, a, b):
    if isinstance(a, tuple):
        return tuple(fn(x, y) for x, y in zip(a, b))
    return fn(a, b)


def _kv_index(c, li):
    """Per-layer view of a stacked cache (basic int index, axis 0)."""
    return _kv_map(lambda x: x[li], c)


def _kv_stack(per_layer):
    """Inverse of _kv_index over a list of per-layer caches."""
    import jax.numpy as jnp

    if isinstance(per_layer[0], tuple):
        return tuple(jnp.stack([p[i] for p in per_layer])
                     for i in range(len(per_layer[0])))
    return jnp.stack(per_layer)


def _kv_write(cache_l, pages, offs, vals):
    """Scatter token rows into a PER-LAYER cache: ``pages``/``offs``
    index arrays (any matching shape S*), ``vals`` [Hkv, *S, D] at the
    compute dtype. int8 caches quantize each row (one fp32 scale per
    head_dim row — the block the page table addresses) at the write."""
    if isinstance(cache_l, tuple):
        from ..memory import quantize_rows_int8

        q, s = cache_l
        qv, sv = quantize_rows_int8(vals)
        return (q.at[:, pages, offs, :].set(qv),
                s.at[:, pages, offs, :].set(sv))
    return cache_l.at[:, pages, offs, :].set(vals.astype(cache_l.dtype))


def _kv_write_layer(cache, li, pages, offs, vals):
    """`_kv_write` against ONE layer of a stacked cache (the eager
    group-prefill path, which walks layers python-side). NOTE the
    scalar ``li`` is itself an advanced index: with the Hkv slice
    separating it from ``pages``/``offs``, the broadcast advanced dims
    move to the FRONT, so the update payload is [N, Hkv, D]."""
    import jax.numpy as jnp

    vals = jnp.swapaxes(vals, 0, 1)                   # [N, Hkv, D]
    if isinstance(cache, tuple):
        from ..memory import quantize_rows_int8

        q, s = cache
        qv, sv = quantize_rows_int8(vals)
        return (q.at[li, :, pages, offs, :].set(qv),
                s.at[li, :, pages, offs, :].set(sv))
    return cache.at[li, :, pages, offs, :].set(vals.astype(cache.dtype))


def _kv_gather_rows(cache_l, idx, dtype):
    """Gather pages by id from a PER-LAYER cache -> values at the
    engine's logical ``dtype``. int8 caches dequantize (codes * scales)
    on the way out; exact caches return their storage as-is."""
    import jax.numpy as jnp

    if isinstance(cache_l, tuple):
        q, s = cache_l
        return (q[:, idx].astype(jnp.float32) * s[:, idx]).astype(dtype)
    return cache_l[:, idx]


def _kv_nbytes(c):
    leaves = c if isinstance(c, tuple) else (c,)
    return sum(int(np.asarray(x).nbytes if not hasattr(x, "nbytes")
                   else x.nbytes) for x in leaves)


_DECODE_WEIGHT_NAMES = ("ln1", "wq", "wk", "wv", "wo", "ln2", "wg",
                        "wu", "wd")
#: the 7 projection slabs eligible for int8-resident packing (norms stay
#: exact: they are cheap, and their dynamic range is the worst int8 fit)
_QUANT_WEIGHT_NAMES = ("wq", "wk", "wv", "wo", "wg", "wu", "wd")


def _wmat(x, w):
    """``x @ W`` for one packed decode weight: exact slabs multiply
    directly; int8-resident ``(codes, scales)`` pairs take the
    dequant-free int8 x int8 -> int32 GEMM (quant.int8_weight_matmul) —
    the weights are never expanded back to wide dtype."""
    if isinstance(w, tuple):
        from ..quant import int8_weight_matmul

        return int8_weight_matmul(x, *w)
    return x @ w


def _layer_slice(w, li):
    """Per-layer view of one stacked weight entry (tuple-aware: an
    int8-packed entry slices codes and scales together)."""
    if isinstance(w, tuple):
        return (w[0][li], w[1][li])
    return w[li]


def _weight_nbytes(weights):
    """Resident bytes of the packed decode tree, keyed by storage dtype —
    the ``serving_weight_bytes{dtype}`` footprint. int8-packed layers
    split between their int8 codes and f32 scale rows."""
    out = {}

    def add(a):
        if a is None:
            return
        if isinstance(a, tuple):
            for x in a:
                add(x)
            return
        key = str(a.dtype)
        out[key] = out.get(key, 0) + int(a.nbytes)

    for w in weights["layers"]:
        add(w)
    for n in ("embed", "fnorm", "head"):
        add(weights[n])
    return out


def _run_layer_stack(scan_layers, layers, x, layer_fn, kc, vc):
    """THE scan-or-unrolled walker over a [L, ...]-stacked weight tuple
    plus cache slabs: ``layer_fn(lp, x, kc_l, vc_l) -> (x, kc_l, vc_l)``.
    Shared by the engine's decode/prefill programs AND the spec-decode
    DraftRunner, so the scan carry/ys shape discipline cannot drift
    between target and draft. Scanned: compile flat in depth (the
    replica cold-start win); unrolled (``PTPU_SCAN_LAYERS=0``): bitwise
    identical, compile linear in depth."""
    import jax

    if scan_layers:
        def step(carry, per):
            lp, kc_l, vc_l = per
            x2, kl, vl = layer_fn(lp, carry, kc_l, vc_l)
            return x2, (kl, vl)

        x, (kc, vc) = jax.lax.scan(step, x, (layers, kc, vc))
        return x, kc, vc
    kls, vls = [], []
    for li in range(layers[0].shape[0]):
        x, kl, vl = layer_fn(tuple(_layer_slice(w, li) for w in layers), x,
                             _kv_index(kc, li), _kv_index(vc, li))
        kls.append(kl)
        vls.append(vl)
    return x, _kv_stack(kls), _kv_stack(vls)


def _pack_weights_stacked(model):
    """Decode weight tree: {"layers": 9x [L, ...] stacked arrays,
    "embed", "fnorm", "head"} — shared by the engine and the spec-decode
    DraftRunner so target and draft numerics come off one packer."""
    import jax.numpy as jnp

    core = model.model if hasattr(model, "model") else model
    head = getattr(model, "lm_head", None)
    L = model.config.num_layers
    dec = getattr(model, "decoder", None)
    if dec is not None and all(
            getattr(getattr(dec, n, None), "_data", None) is not None
            and getattr(dec, n)._data.shape[0] == L
            for n in _DECODE_WEIGHT_NAMES):
        # natively-stacked family (GPTForCausalLMPipe): reference, don't
        # copy — a live-engine reload is free of the sliced-copy peak
        layers = tuple(getattr(dec, n)._data for n in _DECODE_WEIGHT_NAMES)
    else:
        params = model._decode_params()
        layers = tuple(
            jnp.stack([params[li][n]._data for li in range(L)])
            for n in _DECODE_WEIGHT_NAMES)
    return {
        "layers": layers,
        "embed": core.embed_tokens.weight._data,
        "fnorm": core.final_norm.weight._data,
        "head": head.weight._data if head is not None else None,
    }


class PagePool:
    """Free-list page allocator (the block manager)."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = deque(range(num_pages))

    def alloc(self, n: int):
        if n > len(self._free):
            raise MemoryError(
                f"PagePool: need {n} pages, {len(self._free)} free")
        return [self._free.popleft() for _ in range(n)]

    def free(self, pages):
        self._free.extend(pages)

    @property
    def available(self):
        return len(self._free)


class _Request:
    __slots__ = ("rid", "prompt", "generated", "length", "pages",
                 "temperature", "top_k", "top_p", "on_token",
                 "prefill_pos", "seq_tokens", "admit_seq", "swapped",
                 "submit_t", "first_token_t", "deadline")

    def __init__(self, rid, prompt, temperature=0.0, top_k=0, top_p=1.0,
                 on_token=None, deadline=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.generated = []
        self.length = 0          # tokens currently in the kv pages
        self.pages = []
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.on_token = on_token
        self.prefill_pos = 0     # tokens already written to kv (chunked)
        # the tokens prefill must (re)build KV for: the prompt initially;
        # after a preemption, prompt + generated-so-far (the resume prefix)
        self.seq_tokens = self.prompt
        self.admit_seq = -1      # admission order (preemption victims =
                                 # youngest first, vLLM recompute policy)
        self.swapped = None      # host-side KV snapshot (swap policy)
        self.submit_t = time.perf_counter()   # latency telemetry anchors
        self.first_token_t = None
        self.deadline = deadline  # absolute perf_counter() cancel point


def _sample_rows(jax, jnp, logits, temps, top_ks, top_ps, key):
    """Per-row temperature / top-k / top-p sampling; temp<=0 rows take
    argmax. Runs inside the jitted decode step."""
    f32 = logits.astype(jnp.float32)
    greedy = jnp.argmax(f32, -1).astype(jnp.int32)
    # temperature scales BEFORE the filters (HF/vLLM order): the nucleus is
    # computed on the distribution actually sampled from, so high
    # temperature widens it and low temperature narrows it
    scaled = f32 / jnp.maximum(temps[:, None], 1e-6)
    V = scaled.shape[-1]
    srt = jnp.flip(jnp.sort(scaled, -1), -1)                  # desc [B, V]
    k_eff = jnp.where(top_ks > 0, top_ks, V)
    kth = jnp.take_along_axis(
        srt, jnp.clip(k_eff - 1, 0, V - 1)[:, None], 1)       # [B, 1]
    topk_sorted = jnp.where(srt < kth, -jnp.inf, srt)
    probs_sorted = jax.nn.softmax(topk_sorted, -1)
    csum = jnp.cumsum(probs_sorted, -1)
    # nucleus: keep the smallest prefix with cumulative mass >= top_p
    # (the first token is always kept: csum - p_i < p holds at i=0)
    keep = (csum - probs_sorted) < top_ps[:, None]
    thr = jnp.min(jnp.where(keep, topk_sorted, jnp.inf), -1, keepdims=True)
    # a logit survives only if it passes BOTH filters (max of thresholds);
    # keep[:, 0] is always True so thr is finite
    masked = jnp.where(scaled < jnp.maximum(kth, thr), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, masked, -1).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


class ContinuousBatchingEngine:
    def __init__(self, model, max_slots=4, page_size=64, num_pages=None,
                 max_seq_len=None, max_new_tokens=32, eos_token_id=None,
                 seed=0, prefill_chunk=None, preempt_policy="recompute",
                 enable_prefix_cache=False, int8_kv=False,
                 int8_weights=False, draft_model=None, spec_tokens=4,
                 prefill_only=False, rid_base=0):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        cfg = model.config
        self.cfg = cfg
        self.page = page_size
        self.max_seq = max_seq_len or cfg.max_seq_len
        self.pages_per_seq = (self.max_seq + page_size - 1) // page_size
        self.max_slots = max_slots
        self.max_new_tokens = max_new_tokens
        self.eos = eos_token_id
        num_pages = num_pages or (max_slots * self.pages_per_seq + 2)
        self.pool = PagePool(num_pages)
        # one extra non-allocable scratch page: the BATCHED chunked
        # prefill routes padded rows' cache writes there
        self._trash_page = num_pages

        hd = cfg.hidden_size // cfg.num_heads
        self.hd, self.hkv = hd, cfg.num_kv_heads

        # int8 resident weights (docs/QUANT.md): the 7 projection slabs
        # pack as per-output-column int8 codes + f32 scales and every
        # decode/prefill GEMM runs int8 x int8 -> int32 without ever
        # dequantizing the weights (~4x less weight HBM per replica vs
        # f32). Engages only behind the round-trip probe;
        # PTPU_INT8_WEIGHTS=0 is the exact escape hatch. Resolved BEFORE
        # the pack below, which reads the flag.
        from ..quant import int8_weights_enabled

        self.int8_weights = int8_weights_enabled(int8_weights)

        self._model = model
        self._weights = self._pack_weights(model)
        self._key = jax.random.PRNGKey(seed)

        # scan-over-layers decode (docs/SERVING.md cold start): the ONE
        # models.gpt resolver decides — the decode/prefill programs
        # compile as a lax.scan over the [L, ...]-stacked weights+caches
        # (depth-flat build time, the PR 7 discipline) unless
        # PTPU_SCAN_LAYERS=0 keeps the python-unrolled loop, the bitwise
        # escape hatch (proven: greedy streams identical either way).
        from ..models.gpt import scan_layers_enabled

        self._scan_layers = scan_layers_enabled()

        # int8 paged KV (docs/SERVING.md): pages stored as int8 codes +
        # fp32 per-row scales riding in the page table, ~half the exact
        # mode's KV HBM. Engages only behind the parity probe;
        # PTPU_INT8_KV=0 is the exact escape hatch.
        self.int8_kv = int8_kv_enabled(int8_kv)

        # paged caches, stacked KERNEL layout [L, Hkv, num_pages, page, D]
        # (per-layer slices are exactly what paged_attention consumes —
        # no per-step transposes; the leading L axis is what the layer
        # scan iterates)
        dt = self._weights["embed"].dtype
        self._kv_dtype = dt
        cache_shape = (cfg.num_layers, self.hkv, num_pages + 1,
                       page_size, hd)
        if self.int8_kv:
            self.kc = (jnp.zeros(cache_shape, jnp.int8),
                       jnp.zeros(cache_shape[:-1] + (1,), jnp.float32))
            self.vc = (jnp.zeros(cache_shape, jnp.int8),
                       jnp.zeros(cache_shape[:-1] + (1,), jnp.float32))
        else:
            self.kc = jnp.zeros(cache_shape, dt)
            self.vc = jnp.zeros(cache_shape, dt)

        # prefill_only: this engine is the PREFILL half of a
        # disaggregated pair (fleet.disagg) — step() admits and prefills
        # but never runs a decode tick; completed-prefill requests wait
        # in their slots for extract()
        self.prefill_only = bool(prefill_only)

        self._slots: list[_Request | None] = [None] * max_slots
        self._waiting: deque[_Request] = deque()
        # rid_base: fleet routers give each replica a disjoint id space
        # so request trace trees (docs/TELEMETRY.md Tracing) never
        # collide across replicas
        self._next_rid = int(rid_base)
        # weights are argument 0 — NOT closed-over jit constants — so a
        # reload on a live engine feeds the already-compiled step
        self._decode_jit = jax.jit(self._decode_step, donate_argnums=(4, 5),
                                   static_argnums=(10,))
        self.prefill_batches = 0      # observability: admission group count
        self.preemptions = 0          # pages reclaimed from the youngest
        self._admit_counter = 0
        # preempt_policy: what happens to a victim's KV state.
        #   "recompute" — drop pages, fold generated tokens into the resume
        #     prompt, rebuild KV by re-prefilling on re-admission (vLLM
        #     recompute; the r5 default).
        #   "swap" — copy the victim's pages to HOST memory, free the
        #     device pages, and scatter the snapshot back on re-admission
        #     (vLLM swap / the reference block-table cache-offload shape):
        #     no prefill FLOPs are re-paid, at the price of two
        #     host<->device transfers of the live KV. Greedy outputs are
        #     bitwise identical either way (bf16 round-trips exactly
        #     through the host copy); tests assert both.
        if preempt_policy not in ("recompute", "swap"):
            raise ValueError(
                f"preempt_policy must be 'recompute' or 'swap', "
                f"got {preempt_policy!r}")
        self.preempt_policy = preempt_policy
        # enable_prefix_cache=True: automatic prefix caching (vLLM APC /
        # SGLang radix-cache shape). KV pages are content-addressed by
        # their token-prefix chain; a new request whose prompt shares a
        # full-page-aligned prefix with any previously computed sequence
        # REUSES those pages (read-only, refcounted) and prefills only
        # the tail. Released pages are retained "free-but-cached": they
        # are reclaimed lazily (cache eviction, FIFO over ref-0 entries)
        # only when the pool runs short. Matching is capped one token
        # below the prompt end so a fully-cached prompt still computes
        # its first-token logits. Sound because KV at position i is a
        # pure function of tokens[0..i]; writes only ever target
        # positions past the matched prefix (page-granular match), so
        # shared pages are never written. Requires chunked prefill (the
        # tail prefill starts mid-prompt) and the recompute preemption
        # policy (swap restore scatters into pages, which must stay
        # exclusive).
        if enable_prefix_cache:
            if prefill_chunk is None:
                raise ValueError("enable_prefix_cache requires chunked "
                                 "prefill (prefill_chunk=...)")
            if preempt_policy != "recompute":
                raise ValueError("enable_prefix_cache composes only with "
                                 "preempt_policy='recompute'")
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self._prefix_cache = {}       # token-chain digest -> page id
        self._cached_pages = set()    # page ids held by the cache (O(1)
                                      # membership on the release path)
        self._page_ref = {}           # page id -> live-request refcount
        self.prefix_cache_hits = 0    # pages reused instead of prefilled
        self.prefix_cache_evictions = 0
        self.prefix_tokens_skipped = 0
        self.prefix_pages_exported = 0  # shipped to a drain destination
        self.prefix_pages_imported = 0  # warmed from a draining peer
        self._cache_admit_floor = 0   # requests admitted before a
                                      # reload_weights hold stale KV and
                                      # must not register pages
        self.swaps_out = 0            # victims snapshotted to host
        self.swaps_in = 0             # snapshots restored to device
        # fixed-shape ([pages_per_seq] page vector, trash-padded) so each
        # compiles ONCE; swap-in donates the caches (no double buffering)
        self._swap_out_jit = jax.jit(self._swap_gather)
        self._swap_in_jit = jax.jit(self._swap_scatter,
                                    donate_argnums=(0, 1))
        # chunked prefill (vLLM-style): admit immediately, write the
        # prompt's KV `prefill_chunk` tokens per TICK so long prompts
        # don't stall the decode latency of running requests
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        self.prefills_completed = 0   # per-request (both prefill modes)
        # batched chunked prefill: ONE jitted fixed-shape pass advances
        # every prefilling slot by up to prefill_chunk tokens per tick
        # (VERDICT r3 item 7 — the eager per-request chunk loop paid the
        # ~2.5ms/dispatch host cost per layer per request)
        self._prefill_jit = jax.jit(self._prefill_chunk_step,
                                    donate_argnums=(7, 8))
        self.prefill_chunk_steps = 0  # observability: jitted pass count
        # -- request deadlines / cancellation (docs/SERVING.md) --
        self.cancelled = {}           # rid -> reason, drained by callers
        self.cancellations = 0
        # -- draft-model speculative decoding (fleet.spec_decode) --
        # draft K tokens per tick, verify in ONE target forward,
        # accept-prefix; bitwise-greedy-exact vs plain decode (the
        # verify pass runs the SAME per-position paged kernel)
        self.spec_tokens = int(spec_tokens)
        self._draft = None
        if draft_model is not None:
            if self.spec_tokens < 1:
                raise ValueError("spec_tokens must be >= 1 with a "
                                 f"draft model, got {spec_tokens}")
            from .fleet.spec_decode import DraftRunner

            self._draft = DraftRunner(self, draft_model)
            self._verify_jit = jax.jit(self._spec_verify,
                                       donate_argnums=(4, 5))
        self.spec_ticks = 0
        self.spec_draft_tokens = 0
        self.spec_accepted_tokens = 0
        # pages each decoding slot must hold BEFORE a tick: a spec tick
        # writes K drafts + the carry token past `length`, a plain tick
        # writes one
        self._lookahead = (self.spec_tokens + 1 if self._draft is not None
                           else 1)
        self.build_seconds = None     # set by warmup() (cold-start gate)
        # -- brownout degradation knobs (fleet.overload, docs/SERVING.md
        # "Overload & degradation") — reversible service caps the fleet
        # brownout ladder sets under sustained pressure and restores on
        # recovery. All-default = full service, behavior unchanged.
        self.max_new_cap = None       # L1: cap on tokens to generate
        self.spec_paused = False      # L2: skip speculative ticks
                                      #     (greedy-output-invariant)
        self.prefill_chunk_cap = None  # L3: per-tick prefill token
                                       #     budget (output-invariant)

    def _pack_weights(self, model):
        # the decode contract: `_decode_params()` (per-layer weight dicts,
        # llama.py:66 / gpt.py GPTForCausalLMPipe) + embed/final_norm on
        # the model or its `.model` core + optional untied `lm_head`.
        # "layers" is a tuple of 9 LEADING-AXIS-STACKED arrays [L, ...]
        # in _block order — the tree the layer scan iterates. Stacked
        # models (GPTForCausalLMPipe / StackedDecoder) pack ZERO-COPY
        # (the decoder's [L, ...] arrays are referenced as-is); per-layer
        # models stack their slices (one transient per-layer copy during
        # the stack, then only the stacked copy is retained).
        #
        # int8_weights: the 7 projection slabs are re-packed as
        # (codes int8 [L, h, n], scales f32 [L, 1, n]) tuples — embed,
        # norms and head stay exact (embed also fixes the engine's KV
        # dtype). The stacked zero-copy reference is given up for ~4x
        # less resident bytes; per-dtype footprint lands in
        # self.weight_bytes and serving_weight_bytes{dtype}.
        w = _pack_weights_stacked(model)
        if self.int8_weights:
            from ..quant import quantize_weight_cols_int8

            w["layers"] = tuple(
                quantize_weight_cols_int8(arr)
                if name in _QUANT_WEIGHT_NAMES else arr
                for name, arr in zip(_DECODE_WEIGHT_NAMES, w["layers"]))
        self.weight_bytes = _weight_nbytes(w)
        for dt, nb in self.weight_bytes.items():
            _WEIGHT_BYTES.set(float(nb), labels=(dt,))
        return w

    @staticmethod
    def _layer_tuple(weights, li):
        """Per-layer 9-tuple view of the stacked weight tree
        (int8-packed entries slice to per-layer (codes, scales))."""
        return tuple(_layer_slice(w, li) for w in weights["layers"])

    def reload_weights(self, model=None):
        """Re-read weights from the model (e.g. after an in-place update);
        the compiled decode step picks them up on the next tick. Any
        cached prefix KV is invalidated (it was computed under the old
        weights): ref-0 cached pages are freed now, in-use ones when
        their readers release them; requests already admitted are barred
        from registering their (stale) pages.

        The old packed weights are released BEFORE repacking: with the
        lazy per-layer slicing of the stacked models (gpt.py
        _decode_params), a live-engine reload peaks at stacked + new
        slices + one in-flight layer instead of holding old and new
        sliced copies side by side (ADVICE r5). The release is what buys
        the headroom, so a mid-pack failure cannot fall back to the old
        weights — it raises loudly and the engine stays weightless until
        a reload succeeds (serving on half-reloaded state would be
        worse)."""
        self._weights = None
        try:
            self._weights = self._pack_weights(model or self._model)
        except Exception as e:
            raise RuntimeError(
                "reload_weights failed mid-pack; the old weights were "
                "already released (HBM headroom), so the engine has no "
                "weights until a reload_weights() succeeds") from e
        if self.enable_prefix_cache:
            for key in list(self._prefix_cache):
                pg = self._prefix_cache.pop(key)
                self._cached_pages.discard(pg)
                if self._page_ref.get(pg, 0) == 0:
                    self._page_ref.pop(pg, None)
                    self.pool.free([pg])
            self._cache_admit_floor = self._admit_counter

    # -- model math ---------------------------------------------------------
    @staticmethod
    def _rope(x, pos):
        """Shared framework rope (models/gpt.py) — serving stays
        bit-identical to training/generate."""
        from ..models.gpt import _rope_at_positions

        return _rope_at_positions(x, pos)

    def _layer_forward(self, li, lp, x, pos0, attend):
        """One decoder layer of the EAGER prefill paths: projections +
        rope + `attend(li, q, k, v)` (which owns cache writes and the
        attention math) + MLP. Shared by group and chunked prefill so
        their numerics can never diverge."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure

        ln1, wq, wk, wv, wo, ln2, wg, wu, wd = lp
        B, S = x.shape[:2]
        h = _rms_pure(x, ln1)
        q = _wmat(h, wq).reshape(B, S, self.cfg.num_heads, self.hd)
        k = _wmat(h, wk).reshape(B, S, self.hkv, self.hd)
        v = _wmat(h, wv).reshape(B, S, self.hkv, self.hd)
        q, k = self._rope(q, pos0), self._rope(k, pos0)
        o = attend(li, q, k, v)                       # [B, S, Hq, D]
        x = x + _wmat(o.reshape(B, S, -1), wo)
        h2 = _rms_pure(x, ln2)
        return x + _wmat(jax.nn.silu(_wmat(h2, wg)) * _wmat(h2, wu), wd)

    def _head_tokens(self, last, reqs):
        """final-norm'd last hidden rows [B, H] -> first token per req."""
        jax, jnp = self._jax, self._jnp
        w = self._weights
        lg = (last @ w["head"] if w["head"] is not None
              else last @ w["embed"].T)
        self._key, sub = jax.random.split(self._key)
        if any(r.temperature > 0.0 for r in reqs):
            toks = _sample_rows(
                jax, jnp, lg,
                jnp.asarray([r.temperature for r in reqs], jnp.float32),
                jnp.asarray([r.top_k for r in reqs], jnp.int32),
                jnp.asarray([r.top_p for r in reqs], jnp.float32), sub)
        else:
            toks = jnp.argmax(lg.astype(jnp.float32), -1)
        return [int(t) for t in np.asarray(toks)]

    def _prefill_group(self, reqs):
        """Run ALL newly admitted prompts as ONE padded batch: write each
        prompt's KV into its pages, return the first generated token per
        request.

        One pass over the weights per admission group (the reference's
        serving stack batches prefill the same way before handing slots to
        the decode loop). Runs eagerly: page-cache writes copy the pool
        once per layer per GROUP; jitting would retrace per padded length
        (bucket lengths first if admission cost ever dominates)."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure

        self.prefill_batches += 1
        self.prefills_completed += len(reqs)
        w = self._weights
        B = len(reqs)
        lens = np.asarray([len(r.seq_tokens) for r in reqs])
        S = int(lens.max())
        ids_np = np.zeros((B, S), np.int32)
        for i, r in enumerate(reqs):
            ids_np[i, : lens[i]] = r.seq_tokens
        ids = jnp.asarray(ids_np)
        x = w["embed"][ids]                                  # [B, S, H]
        pos0 = jnp.zeros((B,), jnp.int32)
        scale = 1.0 / math.sqrt(self.hd)
        rep = self.cfg.num_heads // self.hkv
        mask = jnp.tril(jnp.ones((S, S), bool))

        # flattened valid (row, pos) pairs -> page/offset scatter targets
        rows = np.concatenate([np.full(l, i) for i, l in enumerate(lens)])
        poss = np.concatenate([np.arange(l) for l in lens])
        tok_pages = np.concatenate(
            [np.asarray(r.pages, np.int64)[np.arange(l) // self.page]
             for r, l in zip(reqs, lens)])
        offs = jnp.asarray(poss % self.page)
        rows_j, poss_j = jnp.asarray(rows), jnp.asarray(poss)

        def attend(li, q, k, v):
            if self.int8_kv:
                # round-trip k/v through the page quantizer BEFORE both
                # the attention math and the cache write: group prefill,
                # chunked prefill, and decode all read the SAME
                # quantized KV (re-quantizing a round-tripped row is
                # exact — the absmax element always maps to code 127,
                # so the recomputed scale is identical)
                from ..memory import (dequantize_rows_int8,
                                      quantize_rows_int8)

                k = dequantize_rows_int8(*quantize_rows_int8(k), k.dtype)
                v = dequantize_rows_int8(*quantize_rows_int8(v), v.dtype)
            ck = jnp.repeat(k, rep, 2) if rep > 1 else k
            cv = jnp.repeat(v, rep, 2) if rep > 1 else v
            logits = jnp.einsum("bthd,bshd->bhts",
                                (q * scale).astype(jnp.float32),
                                ck.astype(jnp.float32))
            logits = jnp.where(mask[None, None], logits, -1e30)
            probs = jax.nn.softmax(logits, -1)
            o = jnp.einsum("bhts,bshd->bthd", probs,
                           cv.astype(jnp.float32)).astype(q.dtype)
            # scatter the group's valid k/v into the owned pages; ADJACENT
            # advanced indices stay in place -> [Hkv, N, D]
            kvals = jnp.swapaxes(k[rows_j, poss_j], 0, 1)
            vvals = jnp.swapaxes(v[rows_j, poss_j], 0, 1)
            self.kc = _kv_write_layer(self.kc, li, tok_pages, offs, kvals)
            self.vc = _kv_write_layer(self.vc, li, tok_pages, offs, vvals)
            return o

        for li in range(self.cfg.num_layers):
            x = self._layer_forward(li, self._layer_tuple(w, li), x, pos0,
                                    attend)
        x = _rms_pure(x, w["fnorm"])
        last = x[jnp.arange(B), jnp.asarray(lens - 1)]       # [B, H]
        toks = self._head_tokens(last, reqs)
        for i, r in enumerate(reqs):
            r.length = int(lens[i])
            # group prefill wrote the whole prompt: keep prefill_pos in
            # lockstep so a later swap snapshot is classified decode-phase
            # (its restore must reserve the growth page, not the prompt)
            r.prefill_pos = int(lens[i])
        if self._draft is not None:
            # the draft's KV for these prompts (same pages/page table)
            self._draft.prefill(reqs, [r.seq_tokens for r in reqs])
        return toks

    def _run_layers(self, weights, x, layer_fn, kc, vc):
        """Run ``layer_fn`` over every decoder layer through the shared
        :func:`_run_layer_stack` walker (scan-over-layers per the
        models.gpt resolver; ``PTPU_SCAN_LAYERS=0`` unrolls bitwise —
        docs/SERVING.md)."""
        return _run_layer_stack(self._scan_layers, weights["layers"], x,
                                layer_fn, kc, vc)

    def _paged_attend(self, q, kc_l, vc_l, tables, lens):
        """Single-position paged attention over a PER-LAYER cache:
        q [B, Hq, D] -> [B, Hq, D]. Exact caches take the Pallas paged
        kernel; int8 caches take the int8-page Pallas kernel
        (``paged_attention_int8``: (codes, scales) dequantized in VMEM
        per fetched page — the PR 12 named follow-up) when the device
        gate allows, else gather the owned pages, dequantize in HBM,
        and run the masked reference attention (docs/SERVING.md). Both
        int8 paths read the SAME codes*scales values; the int8 mode
        itself engages only behind the quantizer parity gate
        (``int8_kv_enabled``)."""
        jax, jnp = self._jax, self._jnp
        if not isinstance(kc_l, tuple):
            from ..ops.pallas.decode_attention import paged_attention

            return paged_attention(q, kc_l, vc_l, tables, lens)
        mode = _int8_paged_kernel_mode()
        if mode != "off":
            from ..ops.pallas.decode_attention import paged_attention_int8

            kc, ks = kc_l
            vc, vs = vc_l
            return paged_attention_int8(
                q, kc, ks, vc, vs, tables, lens,
                interpret=True if mode == "interpret" else None)
        b, hq, hd = q.shape
        dt = self._kv_dtype
        S = self.pages_per_seq * self.page
        ck = _kv_gather_rows(kc_l, tables, dt).reshape(self.hkv, b, S, hd)
        cv = _kv_gather_rows(vc_l, tables, dt).reshape(self.hkv, b, S, hd)
        rep = hq // self.hkv
        if rep > 1:
            ck = jnp.repeat(ck, rep, 0)
            cv = jnp.repeat(cv, rep, 0)
        scale = 1.0 / math.sqrt(hd)
        logits = jnp.einsum("bhd,hbsd->bhs",
                            (q * scale).astype(jnp.float32),
                            ck.astype(jnp.float32))
        mask = jnp.arange(S)[None, None, :] < lens[:, None, None]
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        o = jnp.einsum("bhs,hbsd->bhd", probs, cv.astype(jnp.float32))
        return o.astype(q.dtype)

    def _decode_layer(self, lp, x, lens, tables, page_ids, offs,
                      kc_l, vc_l):
        """One decoder layer of the batched decode tick (the scan
        body): write this token's KV row, paged-attend, MLP. Shares
        `_layer_forward` with the prefill paths so decode numerics can
        never drift from prefill's."""
        jnp = self._jnp
        new = {}

        def attend(li, q, k, v):
            kl = _kv_write(kc_l, page_ids, offs,
                           jnp.swapaxes(k[:, 0], 0, 1))
            vl = _kv_write(vc_l, page_ids, offs,
                           jnp.swapaxes(v[:, 0], 0, 1))
            new["k"], new["v"] = kl, vl
            o = self._paged_attend(q[:, 0], kl, vl, tables, lens + 1)
            return o[:, None]                         # [B, 1, Hq, D]

        x = self._layer_forward(0, lp, x, lens, attend)
        return x, new["k"], new["v"]

    def _decode_step(self, weights, tokens, lens, tables, kc, vc,
                     temps, top_ks, top_ps, key, do_sample=False):
        """ONE batched decode: tokens [B] (last emitted), lens [B] tokens
        already cached, tables [B, pages_per_seq]. Returns (next [B],
        new kc, new vc)."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure

        b = tokens.shape[0]
        x = weights["embed"][tokens][:, None]                # [B, 1, H]
        page_ids = tables[jnp.arange(b), lens // self.page]
        offs = lens % self.page

        def layer_fn(lp, x, kc_l, vc_l):
            return self._decode_layer(lp, x, lens, tables, page_ids,
                                      offs, kc_l, vc_l)

        x, kc, vc = self._run_layers(weights, x, layer_fn, kc, vc)
        x = _rms_pure(x, weights["fnorm"])[:, 0]
        lg = (x @ weights["head"] if weights["head"] is not None
              else x @ weights["embed"].T)
        if do_sample:
            nxt = _sample_rows(jax, jnp, lg, temps, top_ks, top_ps, key)
        else:
            # greedy-only tick: skip the full-vocab sort/cumsum entirely
            nxt = jnp.argmax(lg.astype(jnp.float32), -1).astype(jnp.int32)
        return nxt, kc, vc

    def _spec_verify(self, weights, toks, lens, tables, kc, vc):
        """Speculative-decoding verify: ONE target forward over the
        C = K+1 token window [carry, d1..dK] at positions
        lens..lens+K, returning the target's greedy token at EVERY
        position (t1..t_{K+1}) plus the updated caches.

        Bitwise-greedy-exact by construction (the acceptance contract,
        docs/SERVING.md): projections/norms/rope/MLP are row-local ops
        (batching over positions cannot change a row's value), and
        attention runs the SAME per-position `_paged_attend` with the
        same operands a plain decode tick at that position would see —
        position i reads lens+i+1 valid rows, the earlier window rows
        having just been written with the identical values sequential
        ticks would have written."""
        jnp = self._jnp
        from ..models.gpt import _rms_pure

        b, C = toks.shape
        x = weights["embed"][toks]                           # [B, C, H]
        pos = lens[:, None] + jnp.arange(C)[None, :]         # [B, C]
        page_idx = jnp.clip(pos // self.page, 0, self.pages_per_seq - 1)
        page_ids = jnp.take_along_axis(tables, page_idx, 1)
        offs = pos % self.page

        def layer_fn(lp, x, kc_l, vc_l):
            new = {}

            def attend(li, q, k, v):
                kl = _kv_write(kc_l, page_ids, offs,
                               jnp.transpose(k, (2, 0, 1, 3)))
                vl = _kv_write(vc_l, page_ids, offs,
                               jnp.transpose(v, (2, 0, 1, 3)))
                new["k"], new["v"] = kl, vl
                o = [self._paged_attend(q[:, i], kl, vl, tables,
                                        lens + i + 1) for i in range(C)]
                return jnp.stack(o, 1)                # [B, C, Hq, D]

            x = self._layer_forward(0, lp, x, lens, attend)
            return x, new["k"], new["v"]

        x, kc, vc = self._run_layers(weights, x, layer_fn, kc, vc)
        x = _rms_pure(x, weights["fnorm"])                   # [B, C, H]
        lg = (x @ weights["head"] if weights["head"] is not None
              else x @ weights["embed"].T)
        t = jnp.argmax(lg.astype(jnp.float32), -1).astype(jnp.int32)
        return t, kc, vc

    # -- engine surface -----------------------------------------------------
    def submit(self, prompt_ids, temperature=0.0, top_k=0, top_p=1.0,
               on_token=None, deadline_seconds=None, rid=None) -> int:
        """Queue a request. ``temperature=0`` decodes greedily; otherwise
        softmax sampling with optional top_k / top_p truncation.
        ``on_token(rid, token_id)`` streams each generated token.
        ``deadline_seconds`` cancels the request (queued OR running —
        pages freed, ``serving_cancellations_total{reason="deadline"}``)
        once that much wall time has passed since submit. ``rid`` lets a
        fleet router assign globally-unique ids (trace trees must not
        collide across replicas); the caller owns uniqueness."""
        if len(prompt_ids) == 0:
            raise ValueError("empty prompt: a request needs at least one "
                             "token to prefill")
        total = len(prompt_ids) + self.max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"request needs {total} tokens (prompt "
                f"{len(prompt_ids)} + max_new {self.max_new_tokens}) > "
                f"max_seq_len {self.max_seq}")
        if self._draft is not None and total + self.spec_tokens > self.max_seq:
            raise ValueError(
                f"speculative decoding writes up to {self.spec_tokens} "
                f"draft tokens of KV past the sequence end: request "
                f"needs {total} + {self.spec_tokens} spec headroom > "
                f"max_seq_len {self.max_seq}")
        # feasibility must cover the speculative lookahead too: the
        # grow-pages no-deadlock invariant ("a lone request always
        # fits") prices length + K + 1 tokens under a draft model
        spec_pad = self.spec_tokens if self._draft is not None else 0
        need = (total + spec_pad + self.page - 1) // self.page
        if need > self.pool.num_pages:
            raise ValueError(
                f"request needs {need} pages (incl. {spec_pad} tokens "
                f"of speculative headroom) > pool size "
                f"{self.pool.num_pages}")
        if rid is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            rid = int(rid)
            self._next_rid = max(self._next_rid, rid + 1)
        deadline = (time.perf_counter() + float(deadline_seconds)
                    if deadline_seconds is not None else None)
        self._waiting.append(_Request(
            rid, [int(t) for t in prompt_ids], temperature, top_k, top_p,
            on_token, deadline=deadline))
        # request span tree (docs/TELEMETRY.md Tracing): the async
        # "request" span covers submit → retire; "queue" covers
        # submit → admission (re-opened on preemption requeue)
        _trace.async_begin("request", rid,
                           {"prompt_tokens": len(prompt_ids)})
        _trace.async_begin("queue", rid)
        return rid

    # -- cancellation / deadlines ------------------------------------------
    def _cancel_req(self, req, reason, slot_idx=None):
        """Tear a request out of the engine: release pages (completed
        prefix pages still register into the prefix cache — their KV is
        valid), drop any host snapshot, close its trace spans, count
        it. The request lands in ``self.cancelled`` (rid -> reason) for
        callers that track outcomes."""
        if slot_idx is not None:
            self._slots[slot_idx] = None
            if req.first_token_t is None:
                _trace.async_end("prefill", req.rid, {"cancelled": reason})
        else:
            try:
                self._waiting.remove(req)
            except ValueError:
                pass
            _trace.async_end("queue", req.rid, {"cancelled": reason})
        if req.pages:
            self._release_pages(req, register=True)
        req.swapped = None
        self.cancelled[req.rid] = reason
        self.cancellations += 1
        _CANCELLATIONS.inc(labels=(reason,))
        _trace.async_end("request", req.rid, {"cancelled": reason})

    def cancel(self, rid, reason="user") -> bool:
        """Cancel a queued or running request by id. Returns True if it
        was found live; its pages return to the pool immediately."""
        for i, r in enumerate(self._slots):
            if r is not None and r.rid == rid:
                self._cancel_req(r, reason, slot_idx=i)
                return True
        for r in list(self._waiting):
            if r.rid == rid:
                self._cancel_req(r, reason)
                return True
        return False

    def _sweep_deadlines(self):
        """Cancel every request whose deadline has passed — queued AND
        running (a stuck client must not hold KV pages forever). A
        request that already FINISHED generating is not cancelled: its
        tokens were all delivered, so the retire loop (which runs right
        after this sweep) returns it as a completion."""
        now = time.perf_counter()
        for i, r in enumerate(list(self._slots)):
            if (r is not None and r.deadline is not None
                    and now >= r.deadline and not self._finished(r)):
                self._cancel_req(r, "deadline", slot_idx=i)
        for r in [r for r in self._waiting
                  if r.deadline is not None and now >= r.deadline]:
            self._cancel_req(r, "deadline")

    def _emit(self, req, tok):
        if req.first_token_t is None:
            req.first_token_t = time.perf_counter()
            _TTFT.observe(req.first_token_t - req.submit_t)
            _trace.async_end("prefill", req.rid)
            _trace.async_instant("first_token", req.rid)
        req.generated.append(tok)
        if req.on_token is not None:
            req.on_token(req.rid, tok)

    def _admit(self):
        group = []
        for i in range(self.max_slots):
            if self._slots[i] is not None or not self._waiting:
                continue
            req = self._waiting[0]
            if req.swapped is not None:
                # swap policy re-admission: restore the host KV snapshot
                # into freshly allocated pages — no prefill re-run. For a
                # decode-phase snapshot, also reserve THIS tick's growth
                # page up front: restoring with exactly n pages when
                # length is page-aligned would hand _grow_pages a starved
                # youngest request and swap it straight back out (a full
                # round-trip per tick with zero progress).
                snap = req.swapped
                n = snap["n"]
                # restore the FULL reservation, not just the snapshot
                # pages: a mid-prefill victim needs its whole prompt's
                # pages back for _prefill_tick's scatter targets, and a
                # decode-phase one needs this tick's growth page (without
                # it a page-aligned restoree would be the starved
                # youngest and swap straight back out)
                if snap["prefill_pos"] < len(req.seq_tokens):
                    need = max(n, (len(req.seq_tokens) + self.page - 1)
                               // self.page)
                else:
                    need = max(n, (snap["length"] + self.page) // self.page)
                if need > self.pool.available:
                    break  # head-of-line waits for pages
                self._waiting.popleft()
                req.pages = self.pool.alloc(need)
                # stage the n-page snapshot into fresh fixed-shape host
                # buffers (no zeroing — the padded rows scatter into the
                # scratch page, so their uninitialized contents are
                # irrelevant; the padded h2d volume is the price of the
                # compile-once scatter)
                kh = self._swap_stage(snap["k"], n)
                vh = self._swap_stage(snap["v"], n)
                self.kc, self.vc = self._swap_in_jit(
                    self.kc, self.vc,
                    self._padded_page_vec(req.pages[:n]),
                    _kv_map(self._jnp.asarray, kh),
                    _kv_map(self._jnp.asarray, vh))
                req.prefill_pos = snap["prefill_pos"]
                req.length = snap["length"]
                req.swapped = None
                self.swaps_in += 1
                req.admit_seq = self._admit_counter
                self._admit_counter += 1
                self._slots[i] = req
                if (self._draft is not None
                        and req.prefill_pos >= len(req.seq_tokens)):
                    # a decode-phase snapshot (a disagg handoff, or a
                    # swap-policy victim) carries no draft KV — rebuild
                    # it for the restored context so acceptance doesn't
                    # collapse (mid-prefill snapshots rebuild at the
                    # prefill-completion hook instead)
                    self._draft.prefill(
                        [req],
                        [(req.prompt + req.generated)[:req.length]])
                _ADMISSIONS.inc(labels=("swap_restore",))
                _trace.async_end("queue", req.rid)
                _trace.async_instant("admitted", req.rid,
                                     {"kind": "swap_restore"})
                if req.first_token_t is None:
                    # a mid-prefill swap victim resumes its prefill
                    # phase here — re-open the span so the restore-to-
                    # first-token segment stays in the TTFT anatomy
                    _trace.async_begin(
                        "prefill", req.rid,
                        {"kind": "swap_restore",
                         "resume_tokens": len(req.seq_tokens)})
                continue  # not part of any prefill group
            # reserve only what PREFILL writes (the resume prefix); decode
            # pages are allocated as the sequence grows, with preemption
            # under pressure — block-table growth semantics of the
            # reference's block_multi_head_attention serving path (vs the
            # r4 worst-case prompt+max_new reservation that capped batch
            # width at a fraction of pool capacity). With the prefix
            # cache on, pages holding an already-computed prefix of this
            # prompt are REUSED (read-only) and only the tail is
            # reserved + prefilled.
            shared = self._match_prefix(req.seq_tokens)
            need = ((len(req.seq_tokens) + self.page - 1) // self.page
                    - len(shared))
            if self.enable_prefix_cache:
                # PIN the matched pages before any eviction runs: a ref-0
                # free-but-cached prefix page is otherwise a legal FIFO
                # eviction victim, and reclaiming it here would alias one
                # physical page into prefix-read and tail-write roles
                for pg in shared:
                    self._page_ref[pg] = self._page_ref.get(pg, 0) + 1
                if not self._free_pages_for(need):
                    for pg in shared:  # unpin; retry next tick
                        self._page_ref[pg] -= 1
                    break  # head-of-line waits for pages
            elif need > self.pool.available:
                break  # head-of-line waits for pages
            self._waiting.popleft()
            if self.enable_prefix_cache:
                req.pages = shared + self._alloc_ref(need)
                if shared:
                    req.prefill_pos = max(req.prefill_pos,
                                          len(shared) * self.page)
                    self.prefix_cache_hits += len(shared)
                    self.prefix_tokens_skipped += len(shared) * self.page
            else:
                req.pages = self.pool.alloc(need)
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            self._slots[i] = req
            _ADMISSIONS.inc(labels=("prefill",))
            _trace.async_end("queue", req.rid)
            _trace.async_instant("admitted", req.rid, {"kind": "prefill"})
            if req.first_token_t is None:
                _trace.async_begin(
                    "prefill", req.rid,
                    {"resume_tokens": len(req.seq_tokens)})
            group.append(req)
        if not group:
            return
        if self.prefill_chunk is None:
            with _trace.span("prefill_group",
                             attrs={"requests": len(group)}, cat="serve"):
                first = self._prefill_group(group)
            for req, tok in zip(group, first):
                self._emit(req, tok)
        # chunked mode: KV fills incrementally in step()

    def _prefill_chunk_step(self, weights, ids, pos0, nvalid, tok_pages,
                            offs, hist, kc, vc):
        """ONE jitted fixed-shape chunk pass over ALL prefilling slots:
        ids [B, c] chunk tokens (zero-padded), pos0 [B] absolute start,
        nvalid [B] real tokens this chunk, tok_pages/offs [B, c] scatter
        targets (padded rows -> the scratch page), hist [B, pages_per_seq]
        page tables. Returns (final-normed last-valid hidden [B, H],
        new kc, new vc). Shapes are engine constants (max_slots x
        prefill_chunk x pages_per_seq), so this compiles ONCE."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure

        B, c = ids.shape
        S = self.pages_per_seq * self.page
        scale = 1.0 / math.sqrt(self.hd)
        rep = self.cfg.num_heads // self.hkv
        x = weights["embed"][ids]                            # [B, c, H]
        row_pos = pos0[:, None] + jnp.arange(c)[None, :]     # [B, c]
        cols = jnp.arange(S)
        # chunk rows attend to [cached prefix + own chunk] causally
        mask = cols[None, None, :] <= row_pos[:, :, None]    # [B, c, S]
        tp = tok_pages.reshape(-1)
        of = offs.reshape(-1)
        dt = self._kv_dtype

        def layer_fn(lp, x, kc_l, vc_l):
            new = {}

            def attend(li, q, k, v):
                # write the chunk's kv FIRST, then gather the prefix
                # back (one source of truth for the attention operands;
                # in int8 mode both the own-chunk and prefix reads come
                # back dequantized — identical to what decode will see)
                kv = jnp.swapaxes(
                    k.reshape(B * c, self.hkv, self.hd), 0, 1)
                vv = jnp.swapaxes(
                    v.reshape(B * c, self.hkv, self.hd), 0, 1)
                kl = _kv_write(kc_l, tp, of, kv)
                vl = _kv_write(vc_l, tp, of, vv)
                new["k"], new["v"] = kl, vl
                ck = _kv_gather_rows(kl, hist, dt).reshape(
                    self.hkv, B, S, self.hd)
                cv = _kv_gather_rows(vl, hist, dt).reshape(
                    self.hkv, B, S, self.hd)
                if rep > 1:
                    ck = jnp.repeat(ck, rep, 0)
                    cv = jnp.repeat(cv, rep, 0)
                logits = jnp.einsum("bchd,hbsd->bhcs",
                                    (q * scale).astype(jnp.float32),
                                    ck.astype(jnp.float32))
                logits = jnp.where(mask[:, None], logits, -1e30)
                probs = jax.nn.softmax(logits, -1)
                o = jnp.einsum("bhcs,hbsd->bchd", probs,
                               cv.astype(jnp.float32))
                return o.astype(q.dtype)                 # [B, c, Hq, D]

            x = self._layer_forward(0, lp, x, pos0, attend)
            return x, new["k"], new["v"]

        x, kc, vc = self._run_layers(weights, x, layer_fn, kc, vc)
        last_rows = jnp.clip(nvalid - 1, 0, c - 1)
        last = x[jnp.arange(B), last_rows]                   # [B, H]
        return _rms_pure(last, weights["fnorm"]), kc, vc

    def _prefill_tick(self):
        """Chunked prefill: advance EVERY prefilling slot by up to
        `prefill_chunk` prompt tokens in one jitted batched pass, so
        running requests keep decoding every tick while long prompts fill
        incrementally (the reference serving stack's chunked-prefill /
        mixed-batch scheduling over block_multihead_attention; r3's
        eager per-request loop paid the per-dispatch host cost per layer
        per request)."""
        jnp = self._jnp
        reqs = [r for r in self._slots
                if r is not None and r.prefill_pos < len(r.seq_tokens)]
        if not reqs:
            return
        B, c = self.max_slots, self.prefill_chunk
        # brownout L3: a live chunk cap shrinks the per-tick prefill
        # token budget WITHOUT recompiling — the jitted pass keeps its
        # [B, c] shapes and simply sees fewer valid tokens per row
        c_eff = (c if self.prefill_chunk_cap is None
                 else max(1, min(c, self.prefill_chunk_cap)))
        ids_np = np.zeros((B, c), np.int32)
        pos0 = np.zeros(B, np.int32)
        nvalid = np.zeros(B, np.int32)
        tok_pages = np.full((B, c), self._trash_page, np.int32)
        offs = np.zeros((B, c), np.int32)
        hist = np.zeros((B, self.pages_per_seq), np.int32)
        for i, r in enumerate(reqs):
            pos = r.prefill_pos
            n = min(c_eff, len(r.seq_tokens) - pos)
            ids_np[i, :n] = r.seq_tokens[pos:pos + n]
            pos0[i], nvalid[i] = pos, n
            pages = np.asarray(r.pages, np.int64)
            ap = np.arange(pos, pos + n)
            tok_pages[i, :n] = pages[ap // self.page]
            offs[i, :n] = ap % self.page
            hist[i, :len(r.pages)] = r.pages[:self.pages_per_seq]
        last, self.kc, self.vc = self._prefill_jit(
            self._weights, jnp.asarray(ids_np), jnp.asarray(pos0),
            jnp.asarray(nvalid), jnp.asarray(tok_pages), jnp.asarray(offs),
            jnp.asarray(hist), self.kc, self.vc)
        self.prefill_chunk_steps += 1
        completed = []
        for i, r in enumerate(reqs):
            r.prefill_pos += int(nvalid[i])
            if r.prefill_pos == len(r.seq_tokens):
                completed.append((i, r))
        if completed:
            rows = last[jnp.asarray([i for i, _ in completed])]
            toks = self._head_tokens(rows, [r for _, r in completed])
            for (i, r), tok in zip(completed, toks):
                self.prefills_completed += 1
                r.length = len(r.seq_tokens)
                self._emit(r, tok)
            if self._draft is not None:
                done_reqs = [r for _, r in completed]
                self._draft.prefill(done_reqs,
                                    [r.seq_tokens for r in done_reqs])

    def _swap_gather(self, kc, vc, pages):
        """Every layer's rows for `pages` -> [L, Hkv, P, page, D]
        (P = pages_per_seq, trash-padded; int8 caches yield a
        (codes, scales) leaf pair). One jitted dispatch per swap-out,
        then a single host transfer."""
        g = lambda c: c[:, :, pages]
        return _kv_map(g, kc), _kv_map(g, vc)

    def _swap_scatter(self, kc, vc, pages, k, v):
        """Scatter a host snapshot back into the caches at `pages`
        (trash-padded rows land in the scratch page — harmless by
        definition). Donates kc/vc."""
        sc = lambda c, s: c.at[:, :, pages].set(s)
        return _kv_map2(sc, kc, k), _kv_map2(sc, vc, v)

    def _padded_page_vec(self, pages):
        pad = np.full(self.pages_per_seq, self._trash_page, np.int32)
        pad[: len(pages)] = pages
        return self._jnp.asarray(pad)

    def _snapshot_to_host(self, r):
        """Build ``r.swapped`` — THE host KV snapshot format the
        swap-restore admission path consumes — shared by swap-policy
        preemption and the disagg ``extract()`` seam so the two can
        never drift. Sliced device-side to pages holding LIVE tokens
        before the host copy: the retained snapshot and the d2h
        transfer scale with written KV, not the page reservation (a
        mid-prefill victim's untouched prompt pages and grown-but-empty
        decode pages never leave the device; restore re-allocates the
        full reservation from prefill_pos/length bookkeeping)."""
        k, v = self._swap_out_jit(self.kc, self.vc,
                                  self._padded_page_vec(r.pages))
        written = max(r.length, r.prefill_pos)
        n = min((written + self.page - 1) // self.page, len(r.pages))
        cut = lambda c: np.asarray(c[:, :, :n])
        r.swapped = {"k": _kv_map(cut, k), "v": _kv_map(cut, v),
                     "n": n, "prefill_pos": r.prefill_pos,
                     "length": r.length}
        return r.swapped

    # -- prefix cache (content-addressed KV pages) --------------------------
    def _chain_keys(self, tokens, n_pages):
        """Chain digests of pages 0..n_pages-1: key_i =
        sha1(key_{i-1} || tokens of page i) — O(1) bytes per cache
        entry regardless of prefix depth (the vLLM block-hash-chain
        discipline; 160-bit collision space is identity in practice)."""
        import hashlib

        keys, prev = [], b""
        for i in range(n_pages):
            block = np.asarray(
                tokens[i * self.page: (i + 1) * self.page],
                np.int64).tobytes()
            prev = hashlib.sha1(prev + block).digest()
            keys.append(prev)
        return keys

    def _evictable(self):
        return [k for k, pg in self._prefix_cache.items()
                if self._page_ref.get(pg, 0) == 0]

    def _free_pages_for(self, n):
        """True if n pages can be allocated, evicting ref-0 cached pages
        (FIFO) as needed. Callers must PIN (incref) any matched shared
        pages before calling, or eviction could reclaim them."""
        while self.pool.available < n:
            victims = self._evictable()
            if not victims:
                return False
            key = victims[0]
            page = self._prefix_cache.pop(key)
            self._cached_pages.discard(page)
            self._page_ref.pop(page, None)
            self.pool.free([page])
            self.prefix_cache_evictions += 1
        return True

    def _alloc_ref(self, n):
        pages = self.pool.alloc(n)
        for pg in pages:
            self._page_ref[pg] = self._page_ref.get(pg, 0) + 1
        return pages

    def _release_pages(self, req, register):
        """Drop req's claim on its pages. Own pages whose content is a
        complete, deterministic token-prefix page are REGISTERED into the
        prefix cache (retained, lazily evictable) instead of freed; the
        rest return to the pool. Without the cache enabled this is
        exactly pool.free."""
        if not self.enable_prefix_cache:
            self.pool.free(req.pages)
            req.pages = []
            return
        register = register and req.admit_seq >= self._cache_admit_floor
        written = max(req.length, req.prefill_pos)
        full = req.prompt + req.generated
        n_complete = min(written // self.page, len(req.pages))
        keys = (self._chain_keys(full, n_complete)
                if register and n_complete else [])
        freed = []
        for i, pg in enumerate(req.pages):
            ref = self._page_ref.get(pg, 0) - 1
            if ref < 0:
                # a page released more times than it was claimed is a
                # double-release: silently clamping to zero masked the bug
                # (ADVICE r5) — count it and fail loudly
                _REF_UNDERFLOWS.inc()
                raise RuntimeError(
                    f"PagePool refcount underflow: page {pg} released by "
                    f"request {req.rid} but holds no claim — double "
                    "release (see serving_page_ref_underflows_total)")
            self._page_ref[pg] = ref
            if ref > 0:
                continue  # another live request still reads it
            if pg in self._cached_pages:
                continue  # retained by the cache (free-but-cached)
            if i < len(keys) and keys[i] not in self._prefix_cache:
                self._prefix_cache[keys[i]] = pg
                self._cached_pages.add(pg)
                continue
            freed.append(pg)
            self._page_ref.pop(pg, None)
        self.pool.free(freed)
        req.pages = []

    def _match_prefix(self, tokens):
        """Longest cached full-page chain strictly shorter than the
        prompt (>=1 token always left to prefill). Returns the shared
        page list."""
        if not self.enable_prefix_cache:
            return []
        max_pages = (len(tokens) - 1) // self.page
        shared = []
        for key in self._chain_keys(tokens, max_pages):
            pg = self._prefix_cache.get(key)
            if pg is None:
                break
            shared.append(pg)
        return shared

    def _swap_stage(self, snap, n):
        """FRESH host staging buffers per restore at the fixed
        [L, Hkv, P, page, D] scatter shape (leaf-wise over int8
        code/scale pairs), filled with the n-page snapshot. A reused
        buffer is unsound: on backends that zero-copy host arrays into
        the program (jax CPU aliases numpy memory instead of copying at
        dispatch), overwriting the staging buffer for restore N+1 races
        the still in-flight transfer of restore N. Fresh arrays make
        each restore's payload immutable for the lifetime of its
        dispatch; allocation cost is noise next to the h2d transfer."""

        def stage(leaf):
            shape = leaf.shape[:2] + (self.pages_per_seq,) + leaf.shape[3:]
            buf = np.empty(shape, leaf.dtype)
            buf[:, :, :n] = leaf
            return buf

        return _kv_map(stage, snap)

    def _preempt(self, slot_idx):
        """Evict a running request and requeue it at the FRONT of the
        waiting queue. Policy "recompute": free the pages and fold the
        generated tokens into the resume prompt — re-admission rebuilds
        the KV by prefilling prompt+generated. Policy "swap": snapshot
        the pages to host first — re-admission restores the KV with zero
        recompute. Correctness is bitwise for greedy decodes under both
        policies (asserted by tests)."""
        r = self._slots[slot_idx]
        if self.preempt_policy == "swap" and r.pages:
            # NOTE: the gather materialises [L, Hkv, P, page, D] on device
            # before the host copy. Pool exhaustion here is a logical
            # page-budget limit, not physical HBM exhaustion, so the
            # transient is safe; a deployment sized to true HBM capacity
            # would gather layer-by-layer instead.
            self._snapshot_to_host(r)
            self.swaps_out += 1
            self.pool.free(r.pages)
            r.pages = []
        else:
            # release BEFORE resetting the bookkeeping: registration
            # needs the written-token count, and caching the victim's
            # completed pages makes the recompute resume nearly free
            # (re-admission matches its own prefix)
            self._release_pages(r, register=True)
            r.seq_tokens = r.prompt + r.generated
            r.prefill_pos = 0
            r.length = 0
        self._slots[slot_idx] = None
        self._waiting.appendleft(r)
        self.preemptions += 1
        _PREEMPTIONS.inc(labels=(self.preempt_policy,))
        if r.first_token_t is None:
            _trace.async_end("prefill", r.rid, {"preempted": True})
        _trace.async_instant("preempt", r.rid,
                             {"policy": self.preempt_policy})
        _trace.async_begin("queue", r.rid, {"requeue": True})

    def _grow_pages(self):
        """Ensure every decoding slot owns pages for this tick's token.
        On pool exhaustion, preempt the YOUNGEST running request (its
        oldest peers keep their pages and finish first — guaranteed
        progress, no deadlock: a lone request always fits by the submit()
        feasibility check). Under a draft model the reservation covers
        the whole speculative window (K drafts + carry) instead of one
        token; a prefill-only engine never grows (its admissions reserve
        every page chunked prefill will write)."""
        if self.prefill_only:
            return
        while True:
            # oldest-first service order
            live = sorted(
                ((i, r) for i, r in enumerate(self._slots)
                 if r is not None and r.length > 0),
                key=lambda ir: ir[1].admit_seq)
            short = None
            for i, r in live:
                need = (r.length + self._lookahead
                        + self.page - 1) // self.page
                grow = need - len(r.pages)
                if grow <= 0:
                    continue
                ok = (self._free_pages_for(grow)
                      if self.enable_prefix_cache
                      else grow <= self.pool.available)
                if ok:
                    r.pages.extend(self._alloc_ref(grow)
                                   if self.enable_prefix_cache
                                   else self.pool.alloc(grow))
                else:
                    short = (i, r)
                    break
            if short is None:
                return
            # youngest victim across ALL occupied slots — a just-admitted
            # mid-prefill request is younger than any decoding one, so
            # the oldest running requests keep their pages and finish
            # first; only if the starved request IS the youngest does it
            # preempt itself (re-runs when pages free up)
            occupied = [(i, r) for i, r in enumerate(self._slots)
                        if r is not None]
            victim = max(occupied, key=lambda ir: ir[1].admit_seq)
            self._preempt(victim[0])

    def _finished(self, r):
        """True when a request has nothing left to generate: max_new
        reached, or its newest token is eos. THE completion predicate —
        retire, the decode-tick live filter, and the disagg handoff
        sweep all share it. A live brownout L1 cap (``max_new_cap``)
        lowers the limit for every request still generating; restoring
        the cap to None restores the full budget."""
        limit = self.max_new_tokens
        if self.max_new_cap is not None:
            limit = min(limit, self.max_new_cap)
        return (len(r.generated) >= limit
                or (self.eos is not None and bool(r.generated)
                    and r.generated[-1] == self.eos))

    def _retire(self, req: _Request):
        _REQ_LATENCY.observe(time.perf_counter() - req.submit_t)
        self._release_pages(req, register=True)
        with _trace.span("detokenize", attrs={"rid": req.rid},
                         cat="serve"):
            out = req.prompt + req.generated
        _trace.async_end("request", req.rid,
                         {"generated_tokens": len(req.generated)})
        return out

    def step(self):
        """Admit + one batched decode tick. Returns {rid: full_ids} for
        requests finishing THIS tick."""
        jax, jnp = self._jax, self._jnp
        newly = {}
        # deadlines sweep FIRST: an expired request must not occupy a
        # slot (or pages) for even one more tick
        self._sweep_deadlines()
        # retire next: a finishing slot frees pages and a slot for this
        # very tick's admissions
        for i, r in enumerate(list(self._slots)):
            if r is not None and self._finished(r):
                newly[r.rid] = self._retire(r)
                self._slots[i] = None
        with _trace.span("admission", cat="serve"):
            self._admit()
        if self.prefill_chunk is not None:
            with _trace.span("prefill_tick", cat="serve"):
                self._prefill_tick()
        self._grow_pages()
        # a request that hit max_new/eos at prefill completion THIS
        # tick must not decode once more before next tick's retire —
        # the off-by-one emitted max_new+1 tokens (and a token PAST
        # eos) whenever completion landed on the prefill path
        live = ([] if self.prefill_only else
                [(i, r) for i, r in enumerate(self._slots)
                 if r is not None and r.generated and r.length > 0
                 and not self._finished(r)])
        if _TELEMETRY_REG.enabled:
            _STEPS.inc()
            _QUEUE_DEPTH.set(len(self._waiting))
            occupied = sum(1 for s in self._slots if s is not None)
            _SLOTS_OCCUPIED.set(occupied)
            _KV_UTIL.set(1.0 - self.pool.available / self.pool.num_pages)
            _INT8_KV.set(1.0 if self.int8_kv else 0.0)
            if live:
                _BATCH_OCCUPANCY.observe(len(live) / self.max_slots)
        if not live:
            return newly
        # static greedy/sampling mode: one retrace per mode, and the
        # default all-greedy workload never pays the vocab sort
        do_sample = any(r.temperature > 0.0 for _, r in live)
        if (self._draft is not None and not do_sample
                and not self.spec_paused):
            # speculative tick: draft K, verify in one target forward
            self._spec_tick(live)
            return newly
        if self._draft is not None:
            _SPEC_TICKS.inc(labels=("fallback",))
        # fixed-width batch: pad with slot 0's state (results discarded)
        pad_to = self.max_slots
        rows = [r for _, r in live] + [live[0][1]] * (pad_to - len(live))
        tokens = jnp.asarray([r.generated[-1] for r in rows], jnp.int32)
        lens = jnp.asarray([r.length for r in rows], jnp.int32)
        tables = self._table_rows(rows)
        temps = jnp.asarray([r.temperature for r in rows], jnp.float32)
        top_ks = jnp.asarray([r.top_k for r in rows], jnp.int32)
        top_ps = jnp.asarray([r.top_p for r in rows], jnp.float32)
        self._key, sub = jax.random.split(self._key)
        with _trace.span("decode_tick",
                         attrs={"live": len(live)}, cat="serve"):
            nxt, self.kc, self.vc = self._decode_jit(
                self._weights, tokens, lens, tables, self.kc,
                self.vc, temps, top_ks, top_ps, sub, do_sample)
            # the host fetch is the tick's real sync point — inside the
            # span so decode wall time includes device work
            nxt = np.asarray(nxt)
        if self._draft is not None:
            # fallback tick under a draft: mirror the carry token into
            # the draft's KV (proposal discarded) so the draft cache
            # stays hole-free — without this, every sampled tick leaves
            # a permanently stale draft row and speculative acceptance
            # silently collapses once greedy ticks resume
            self._draft.catch_up(tokens, lens, tables)
        for j, (i, r) in enumerate(live):
            r.length += 1
            self._emit(r, int(nxt[j]))
        return newly

    def _table_rows(self, rows):
        """Fixed-shape [B, pages_per_seq] page tables (zero-padded; the
        kernels clamp + length-mask padded entries)."""
        table_rows = []
        for r in rows:
            row = list(r.pages) + [0] * (self.pages_per_seq - len(r.pages))
            table_rows.append(row[: self.pages_per_seq])
        return self._jnp.asarray(np.asarray(table_rows, np.int32))

    def _spec_tick(self, live):
        """Draft-model speculative decode tick (docs/SERVING.md): the
        draft proposes K greedy tokens per live row, the target verifies
        all of them in ONE forward (`_spec_verify`), and the longest
        draft prefix matching the target's own greedy tokens is emitted
        plus the target's bonus token — 1..K+1 tokens per tick, every
        one bitwise-identical to what plain greedy decode would emit."""
        jnp = self._jnp
        K = self.spec_tokens
        pad_to = self.max_slots
        rows = [r for _, r in live] + [live[0][1]] * (pad_to - len(live))
        lens_np = np.asarray([r.length for r in rows], np.int32)
        lens = jnp.asarray(lens_np)
        tables = self._table_rows(rows)

        def ctx_tok(r, i):
            # context token i without materializing prompt+generated
            # (O(seq) per row per tick on the hot path otherwise)
            n = len(r.prompt)
            return r.prompt[i] if i < n else r.generated[i - n]

        # context[length] is the carry token (generated[-1]);
        # context[length-1] re-primes the draft's previous position —
        # always a rewrite of the same value EXCEPT after a fully-
        # accepted window, where it fills the draft-KV hole for the
        # token the draft proposed but never consumed
        prev = np.asarray([ctx_tok(rows[j], int(lens_np[j]) - 1)
                           for j in range(pad_to)], np.int32)
        cur = np.asarray([ctx_tok(rows[j], int(lens_np[j]))
                          for j in range(pad_to)], np.int32)
        with _trace.span("spec_draft", attrs={"k": K}, cat="serve"):
            d_toks = self._draft.propose(prev, cur, lens, tables, K)
        toks = np.concatenate([cur[:, None], d_toks], 1)     # [B, K+1]
        with _trace.span("spec_verify",
                         attrs={"live": len(live), "k": K},
                         cat="serve"):
            t_out, self.kc, self.vc = self._verify_jit(
                self._weights, jnp.asarray(toks), lens, tables,
                self.kc, self.vc)
            t_np = np.asarray(t_out)
        accepted_total = 0
        for j, (i, r) in enumerate(live):
            drafts, targets = d_toks[j], t_np[j]
            m = 0
            while m < K and int(drafts[m]) == int(targets[m]):
                m += 1
            accepted_total += m
            out = []
            for t in [int(x) for x in drafts[:m]] + [int(targets[m])]:
                out.append(t)
                if self.eos is not None and t == self.eos:
                    break
                if len(r.generated) + len(out) >= self.max_new_tokens:
                    break
            r.length += len(out)
            for t in out:
                self._emit(r, t)
        self.spec_ticks += 1
        self.spec_draft_tokens += K * len(live)
        self.spec_accepted_tokens += accepted_total
        if _TELEMETRY_REG.enabled:
            _SPEC_TICKS.inc(labels=("spec",))
            _SPEC_DRAFTED.inc(K * len(live))
            _SPEC_ACCEPTED.inc(accepted_total)
        _trace.instant("spec_accept",
                       {"accepted": accepted_total,
                        "drafted": K * len(live)}, cat="serve")

    @property
    def spec_acceptance_rate(self):
        """Fraction of drafted tokens the target verify accepted."""
        return (self.spec_accepted_tokens
                / max(1, self.spec_draft_tokens))

    def run_until_complete(self, max_ticks=10000):
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            if not self._waiting and all(s is None for s in self._slots):
                return done
        raise TimeoutError("serving loop did not drain")

    # -- fleet surface (router / disaggregated serving) ---------------------
    def load(self):
        """Live load signals for an admission router (docs/SERVING.md):
        queue depth, slot occupancy, and KV headroom — the same state
        the per-tick telemetry gauges publish, read synchronously."""
        occupied = sum(1 for s in self._slots if s is not None)
        return {
            "queue_depth": len(self._waiting),
            "occupied_slots": occupied,
            "free_slots": self.max_slots - occupied,
            "kv_free_fraction": self.pool.available / self.pool.num_pages,
            # per-replica resident decode-weight footprint by storage
            # dtype (docs/QUANT.md): int8-packed replicas report the
            # reduced bytes a placement router can pack against
            "int8_weights": self.int8_weights,
            "weight_bytes": dict(self.weight_bytes),
        }

    def prefix_match_pages(self, tokens):
        """How many full KV pages of this prompt's prefix the engine's
        prefix cache already holds — the prefix-affinity routing signal.
        0 when the cache is off (match is read-only: nothing is pinned)."""
        return len(self._match_prefix([int(t) for t in tokens]))

    def extract(self, slot_idx):
        """Disaggregated-serving handoff seam (fleet.disagg): snapshot a
        slot's KV pages + resume state to host exactly like a swap-out,
        release the slot and its pages, and return the request. A
        decode engine `inject()`s the request; its swap-restore
        admission path scatters the pages back — bitwise (exact caches
        round-trip unchanged; int8 caches move their raw codes+scales).
        Unlike `_preempt(policy="swap")`, this works with ANY preempt
        policy and registers completed prefix pages into this engine's
        prefix cache (the prefill worker keeps the warm prefix)."""
        r = self._slots[slot_idx]
        if r is None:
            raise ValueError(f"slot {slot_idx} is empty")
        self._snapshot_to_host(r)
        self._release_pages(r, register=True)
        self._slots[slot_idx] = None
        return r

    def inject(self, req):
        """Accept a request extracted from another engine (the decode
        half of a disaggregated pair). Its host snapshot restores
        through the standard swap-restore admission path. Both engines
        must share the page geometry (page_size, pages_per_seq) and KV
        mode; the disagg wrapper enforces this."""
        self._next_rid = max(self._next_rid, req.rid + 1)
        self._waiting.append(req)

    def export_prefix_pages(self, max_pages=None):
        """Serialize prefix-cache entries — (chain key, one-page KV
        snapshot) pairs, in cache insertion order so every chain ships
        head-first — for a drain destination to warm its cache from
        before this engine retires. ``max_pages`` caps the payload; a
        chain cut mid-way imports as a valid shorter prefix (a shipped
        tail whose head was cut is unreachable by ``_match_prefix`` and
        simply evicts under pressure)."""
        if not self.enable_prefix_cache:
            return []
        keys = list(self._prefix_cache)
        if max_pages is not None:
            keys = keys[: int(max_pages)]
        entries = []
        for start in range(0, len(keys), self.pages_per_seq):
            chunk = keys[start: start + self.pages_per_seq]
            pages = [self._prefix_cache[k] for k in chunk]
            k, v = self._swap_out_jit(self.kc, self.vc,
                                      self._padded_page_vec(pages))
            for i, key in enumerate(chunk):
                cut = lambda c, i=i: np.asarray(c[:, :, i: i + 1])
                entries.append({"key": bytes(key),
                                "k": _kv_map(cut, k),
                                "v": _kv_map(cut, v)})
        self.prefix_pages_exported += len(entries)
        return entries

    def import_prefix_pages(self, entries):
        """Install exported prefix pages: allocate a page, scatter the
        snapshot into the caches, register key -> page at refcount 0 —
        free-but-cached, evictable under pressure like any cached page.
        Known keys are skipped; import never evicts anything (free-pool
        pages only: warming must not cannibalize live or warmer state).
        Returns the number of pages imported."""
        if not self.enable_prefix_cache:
            return 0
        n = 0
        for e in entries:
            key = bytes(e["key"])
            if key in self._prefix_cache:
                continue
            if self.pool.available == 0:
                break
            pg = self.pool.alloc(1)[0]
            pages = self._jnp.asarray(np.asarray([pg], np.int32))
            self.kc, self.vc = self._swap_scatter(
                self.kc, self.vc, pages, e["k"], e["v"])
            self._prefix_cache[key] = pg
            self._cached_pages.add(pg)
            self._page_ref[pg] = 0
            n += 1
        self.prefix_pages_imported += n
        return n

    def warmup(self, sample=False):
        """Compile the engine's programs on dummy operands (cache writes
        land in the scratch page) and record the wall time in
        ``self.build_seconds`` — the replica cold-start number the
        serving bench records and bench_gate gates (docs/SERVING.md).
        Greedy programs only unless ``sample=True`` (the first sampled
        tick otherwise pays its own compile). A ``prefill_only`` engine
        compiles only its prefill program — the decode/verify programs
        never run there, and charging their compile into the gated
        cold-start number would overstate real spin-up cost."""
        jax, jnp = self._jax, self._jnp
        t0 = time.perf_counter()
        b = self.max_slots
        tokens = jnp.zeros((b,), jnp.int32)
        lens = jnp.zeros((b,), jnp.int32)
        tables = jnp.full((b, self.pages_per_seq), self._trash_page,
                          jnp.int32)
        temps = jnp.zeros((b,), jnp.float32)
        top_ks = jnp.zeros((b,), jnp.int32)
        top_ps = jnp.ones((b,), jnp.float32)
        key = jax.random.PRNGKey(0)   # never touches self._key's stream
        modes = () if self.prefill_only else (
            (False, True) if sample else (False,))
        for do_sample in modes:
            nxt, self.kc, self.vc = self._decode_jit(
                self._weights, tokens, lens, tables, self.kc, self.vc,
                temps, top_ks, top_ps, key, do_sample)
            np.asarray(nxt)           # block: compile + first dispatch
        if self.prefill_chunk is not None:
            B, c = self.max_slots, self.prefill_chunk
            last, self.kc, self.vc = self._prefill_jit(
                self._weights, jnp.zeros((B, c), jnp.int32),
                jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
                jnp.full((B, c), self._trash_page, jnp.int32),
                jnp.zeros((B, c), jnp.int32),
                jnp.full((B, self.pages_per_seq), self._trash_page,
                         jnp.int32),
                self.kc, self.vc)
            np.asarray(last)
        if self._draft is not None and not self.prefill_only:
            t_out, self.kc, self.vc = self._verify_jit(
                self._weights,
                jnp.zeros((b, self.spec_tokens + 1), jnp.int32),
                lens, tables, self.kc, self.vc)
            np.asarray(t_out)
            self._draft.warmup(tables)
        self.build_seconds = time.perf_counter() - t0
        return self.build_seconds
