"""Continuous-batching LLM serving over paged KV caches.

Capability slot: the reference's LLM serving stack (the C++ side of
`block_multi_head_attention` + the fastdeploy/serving slot managers that
drive it). TPU-native design:

- KV lives in PAGES `[num_pages, Hkv, page_size, D]` per layer; a
  `PagePool` hands pages to sequences on admission and reclaims them on
  completion, so memory scales with live tokens, not max_seq * slots.
- `ContinuousBatchingEngine` drives the vLLM-style loop: admit waiting
  requests into free slots (prefill writes the prompts' KV into their
  pages), then run ONE batched decode step for every live slot per
  `step()` — new requests join mid-flight without stalling running ones,
  finished slots free their pages immediately.
- Admission prefills ALL newly admitted prompts as one padded batch —
  one pass over the weights per admission group, not per request.
- The decode step's attention is the pallas paged kernel
  (`ops/pallas/decode_attention.paged_attention`): block tables via
  scalar prefetch, so only the pages a sequence owns are fetched.
- Sampling runs inside the jitted decode step: per-request temperature /
  top-k / top-p (temperature 0 = greedy, the default). Per-token
  streaming callbacks fire as tokens are emitted.
- Admission reserves only prefill pages; decode pages are allocated as
  sequences grow. On pool exhaustion the youngest request is preempted:
  policy "recompute" (default) folds its tokens into the resume prompt,
  "swap" round-trips its KV through host memory (measured tradeoffs in
  docs/ROUND5_RESPONSE.md).
- `enable_prefix_cache=True` adds automatic prefix caching: pages are
  content-addressed by sha1 block-hash chains and reused read-only
  across requests sharing a prompt prefix (~2x TTFT on long shared
  system prompts, measured).

Weights are packed into an explicit pytree passed to the jitted step (not
closed-over constants), so `reload_weights()` on a live engine takes
effect without recompilation.

Works with the GPT/LLaMA stacked-weights families (anything exposing
`_decode_params()` — llama.py:66).
"""
from __future__ import annotations

import math
import time
from collections import deque

import numpy as np

from .. import telemetry as _telemetry
from ..telemetry import trace as _trace

__all__ = ["PagePool", "ContinuousBatchingEngine"]

# serving metrics (names/labels contract: docs/TELEMETRY.md). Gauges are
# refreshed once per step(); counters tick at the event sites.
_TELEMETRY_REG = _telemetry.get_registry()
_QUEUE_DEPTH = _telemetry.gauge(
    "serving_queue_depth", "requests waiting for admission")
_SLOTS_OCCUPIED = _telemetry.gauge(
    "serving_slots_occupied", "engine slots holding a live request")
_BATCH_OCCUPANCY = _telemetry.histogram(
    "serving_batch_occupancy", "live slots / max_slots per decode tick",
    buckets=tuple(i / 8 for i in range(1, 9)))
_KV_UTIL = _telemetry.gauge(
    "serving_kv_page_utilization", "fraction of KV pages allocated")
_ADMISSIONS = _telemetry.counter(
    "serving_admissions_total", "requests admitted into slots",
    labelnames=("kind",))
_PREEMPTIONS = _telemetry.counter(
    "serving_preemptions_total", "requests evicted under page pressure",
    labelnames=("policy",))
_STEPS = _telemetry.counter(
    "serving_steps_total", "engine decode ticks")
_REQ_LATENCY = _telemetry.histogram(
    "serving_request_latency_seconds", "submit-to-completion wall time")
_TTFT = _telemetry.histogram(
    "serving_ttft_seconds", "submit-to-first-token wall time")
_REF_UNDERFLOWS = _telemetry.counter(
    "serving_page_ref_underflows_total",
    "KV page refcount decremented below zero (double-release bug)")


class PagePool:
    """Free-list page allocator (the block manager)."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = deque(range(num_pages))

    def alloc(self, n: int):
        if n > len(self._free):
            raise MemoryError(
                f"PagePool: need {n} pages, {len(self._free)} free")
        return [self._free.popleft() for _ in range(n)]

    def free(self, pages):
        self._free.extend(pages)

    @property
    def available(self):
        return len(self._free)


class _Request:
    __slots__ = ("rid", "prompt", "generated", "length", "pages",
                 "temperature", "top_k", "top_p", "on_token",
                 "prefill_pos", "seq_tokens", "admit_seq", "swapped",
                 "submit_t", "first_token_t")

    def __init__(self, rid, prompt, temperature=0.0, top_k=0, top_p=1.0,
                 on_token=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.generated = []
        self.length = 0          # tokens currently in the kv pages
        self.pages = []
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.on_token = on_token
        self.prefill_pos = 0     # tokens already written to kv (chunked)
        # the tokens prefill must (re)build KV for: the prompt initially;
        # after a preemption, prompt + generated-so-far (the resume prefix)
        self.seq_tokens = self.prompt
        self.admit_seq = -1      # admission order (preemption victims =
                                 # youngest first, vLLM recompute policy)
        self.swapped = None      # host-side KV snapshot (swap policy)
        self.submit_t = time.perf_counter()   # latency telemetry anchors
        self.first_token_t = None


def _sample_rows(jax, jnp, logits, temps, top_ks, top_ps, key):
    """Per-row temperature / top-k / top-p sampling; temp<=0 rows take
    argmax. Runs inside the jitted decode step."""
    f32 = logits.astype(jnp.float32)
    greedy = jnp.argmax(f32, -1).astype(jnp.int32)
    # temperature scales BEFORE the filters (HF/vLLM order): the nucleus is
    # computed on the distribution actually sampled from, so high
    # temperature widens it and low temperature narrows it
    scaled = f32 / jnp.maximum(temps[:, None], 1e-6)
    V = scaled.shape[-1]
    srt = jnp.flip(jnp.sort(scaled, -1), -1)                  # desc [B, V]
    k_eff = jnp.where(top_ks > 0, top_ks, V)
    kth = jnp.take_along_axis(
        srt, jnp.clip(k_eff - 1, 0, V - 1)[:, None], 1)       # [B, 1]
    topk_sorted = jnp.where(srt < kth, -jnp.inf, srt)
    probs_sorted = jax.nn.softmax(topk_sorted, -1)
    csum = jnp.cumsum(probs_sorted, -1)
    # nucleus: keep the smallest prefix with cumulative mass >= top_p
    # (the first token is always kept: csum - p_i < p holds at i=0)
    keep = (csum - probs_sorted) < top_ps[:, None]
    thr = jnp.min(jnp.where(keep, topk_sorted, jnp.inf), -1, keepdims=True)
    # a logit survives only if it passes BOTH filters (max of thresholds);
    # keep[:, 0] is always True so thr is finite
    masked = jnp.where(scaled < jnp.maximum(kth, thr), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, masked, -1).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


class ContinuousBatchingEngine:
    def __init__(self, model, max_slots=4, page_size=64, num_pages=None,
                 max_seq_len=None, max_new_tokens=32, eos_token_id=None,
                 seed=0, prefill_chunk=None, preempt_policy="recompute",
                 enable_prefix_cache=False):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        cfg = model.config
        self.cfg = cfg
        self.page = page_size
        self.max_seq = max_seq_len or cfg.max_seq_len
        self.pages_per_seq = (self.max_seq + page_size - 1) // page_size
        self.max_slots = max_slots
        self.max_new_tokens = max_new_tokens
        self.eos = eos_token_id
        num_pages = num_pages or (max_slots * self.pages_per_seq + 2)
        self.pool = PagePool(num_pages)
        # one extra non-allocable scratch page: the BATCHED chunked
        # prefill routes padded rows' cache writes there
        self._trash_page = num_pages

        hd = cfg.hidden_size // cfg.num_heads
        self.hd, self.hkv = hd, cfg.num_kv_heads

        self._model = model
        self._weights = self._pack_weights(model)
        self._key = jax.random.PRNGKey(seed)

        # paged caches per layer, KERNEL layout [Hkv, num_pages, page, D]
        # (what paged_attention consumes — no per-step transposes)
        dt = self._weights["embed"].dtype
        self.kc = [jnp.zeros((self.hkv, num_pages + 1, page_size, hd), dt)
                   for _ in range(cfg.num_layers)]
        self.vc = [jnp.zeros((self.hkv, num_pages + 1, page_size, hd), dt)
                   for _ in range(cfg.num_layers)]

        self._slots: list[_Request | None] = [None] * max_slots
        self._waiting: deque[_Request] = deque()
        self._next_rid = 0
        # weights are argument 0 — NOT closed-over jit constants — so a
        # reload on a live engine feeds the already-compiled step
        self._decode_jit = jax.jit(self._decode_step, donate_argnums=(4, 5),
                                   static_argnums=(10,))
        self.prefill_batches = 0      # observability: admission group count
        self.preemptions = 0          # pages reclaimed from the youngest
        self._admit_counter = 0
        # preempt_policy: what happens to a victim's KV state.
        #   "recompute" — drop pages, fold generated tokens into the resume
        #     prompt, rebuild KV by re-prefilling on re-admission (vLLM
        #     recompute; the r5 default).
        #   "swap" — copy the victim's pages to HOST memory, free the
        #     device pages, and scatter the snapshot back on re-admission
        #     (vLLM swap / the reference block-table cache-offload shape):
        #     no prefill FLOPs are re-paid, at the price of two
        #     host<->device transfers of the live KV. Greedy outputs are
        #     bitwise identical either way (bf16 round-trips exactly
        #     through the host copy); tests assert both.
        if preempt_policy not in ("recompute", "swap"):
            raise ValueError(
                f"preempt_policy must be 'recompute' or 'swap', "
                f"got {preempt_policy!r}")
        self.preempt_policy = preempt_policy
        # enable_prefix_cache=True: automatic prefix caching (vLLM APC /
        # SGLang radix-cache shape). KV pages are content-addressed by
        # their token-prefix chain; a new request whose prompt shares a
        # full-page-aligned prefix with any previously computed sequence
        # REUSES those pages (read-only, refcounted) and prefills only
        # the tail. Released pages are retained "free-but-cached": they
        # are reclaimed lazily (cache eviction, FIFO over ref-0 entries)
        # only when the pool runs short. Matching is capped one token
        # below the prompt end so a fully-cached prompt still computes
        # its first-token logits. Sound because KV at position i is a
        # pure function of tokens[0..i]; writes only ever target
        # positions past the matched prefix (page-granular match), so
        # shared pages are never written. Requires chunked prefill (the
        # tail prefill starts mid-prompt) and the recompute preemption
        # policy (swap restore scatters into pages, which must stay
        # exclusive).
        if enable_prefix_cache:
            if prefill_chunk is None:
                raise ValueError("enable_prefix_cache requires chunked "
                                 "prefill (prefill_chunk=...)")
            if preempt_policy != "recompute":
                raise ValueError("enable_prefix_cache composes only with "
                                 "preempt_policy='recompute'")
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self._prefix_cache = {}       # token-chain digest -> page id
        self._cached_pages = set()    # page ids held by the cache (O(1)
                                      # membership on the release path)
        self._page_ref = {}           # page id -> live-request refcount
        self.prefix_cache_hits = 0    # pages reused instead of prefilled
        self.prefix_cache_evictions = 0
        self.prefix_tokens_skipped = 0
        self._cache_admit_floor = 0   # requests admitted before a
                                      # reload_weights hold stale KV and
                                      # must not register pages
        self.swaps_out = 0            # victims snapshotted to host
        self.swaps_in = 0             # snapshots restored to device
        # fixed-shape ([pages_per_seq] page vector, trash-padded) so each
        # compiles ONCE; swap-in donates the caches (no double buffering)
        self._swap_out_jit = jax.jit(self._swap_gather)
        self._swap_in_jit = jax.jit(self._swap_scatter,
                                    donate_argnums=(0, 1))
        # chunked prefill (vLLM-style): admit immediately, write the
        # prompt's KV `prefill_chunk` tokens per TICK so long prompts
        # don't stall the decode latency of running requests
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        self.prefills_completed = 0   # per-request (both prefill modes)
        # batched chunked prefill: ONE jitted fixed-shape pass advances
        # every prefilling slot by up to prefill_chunk tokens per tick
        # (VERDICT r3 item 7 — the eager per-request chunk loop paid the
        # ~2.5ms/dispatch host cost per layer per request)
        self._prefill_jit = jax.jit(self._prefill_chunk_step,
                                    donate_argnums=(7, 8))
        self.prefill_chunk_steps = 0  # observability: jitted pass count

    @staticmethod
    def _pack_weights(model):
        # the decode contract: `_decode_params()` (per-layer weight dicts,
        # llama.py:66 / gpt.py GPTForCausalLMPipe) + embed/final_norm on
        # the model or its `.model` core + optional untied `lm_head`
        params = model._decode_params()
        core = model.model if hasattr(model, "model") else model
        head = getattr(model, "lm_head", None)
        return {
            "layers": [tuple(lp[k]._data for k in
                             ("ln1", "wq", "wk", "wv", "wo", "ln2", "wg",
                              "wu", "wd")) for lp in params],
            "embed": core.embed_tokens.weight._data,
            "fnorm": core.final_norm.weight._data,
            "head": head.weight._data if head is not None else None,
        }

    def reload_weights(self, model=None):
        """Re-read weights from the model (e.g. after an in-place update);
        the compiled decode step picks them up on the next tick. Any
        cached prefix KV is invalidated (it was computed under the old
        weights): ref-0 cached pages are freed now, in-use ones when
        their readers release them; requests already admitted are barred
        from registering their (stale) pages.

        The old packed weights are released BEFORE repacking: with the
        lazy per-layer slicing of the stacked models (gpt.py
        _decode_params), a live-engine reload peaks at stacked + new
        slices + one in-flight layer instead of holding old and new
        sliced copies side by side (ADVICE r5). The release is what buys
        the headroom, so a mid-pack failure cannot fall back to the old
        weights — it raises loudly and the engine stays weightless until
        a reload succeeds (serving on half-reloaded state would be
        worse)."""
        self._weights = None
        try:
            self._weights = self._pack_weights(model or self._model)
        except Exception as e:
            raise RuntimeError(
                "reload_weights failed mid-pack; the old weights were "
                "already released (HBM headroom), so the engine has no "
                "weights until a reload_weights() succeeds") from e
        if self.enable_prefix_cache:
            for key in list(self._prefix_cache):
                pg = self._prefix_cache.pop(key)
                self._cached_pages.discard(pg)
                if self._page_ref.get(pg, 0) == 0:
                    self._page_ref.pop(pg, None)
                    self.pool.free([pg])
            self._cache_admit_floor = self._admit_counter

    # -- model math ---------------------------------------------------------
    @staticmethod
    def _rope(x, pos):
        """Shared framework rope (models/gpt.py) — serving stays
        bit-identical to training/generate."""
        from ..models.gpt import _rope_at_positions

        return _rope_at_positions(x, pos)

    def _layer_forward(self, li, lp, x, pos0, attend):
        """One decoder layer of the EAGER prefill paths: projections +
        rope + `attend(li, q, k, v)` (which owns cache writes and the
        attention math) + MLP. Shared by group and chunked prefill so
        their numerics can never diverge."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure

        ln1, wq, wk, wv, wo, ln2, wg, wu, wd = lp
        B, S = x.shape[:2]
        h = _rms_pure(x, ln1)
        q = (h @ wq).reshape(B, S, self.cfg.num_heads, self.hd)
        k = (h @ wk).reshape(B, S, self.hkv, self.hd)
        v = (h @ wv).reshape(B, S, self.hkv, self.hd)
        q, k = self._rope(q, pos0), self._rope(k, pos0)
        o = attend(li, q, k, v)                       # [B, S, Hq, D]
        x = x + o.reshape(B, S, -1) @ wo
        h2 = _rms_pure(x, ln2)
        return x + (jax.nn.silu(h2 @ wg) * (h2 @ wu)) @ wd

    def _head_tokens(self, last, reqs):
        """final-norm'd last hidden rows [B, H] -> first token per req."""
        jax, jnp = self._jax, self._jnp
        w = self._weights
        lg = (last @ w["head"] if w["head"] is not None
              else last @ w["embed"].T)
        self._key, sub = jax.random.split(self._key)
        if any(r.temperature > 0.0 for r in reqs):
            toks = _sample_rows(
                jax, jnp, lg,
                jnp.asarray([r.temperature for r in reqs], jnp.float32),
                jnp.asarray([r.top_k for r in reqs], jnp.int32),
                jnp.asarray([r.top_p for r in reqs], jnp.float32), sub)
        else:
            toks = jnp.argmax(lg.astype(jnp.float32), -1)
        return [int(t) for t in np.asarray(toks)]

    def _prefill_group(self, reqs):
        """Run ALL newly admitted prompts as ONE padded batch: write each
        prompt's KV into its pages, return the first generated token per
        request.

        One pass over the weights per admission group (the reference's
        serving stack batches prefill the same way before handing slots to
        the decode loop). Runs eagerly: page-cache writes copy the pool
        once per layer per GROUP; jitting would retrace per padded length
        (bucket lengths first if admission cost ever dominates)."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure

        self.prefill_batches += 1
        self.prefills_completed += len(reqs)
        w = self._weights
        B = len(reqs)
        lens = np.asarray([len(r.seq_tokens) for r in reqs])
        S = int(lens.max())
        ids_np = np.zeros((B, S), np.int32)
        for i, r in enumerate(reqs):
            ids_np[i, : lens[i]] = r.seq_tokens
        ids = jnp.asarray(ids_np)
        x = w["embed"][ids]                                  # [B, S, H]
        pos0 = jnp.zeros((B,), jnp.int32)
        scale = 1.0 / math.sqrt(self.hd)
        rep = self.cfg.num_heads // self.hkv
        mask = jnp.tril(jnp.ones((S, S), bool))

        # flattened valid (row, pos) pairs -> page/offset scatter targets
        rows = np.concatenate([np.full(l, i) for i, l in enumerate(lens)])
        poss = np.concatenate([np.arange(l) for l in lens])
        tok_pages = np.concatenate(
            [np.asarray(r.pages, np.int64)[np.arange(l) // self.page]
             for r, l in zip(reqs, lens)])
        offs = jnp.asarray(poss % self.page)
        rows_j, poss_j = jnp.asarray(rows), jnp.asarray(poss)

        def attend(li, q, k, v):
            ck = jnp.repeat(k, rep, 2) if rep > 1 else k
            cv = jnp.repeat(v, rep, 2) if rep > 1 else v
            logits = jnp.einsum("bthd,bshd->bhts",
                                (q * scale).astype(jnp.float32),
                                ck.astype(jnp.float32))
            logits = jnp.where(mask[None, None], logits, -1e30)
            probs = jax.nn.softmax(logits, -1)
            o = jnp.einsum("bhts,bshd->bthd", probs,
                           cv.astype(jnp.float32)).astype(q.dtype)
            # scatter the group's valid k/v into the owned pages; ADJACENT
            # advanced indices (axes 1,2) stay in place -> [Hkv, N, D]
            kvals = jnp.swapaxes(k[rows_j, poss_j], 0, 1)
            vvals = jnp.swapaxes(v[rows_j, poss_j], 0, 1)
            self.kc[li] = self.kc[li].at[:, tok_pages, offs, :].set(
                kvals.astype(self.kc[li].dtype))
            self.vc[li] = self.vc[li].at[:, tok_pages, offs, :].set(
                vvals.astype(self.vc[li].dtype))
            return o

        for li, lp in enumerate(w["layers"]):
            x = self._layer_forward(li, lp, x, pos0, attend)
        x = _rms_pure(x, w["fnorm"])
        last = x[jnp.arange(B), jnp.asarray(lens - 1)]       # [B, H]
        toks = self._head_tokens(last, reqs)
        for i, r in enumerate(reqs):
            r.length = int(lens[i])
            # group prefill wrote the whole prompt: keep prefill_pos in
            # lockstep so a later swap snapshot is classified decode-phase
            # (its restore must reserve the growth page, not the prompt)
            r.prefill_pos = int(lens[i])
        return toks

    def _decode_step(self, weights, tokens, lens, tables, kc, vc,
                     temps, top_ks, top_ps, key, do_sample=False):
        """ONE batched decode: tokens [B] (last emitted), lens [B] tokens
        already cached, tables [B, pages_per_seq]. Returns (next [B],
        new kc, new vc)."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure
        from ..ops.pallas.decode_attention import paged_attention

        b = tokens.shape[0]
        x = weights["embed"][tokens][:, None]                # [B, 1, H]
        page_ids = tables[jnp.arange(b), lens // self.page]
        offs = lens % self.page
        for li, lp in enumerate(weights["layers"]):
            ln1, wq, wk, wv, wo, ln2, wg, wu, wd = lp
            h = _rms_pure(x, ln1)
            q = (h @ wq).reshape(b, 1, self.cfg.num_heads, self.hd)
            k = (h @ wk).reshape(b, 1, self.hkv, self.hd)
            v = (h @ wv).reshape(b, 1, self.hkv, self.hd)
            q, k = self._rope(q, lens), self._rope(k, lens)
            kc_l = kc[li].at[:, page_ids, offs, :].set(
                jnp.swapaxes(k[:, 0], 0, 1).astype(kc[li].dtype))
            vc_l = vc[li].at[:, page_ids, offs, :].set(
                jnp.swapaxes(v[:, 0], 0, 1).astype(vc[li].dtype))
            kc[li], vc[li] = kc_l, vc_l
            o = paged_attention(q[:, 0], kc_l, vc_l, tables, lens + 1)
            x = x + o.reshape(b, 1, -1).astype(x.dtype) @ wo
            h2 = _rms_pure(x, ln2)
            x = x + (jax.nn.silu(h2 @ wg) * (h2 @ wu)) @ wd
        x = _rms_pure(x, weights["fnorm"])[:, 0]
        lg = (x @ weights["head"] if weights["head"] is not None
              else x @ weights["embed"].T)
        if do_sample:
            nxt = _sample_rows(jax, jnp, lg, temps, top_ks, top_ps, key)
        else:
            # greedy-only tick: skip the full-vocab sort/cumsum entirely
            nxt = jnp.argmax(lg.astype(jnp.float32), -1).astype(jnp.int32)
        return nxt, kc, vc

    # -- engine surface -----------------------------------------------------
    def submit(self, prompt_ids, temperature=0.0, top_k=0, top_p=1.0,
               on_token=None) -> int:
        """Queue a request. ``temperature=0`` decodes greedily; otherwise
        softmax sampling with optional top_k / top_p truncation.
        ``on_token(rid, token_id)`` streams each generated token."""
        if len(prompt_ids) == 0:
            raise ValueError("empty prompt: a request needs at least one "
                             "token to prefill")
        total = len(prompt_ids) + self.max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"request needs {total} tokens (prompt "
                f"{len(prompt_ids)} + max_new {self.max_new_tokens}) > "
                f"max_seq_len {self.max_seq}")
        need = (total + self.page - 1) // self.page
        if need > self.pool.num_pages:
            raise ValueError(
                f"request needs {need} pages > pool size "
                f"{self.pool.num_pages}")
        rid = self._next_rid
        self._next_rid += 1
        self._waiting.append(_Request(
            rid, [int(t) for t in prompt_ids], temperature, top_k, top_p,
            on_token))
        # request span tree (docs/TELEMETRY.md Tracing): the async
        # "request" span covers submit → retire; "queue" covers
        # submit → admission (re-opened on preemption requeue)
        _trace.async_begin("request", rid,
                           {"prompt_tokens": len(prompt_ids)})
        _trace.async_begin("queue", rid)
        return rid

    def _emit(self, req, tok):
        if req.first_token_t is None:
            req.first_token_t = time.perf_counter()
            _TTFT.observe(req.first_token_t - req.submit_t)
            _trace.async_end("prefill", req.rid)
            _trace.async_instant("first_token", req.rid)
        req.generated.append(tok)
        if req.on_token is not None:
            req.on_token(req.rid, tok)

    def _admit(self):
        group = []
        for i in range(self.max_slots):
            if self._slots[i] is not None or not self._waiting:
                continue
            req = self._waiting[0]
            if req.swapped is not None:
                # swap policy re-admission: restore the host KV snapshot
                # into freshly allocated pages — no prefill re-run. For a
                # decode-phase snapshot, also reserve THIS tick's growth
                # page up front: restoring with exactly n pages when
                # length is page-aligned would hand _grow_pages a starved
                # youngest request and swap it straight back out (a full
                # round-trip per tick with zero progress).
                snap = req.swapped
                n = snap["n"]
                # restore the FULL reservation, not just the snapshot
                # pages: a mid-prefill victim needs its whole prompt's
                # pages back for _prefill_tick's scatter targets, and a
                # decode-phase one needs this tick's growth page (without
                # it a page-aligned restoree would be the starved
                # youngest and swap straight back out)
                if snap["prefill_pos"] < len(req.seq_tokens):
                    need = max(n, (len(req.seq_tokens) + self.page - 1)
                               // self.page)
                else:
                    need = max(n, (snap["length"] + self.page) // self.page)
                if need > self.pool.available:
                    break  # head-of-line waits for pages
                self._waiting.popleft()
                req.pages = self.pool.alloc(need)
                # stage the n-page snapshot into a fresh fixed-shape host
                # pair (no zeroing — the padded rows scatter into the
                # scratch page, so their uninitialized contents are
                # irrelevant; the padded h2d volume is the price of the
                # compile-once scatter)
                kh, vh = self._swap_stage(snap["k"].shape, snap["k"].dtype)
                kh[:, :, :n] = snap["k"]
                vh[:, :, :n] = snap["v"]
                self.kc, self.vc = self._swap_in_jit(
                    list(self.kc), list(self.vc),
                    self._padded_page_vec(req.pages[:n]),
                    self._jnp.asarray(kh), self._jnp.asarray(vh))
                req.prefill_pos = snap["prefill_pos"]
                req.length = snap["length"]
                req.swapped = None
                self.swaps_in += 1
                req.admit_seq = self._admit_counter
                self._admit_counter += 1
                self._slots[i] = req
                _ADMISSIONS.inc(labels=("swap_restore",))
                _trace.async_end("queue", req.rid)
                _trace.async_instant("admitted", req.rid,
                                     {"kind": "swap_restore"})
                if req.first_token_t is None:
                    # a mid-prefill swap victim resumes its prefill
                    # phase here — re-open the span so the restore-to-
                    # first-token segment stays in the TTFT anatomy
                    _trace.async_begin(
                        "prefill", req.rid,
                        {"kind": "swap_restore",
                         "resume_tokens": len(req.seq_tokens)})
                continue  # not part of any prefill group
            # reserve only what PREFILL writes (the resume prefix); decode
            # pages are allocated as the sequence grows, with preemption
            # under pressure — block-table growth semantics of the
            # reference's block_multi_head_attention serving path (vs the
            # r4 worst-case prompt+max_new reservation that capped batch
            # width at a fraction of pool capacity). With the prefix
            # cache on, pages holding an already-computed prefix of this
            # prompt are REUSED (read-only) and only the tail is
            # reserved + prefilled.
            shared = self._match_prefix(req.seq_tokens)
            need = ((len(req.seq_tokens) + self.page - 1) // self.page
                    - len(shared))
            if self.enable_prefix_cache:
                # PIN the matched pages before any eviction runs: a ref-0
                # free-but-cached prefix page is otherwise a legal FIFO
                # eviction victim, and reclaiming it here would alias one
                # physical page into prefix-read and tail-write roles
                for pg in shared:
                    self._page_ref[pg] = self._page_ref.get(pg, 0) + 1
                if not self._free_pages_for(need):
                    for pg in shared:  # unpin; retry next tick
                        self._page_ref[pg] -= 1
                    break  # head-of-line waits for pages
            elif need > self.pool.available:
                break  # head-of-line waits for pages
            self._waiting.popleft()
            if self.enable_prefix_cache:
                req.pages = shared + self._alloc_ref(need)
                if shared:
                    req.prefill_pos = max(req.prefill_pos,
                                          len(shared) * self.page)
                    self.prefix_cache_hits += len(shared)
                    self.prefix_tokens_skipped += len(shared) * self.page
            else:
                req.pages = self.pool.alloc(need)
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            self._slots[i] = req
            _ADMISSIONS.inc(labels=("prefill",))
            _trace.async_end("queue", req.rid)
            _trace.async_instant("admitted", req.rid, {"kind": "prefill"})
            if req.first_token_t is None:
                _trace.async_begin(
                    "prefill", req.rid,
                    {"resume_tokens": len(req.seq_tokens)})
            group.append(req)
        if not group:
            return
        if self.prefill_chunk is None:
            with _trace.span("prefill_group",
                             attrs={"requests": len(group)}, cat="serve"):
                first = self._prefill_group(group)
            for req, tok in zip(group, first):
                self._emit(req, tok)
        # chunked mode: KV fills incrementally in step()

    def _prefill_chunk_step(self, weights, ids, pos0, nvalid, tok_pages,
                            offs, hist, kc, vc):
        """ONE jitted fixed-shape chunk pass over ALL prefilling slots:
        ids [B, c] chunk tokens (zero-padded), pos0 [B] absolute start,
        nvalid [B] real tokens this chunk, tok_pages/offs [B, c] scatter
        targets (padded rows -> the scratch page), hist [B, pages_per_seq]
        page tables. Returns (final-normed last-valid hidden [B, H],
        new kc, new vc). Shapes are engine constants (max_slots x
        prefill_chunk x pages_per_seq), so this compiles ONCE."""
        jax, jnp = self._jax, self._jnp
        from ..models.gpt import _rms_pure

        B, c = ids.shape
        S = self.pages_per_seq * self.page
        scale = 1.0 / math.sqrt(self.hd)
        rep = self.cfg.num_heads // self.hkv
        x = weights["embed"][ids]                            # [B, c, H]
        row_pos = pos0[:, None] + jnp.arange(c)[None, :]     # [B, c]
        cols = jnp.arange(S)
        # chunk rows attend to [cached prefix + own chunk] causally
        mask = cols[None, None, :] <= row_pos[:, :, None]    # [B, c, S]
        tp = tok_pages.reshape(-1)
        of = offs.reshape(-1)

        def attend(li, q, k, v):
            # write the chunk's kv FIRST, then gather the prefix back
            # (one source of truth for the attention operands)
            kv = jnp.swapaxes(k.reshape(B * c, self.hkv, self.hd), 0, 1)
            vv = jnp.swapaxes(v.reshape(B * c, self.hkv, self.hd), 0, 1)
            kc[li] = kc[li].at[:, tp, of, :].set(kv.astype(kc[li].dtype))
            vc[li] = vc[li].at[:, tp, of, :].set(vv.astype(vc[li].dtype))
            ck = kc[li][:, hist].reshape(self.hkv, B, S, self.hd)
            cv = vc[li][:, hist].reshape(self.hkv, B, S, self.hd)
            if rep > 1:
                ck = jnp.repeat(ck, rep, 0)
                cv = jnp.repeat(cv, rep, 0)
            logits = jnp.einsum("bchd,hbsd->bhcs",
                                (q * scale).astype(jnp.float32),
                                ck.astype(jnp.float32))
            logits = jnp.where(mask[:, None], logits, -1e30)
            probs = jax.nn.softmax(logits, -1)
            o = jnp.einsum("bhcs,hbsd->bchd", probs,
                           cv.astype(jnp.float32))
            return o.astype(q.dtype)                     # [B, c, Hq, D]

        for li, lp in enumerate(weights["layers"]):
            x = self._layer_forward(li, lp, x, pos0, attend)
        last_rows = jnp.clip(nvalid - 1, 0, c - 1)
        last = x[jnp.arange(B), last_rows]                   # [B, H]
        return _rms_pure(last, weights["fnorm"]), kc, vc

    def _prefill_tick(self):
        """Chunked prefill: advance EVERY prefilling slot by up to
        `prefill_chunk` prompt tokens in one jitted batched pass, so
        running requests keep decoding every tick while long prompts fill
        incrementally (the reference serving stack's chunked-prefill /
        mixed-batch scheduling over block_multihead_attention; r3's
        eager per-request loop paid the per-dispatch host cost per layer
        per request)."""
        jnp = self._jnp
        reqs = [r for r in self._slots
                if r is not None and r.prefill_pos < len(r.seq_tokens)]
        if not reqs:
            return
        B, c = self.max_slots, self.prefill_chunk
        ids_np = np.zeros((B, c), np.int32)
        pos0 = np.zeros(B, np.int32)
        nvalid = np.zeros(B, np.int32)
        tok_pages = np.full((B, c), self._trash_page, np.int32)
        offs = np.zeros((B, c), np.int32)
        hist = np.zeros((B, self.pages_per_seq), np.int32)
        for i, r in enumerate(reqs):
            pos = r.prefill_pos
            n = min(c, len(r.seq_tokens) - pos)
            ids_np[i, :n] = r.seq_tokens[pos:pos + n]
            pos0[i], nvalid[i] = pos, n
            pages = np.asarray(r.pages, np.int64)
            ap = np.arange(pos, pos + n)
            tok_pages[i, :n] = pages[ap // self.page]
            offs[i, :n] = ap % self.page
            hist[i, :len(r.pages)] = r.pages[:self.pages_per_seq]
        last, self.kc, self.vc = self._prefill_jit(
            self._weights, jnp.asarray(ids_np), jnp.asarray(pos0),
            jnp.asarray(nvalid), jnp.asarray(tok_pages), jnp.asarray(offs),
            jnp.asarray(hist), list(self.kc), list(self.vc))
        self.prefill_chunk_steps += 1
        completed = []
        for i, r in enumerate(reqs):
            r.prefill_pos += int(nvalid[i])
            if r.prefill_pos == len(r.seq_tokens):
                completed.append((i, r))
        if completed:
            rows = last[jnp.asarray([i for i, _ in completed])]
            toks = self._head_tokens(rows, [r for _, r in completed])
            for (i, r), tok in zip(completed, toks):
                self.prefills_completed += 1
                r.length = len(r.seq_tokens)
                self._emit(r, tok)

    def _swap_gather(self, kc, vc, pages):
        """Stack every layer's rows for `pages` -> [L, Hkv, P, page, D]
        (P = pages_per_seq, trash-padded). One jitted dispatch per
        swap-out, then a single host transfer."""
        jnp = self._jnp
        k = jnp.stack([c[:, pages] for c in kc])
        v = jnp.stack([c[:, pages] for c in vc])
        return k, v

    def _swap_scatter(self, kc, vc, pages, k, v):
        """Scatter a host snapshot back into the caches at `pages`
        (trash-padded rows land in the scratch page — harmless by
        definition). Donates kc/vc."""
        kc = [c.at[:, pages].set(k[li]) for li, c in enumerate(kc)]
        vc = [c.at[:, pages].set(v[li]) for li, c in enumerate(vc)]
        return kc, vc

    def _padded_page_vec(self, pages):
        pad = np.full(self.pages_per_seq, self._trash_page, np.int32)
        pad[: len(pages)] = pages
        return self._jnp.asarray(pad)

    # -- prefix cache (content-addressed KV pages) --------------------------
    def _chain_keys(self, tokens, n_pages):
        """Chain digests of pages 0..n_pages-1: key_i =
        sha1(key_{i-1} || tokens of page i) — O(1) bytes per cache
        entry regardless of prefix depth (the vLLM block-hash-chain
        discipline; 160-bit collision space is identity in practice)."""
        import hashlib

        keys, prev = [], b""
        for i in range(n_pages):
            block = np.asarray(
                tokens[i * self.page: (i + 1) * self.page],
                np.int64).tobytes()
            prev = hashlib.sha1(prev + block).digest()
            keys.append(prev)
        return keys

    def _evictable(self):
        return [k for k, pg in self._prefix_cache.items()
                if self._page_ref.get(pg, 0) == 0]

    def _free_pages_for(self, n):
        """True if n pages can be allocated, evicting ref-0 cached pages
        (FIFO) as needed. Callers must PIN (incref) any matched shared
        pages before calling, or eviction could reclaim them."""
        while self.pool.available < n:
            victims = self._evictable()
            if not victims:
                return False
            key = victims[0]
            page = self._prefix_cache.pop(key)
            self._cached_pages.discard(page)
            self._page_ref.pop(page, None)
            self.pool.free([page])
            self.prefix_cache_evictions += 1
        return True

    def _alloc_ref(self, n):
        pages = self.pool.alloc(n)
        for pg in pages:
            self._page_ref[pg] = self._page_ref.get(pg, 0) + 1
        return pages

    def _release_pages(self, req, register):
        """Drop req's claim on its pages. Own pages whose content is a
        complete, deterministic token-prefix page are REGISTERED into the
        prefix cache (retained, lazily evictable) instead of freed; the
        rest return to the pool. Without the cache enabled this is
        exactly pool.free."""
        if not self.enable_prefix_cache:
            self.pool.free(req.pages)
            req.pages = []
            return
        register = register and req.admit_seq >= self._cache_admit_floor
        written = max(req.length, req.prefill_pos)
        full = req.prompt + req.generated
        n_complete = min(written // self.page, len(req.pages))
        keys = (self._chain_keys(full, n_complete)
                if register and n_complete else [])
        freed = []
        for i, pg in enumerate(req.pages):
            ref = self._page_ref.get(pg, 0) - 1
            if ref < 0:
                # a page released more times than it was claimed is a
                # double-release: silently clamping to zero masked the bug
                # (ADVICE r5) — count it and fail loudly
                _REF_UNDERFLOWS.inc()
                raise RuntimeError(
                    f"PagePool refcount underflow: page {pg} released by "
                    f"request {req.rid} but holds no claim — double "
                    "release (see serving_page_ref_underflows_total)")
            self._page_ref[pg] = ref
            if ref > 0:
                continue  # another live request still reads it
            if pg in self._cached_pages:
                continue  # retained by the cache (free-but-cached)
            if i < len(keys) and keys[i] not in self._prefix_cache:
                self._prefix_cache[keys[i]] = pg
                self._cached_pages.add(pg)
                continue
            freed.append(pg)
            self._page_ref.pop(pg, None)
        self.pool.free(freed)
        req.pages = []

    def _match_prefix(self, tokens):
        """Longest cached full-page chain strictly shorter than the
        prompt (>=1 token always left to prefill). Returns the shared
        page list."""
        if not self.enable_prefix_cache:
            return []
        max_pages = (len(tokens) - 1) // self.page
        shared = []
        for key in self._chain_keys(tokens, max_pages):
            pg = self._prefix_cache.get(key)
            if pg is None:
                break
            shared.append(pg)
        return shared

    def _swap_stage(self, snap_shape, dtype):
        """FRESH host staging pair per restore at the fixed
        [L, Hkv, P, page, D] scatter shape. A reused buffer is unsound:
        on backends that zero-copy host arrays into the program
        (jax CPU aliases numpy memory instead of copying at dispatch),
        overwriting the staging pair for restore N+1 races the still
        in-flight transfer of restore N. Fresh arrays make each restore's
        payload immutable for the lifetime of its dispatch; allocation
        cost is noise next to the h2d transfer itself."""
        shape = snap_shape[:2] + (self.pages_per_seq,) + snap_shape[3:]
        return (np.empty(shape, dtype), np.empty(shape, dtype))

    def _preempt(self, slot_idx):
        """Evict a running request and requeue it at the FRONT of the
        waiting queue. Policy "recompute": free the pages and fold the
        generated tokens into the resume prompt — re-admission rebuilds
        the KV by prefilling prompt+generated. Policy "swap": snapshot
        the pages to host first — re-admission restores the KV with zero
        recompute. Correctness is bitwise for greedy decodes under both
        policies (asserted by tests)."""
        r = self._slots[slot_idx]
        if self.preempt_policy == "swap" and r.pages:
            # NOTE: the gather materialises [L, Hkv, P, page, D] on device
            # before the host copy. Pool exhaustion here is a logical
            # page-budget limit, not physical HBM exhaustion, so the
            # transient is safe; a deployment sized to true HBM capacity
            # would gather layer-by-layer instead.
            k, v = self._swap_out_jit(list(self.kc), list(self.vc),
                                      self._padded_page_vec(r.pages))
            # slice to pages holding LIVE tokens device-side before the
            # host copy: the retained snapshot and the d2h transfer scale
            # with written KV, not the page reservation (a mid-prefill
            # victim's untouched prompt pages and grown-but-empty decode
            # pages never leave the device; restore re-allocates the full
            # reservation from prefill_pos/length bookkeeping)
            written = max(r.length, r.prefill_pos)
            n = min((written + self.page - 1) // self.page, len(r.pages))
            r.swapped = {"k": np.asarray(k[:, :, :n]),
                         "v": np.asarray(v[:, :, :n]),
                         "n": n, "prefill_pos": r.prefill_pos,
                         "length": r.length}
            self.swaps_out += 1
            self.pool.free(r.pages)
            r.pages = []
        else:
            # release BEFORE resetting the bookkeeping: registration
            # needs the written-token count, and caching the victim's
            # completed pages makes the recompute resume nearly free
            # (re-admission matches its own prefix)
            self._release_pages(r, register=True)
            r.seq_tokens = r.prompt + r.generated
            r.prefill_pos = 0
            r.length = 0
        self._slots[slot_idx] = None
        self._waiting.appendleft(r)
        self.preemptions += 1
        _PREEMPTIONS.inc(labels=(self.preempt_policy,))
        if r.first_token_t is None:
            _trace.async_end("prefill", r.rid, {"preempted": True})
        _trace.async_instant("preempt", r.rid,
                             {"policy": self.preempt_policy})
        _trace.async_begin("queue", r.rid, {"requeue": True})

    def _grow_pages(self):
        """Ensure every decoding slot owns pages for this tick's token.
        On pool exhaustion, preempt the YOUNGEST running request (its
        oldest peers keep their pages and finish first — guaranteed
        progress, no deadlock: a lone request always fits by the submit()
        feasibility check)."""
        while True:
            # oldest-first service order
            live = sorted(
                ((i, r) for i, r in enumerate(self._slots)
                 if r is not None and r.length > 0),
                key=lambda ir: ir[1].admit_seq)
            short = None
            for i, r in live:
                need = (r.length + 1 + self.page - 1) // self.page
                grow = need - len(r.pages)
                if grow <= 0:
                    continue
                ok = (self._free_pages_for(grow)
                      if self.enable_prefix_cache
                      else grow <= self.pool.available)
                if ok:
                    r.pages.extend(self._alloc_ref(grow)
                                   if self.enable_prefix_cache
                                   else self.pool.alloc(grow))
                else:
                    short = (i, r)
                    break
            if short is None:
                return
            # youngest victim across ALL occupied slots — a just-admitted
            # mid-prefill request is younger than any decoding one, so
            # the oldest running requests keep their pages and finish
            # first; only if the starved request IS the youngest does it
            # preempt itself (re-runs when pages free up)
            occupied = [(i, r) for i, r in enumerate(self._slots)
                        if r is not None]
            victim = max(occupied, key=lambda ir: ir[1].admit_seq)
            self._preempt(victim[0])

    def _retire(self, req: _Request):
        _REQ_LATENCY.observe(time.perf_counter() - req.submit_t)
        self._release_pages(req, register=True)
        with _trace.span("detokenize", attrs={"rid": req.rid},
                         cat="serve"):
            out = req.prompt + req.generated
        _trace.async_end("request", req.rid,
                         {"generated_tokens": len(req.generated)})
        return out

    def step(self):
        """Admit + one batched decode tick. Returns {rid: full_ids} for
        requests finishing THIS tick."""
        jax, jnp = self._jax, self._jnp
        newly = {}
        # retire FIRST: a finishing slot frees pages and a slot for this
        # very tick's admissions
        for i, r in enumerate(list(self._slots)):
            if r is not None and (
                    len(r.generated) >= self.max_new_tokens or (
                    self.eos is not None and r.generated
                    and r.generated[-1] == self.eos)):
                newly[r.rid] = self._retire(r)
                self._slots[i] = None
        with _trace.span("admission", cat="serve"):
            self._admit()
        if self.prefill_chunk is not None:
            with _trace.span("prefill_tick", cat="serve"):
                self._prefill_tick()
        self._grow_pages()
        live = [(i, r) for i, r in enumerate(self._slots)
                if r is not None and r.generated and r.length > 0]
        if _TELEMETRY_REG.enabled:
            _STEPS.inc()
            _QUEUE_DEPTH.set(len(self._waiting))
            occupied = sum(1 for s in self._slots if s is not None)
            _SLOTS_OCCUPIED.set(occupied)
            _KV_UTIL.set(1.0 - self.pool.available / self.pool.num_pages)
            if live:
                _BATCH_OCCUPANCY.observe(len(live) / self.max_slots)
        if not live:
            return newly
        # fixed-width batch: pad with slot 0's state (results discarded)
        pad_to = self.max_slots
        rows = [r for _, r in live] + [live[0][1]] * (pad_to - len(live))
        tokens = jnp.asarray([r.generated[-1] for r in rows], jnp.int32)
        lens = jnp.asarray([r.length for r in rows], jnp.int32)
        table_rows = []
        for r in rows:
            row = list(r.pages) + [0] * (self.pages_per_seq - len(r.pages))
            table_rows.append(row[: self.pages_per_seq])
        tables = jnp.asarray(np.asarray(table_rows, np.int32))
        temps = jnp.asarray([r.temperature for r in rows], jnp.float32)
        top_ks = jnp.asarray([r.top_k for r in rows], jnp.int32)
        top_ps = jnp.asarray([r.top_p for r in rows], jnp.float32)
        self._key, sub = jax.random.split(self._key)
        # static greedy/sampling mode: one retrace per mode, and the
        # default all-greedy workload never pays the vocab sort
        do_sample = any(r.temperature > 0.0 for _, r in live)
        with _trace.span("decode_tick",
                         attrs={"live": len(live)}, cat="serve"):
            nxt, self.kc, self.vc = self._decode_jit(
                self._weights, tokens, lens, tables, list(self.kc),
                list(self.vc), temps, top_ks, top_ps, sub, do_sample)
            # the host fetch is the tick's real sync point — inside the
            # span so decode wall time includes device work
            nxt = np.asarray(nxt)
        for j, (i, r) in enumerate(live):
            r.length += 1
            self._emit(r, int(nxt[j]))
        return newly

    def run_until_complete(self, max_ticks=10000):
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            if not self._waiting and all(s is None for s in self._slots):
                return done
        raise TimeoutError("serving loop did not drain")
