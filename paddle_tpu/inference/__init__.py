"""paddle.inference — the deployment engine.

Capability slot: the reference's AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.h:101; ZeroCopyRun :211):
load a *serialized* model in a fresh process, optimize, and serve
run(feeds)->fetches with zero-copy tensor handles.

TPU-native design: the artifact is a StableHLO program emitted by
``paddle.jit.save`` (jax.export — no pickled Python). "Analysis passes"
are XLA's job: the program is AOT-compiled once at load; weights live as
device-resident arrays inside the predictor, so each ``run()`` only
transfers the feeds (ZeroCopy contract).
"""
from __future__ import annotations

import enum
import os

import numpy as np


class PrecisionType(enum.Enum):
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3
    TPU = 4


class Config:
    """Predictor configuration (parity: paddle_infer.Config).

    Accepts the jit.save prefix, a model dir containing one artifact, or the
    explicit (prog_file, params_file) pair the reference takes.
    """

    def __init__(self, prog_file=None, params_file=None):
        self._prefix = None
        self._params_file = params_file
        if prog_file is not None and params_file is None and (
                os.path.isdir(prog_file)):
            cands = [f[: -len(".pdmodel")] for f in os.listdir(prog_file)
                     if f.endswith(".pdmodel")]
            if len(cands) != 1:
                raise ValueError(
                    f"model dir {prog_file!r} must hold exactly one .pdmodel")
            self._prefix = os.path.join(prog_file, cands[0])
        elif prog_file is not None:
            p = prog_file
            if p.endswith(".pdmodel"):
                p = p[: -len(".pdmodel")]
            self._prefix = p
        self._mem_optim = True
        self._ir_optim = True
        self._glog_info = True
        self._num_threads = 1

    # --- reference surface (most toggles are XLA's job; kept as records) ---
    def set_model(self, prog_file, params_file=None):
        self._prefix = prog_file[: -len(".pdmodel")] if prog_file.endswith(
            ".pdmodel") else prog_file
        self._params_file = params_file

    def model_dir(self):
        return os.path.dirname(self._prefix or "")

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or (self._prefix or "") + ".pdiparams"

    def enable_memory_optim(self, flag=True):
        self._mem_optim = flag

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def disable_glog_info(self):
        self._glog_info = False

    def set_cpu_math_library_num_threads(self, n):
        self._num_threads = n

    def enable_use_gpu(self, *a, **kw):
        pass  # device selection is jax's; the program runs where it compiled

    def disable_gpu(self):
        pass

    def use_gpu(self):
        return False

    def summary(self):
        return f"Config(prefix={self._prefix!r})"


class Tensor_:
    """Zero-copy handle (parity: ZeroCopyTensor / paddle_infer.Tensor)."""

    def __init__(self, name, predictor, is_input, index):
        self.name = name
        self._p = predictor
        self._is_input = is_input
        self._i = index

    def shape(self):
        if self._is_input:
            return list(self._p._input_avals[self._i].shape)
        out = self._p._outputs
        return list(out[self._i].shape) if out is not None else []

    def reshape(self, shape):
        pass  # shapes are fixed by the exported program

    def copy_from_cpu(self, data):
        if not self._is_input:
            raise RuntimeError("copy_from_cpu on an output handle")
        aval = self._p._input_avals[self._i]
        arr = np.asarray(data)
        want = tuple(aval.shape)
        ok = len(arr.shape) == len(want) and all(
            w < 0 or g == w for g, w in zip(arr.shape, want))
        if not ok:  # -1 marks a dynamic (symbolic) dim in the artifact
            raise ValueError(
                f"feed {self.name!r}: expected shape {want}, "
                f"got {tuple(arr.shape)}")
        self._p._feeds[self._i] = arr.astype(aval.dtype, copy=False)

    def share_external_data(self, data):
        self.copy_from_cpu(data)

    def copy_to_cpu(self):
        if self._is_input:
            raise RuntimeError("copy_to_cpu on an input handle")
        if self._p._outputs is None:
            raise RuntimeError("run() the predictor before copy_to_cpu")
        return np.asarray(self._p._outputs[self._i])


class Predictor:
    """AOT predictor over a jit.save artifact (parity: AnalysisPredictor)."""

    def __init__(self, config: Config):
        import jax

        from ..jit import load_artifact

        if isinstance(config, str):
            config = Config(config)
        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._config = config
        exported, weights, meta = load_artifact(
            config._prefix, params_file=config._params_file)
        self._exported = exported
        self._meta = meta
        class _Aval:
            def __init__(self, shape, dtype):
                self.shape, self.dtype = shape, dtype

        self._input_names = meta["input_names"]
        # dims of -1 are dynamic (symbolic in the exported program)
        self._input_avals = [
            _Aval(tuple(s["shape"]), np.dtype(s["dtype"]))
            for s in meta["inputs"]
        ]
        # weights go to device once; runs only move the feeds (ZeroCopyRun)
        self._weights = [jax.device_put(w) for w in weights]
        self._jit = jax.jit(exported.call)
        self._feeds = [None] * len(self._input_avals)
        self._outputs = None
        self._n_outputs = self._count_leaves(meta["outputs"])
        self._compiled = {}  # feed-shapes -> AOT executable

    @staticmethod
    def _count_leaves(desc):
        if desc["kind"] == "leaf":
            return 1
        if desc["kind"] == "none":
            return 0
        return sum(Predictor._count_leaves(d) for d in desc["items"])

    # --- ZeroCopy surface --------------------------------------------------
    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return [f"fetch_{i}" for i in range(self._n_outputs)]

    def get_input_handle(self, name):
        return Tensor_(name, self, True, self._input_names.index(name))

    def get_output_handle(self, name):
        return Tensor_(name, self, False, int(name.rsplit("_", 1)[1]))

    def run(self, inputs=None):
        """Execute the program. ``inputs`` (optional list of arrays, feed
        order) is the convenience form; otherwise use the input handles."""
        if inputs is not None:
            for i, a in enumerate(inputs):
                # same normalization copy_from_cpu applies (python lists feed
                # float64 otherwise, and the exported program is dtype-exact)
                self._feeds[i] = np.asarray(a).astype(
                    self._input_avals[i].dtype, copy=False)
        missing = [self._input_names[i]
                   for i, f in enumerate(self._feeds) if f is None]
        if missing:
            raise RuntimeError(f"missing feeds: {missing}")
        key = tuple(f.shape for f in self._feeds)
        if key not in self._compiled:  # AOT compile per concrete shape set
            self._compiled[key] = self._jit.lower(
                self._weights, *self._feeds).compile()
        self._outputs = self._compiled[key](self._weights, *self._feeds)
        return list(self._outputs)

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass

    def clone(self):
        """A predictor sharing this one's program, device weights, and
        compiled executables — only the feed/fetch state is fresh (the
        reference's per-thread clone contract)."""
        twin = Predictor.__new__(Predictor)
        twin.__dict__.update(self.__dict__)
        twin._feeds = [None] * len(self._input_avals)
        twin._outputs = None
        return twin


def create_predictor(config) -> Predictor:
    return Predictor(config)


# convenience aliases matching paddle_infer's module-level names
Tensor = Tensor_


class DataType(enum.Enum):
    """parity: paddle_infer DataType (ordinals match the reference)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    BOOL = 7
    FLOAT64 = 8


_NUM_BYTES = {
    DataType.FLOAT32: 4, DataType.FLOAT16: 2, DataType.INT64: 8,
    DataType.INT32: 4, DataType.UINT8: 1, DataType.INT8: 1,
    DataType.BOOL: 1, DataType.BFLOAT16: 2, DataType.FLOAT64: 8,
}


def get_num_bytes_of_data_type(dtype) -> int:
    return _NUM_BYTES[DataType(dtype) if not isinstance(dtype, DataType)
                      else dtype]


def get_version() -> str:
    from .. import version

    return f"paddle_tpu inference {version.full_version}"


def get_trt_compile_version():
    """No TensorRT on TPU — the XLA compiler fills the slot."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name: str) -> str:
    return op_name  # one compiler: op names ARE the kernel names


def _artifact_prefix(p):
    for suf in (".pdmodel", ".pdiparams", ".pdmeta.json"):
        if p.endswith(suf):
            return p[: -len(suf)]
    return p


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Rewrite a saved jit.save artifact to hold bf16 weights (the TPU
    mixed precision; parity: inference convert_to_mixed_precision).

    The program is re-exported as a wrapper that accepts bf16 weights and
    upcasts at the boundary, so the artifact halves its weight bytes (disk
    and HBM) without needing the original Python class; XLA folds the
    casts into the first consumers."""
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import export as jax_export

    from ..jit import _ARTIFACT_VERSION, load_artifact

    if black_list:
        raise NotImplementedError(
            "convert_to_mixed_precision: per-op black_list requires "
            "retracing the model; re-save with a custom dtype policy "
            "instead")
    if not keep_io_types:
        raise NotImplementedError(
            "convert_to_mixed_precision: keep_io_types=False (bf16 IO) is "
            "not supported — the wrapper upcasts weights only and the "
            "program keeps its original input/output dtypes")
    for p, suffix in ((model_file, ".pdmodel"), (params_file, ".pdiparams"),
                      (mixed_model_file, ".pdmodel"),
                      (mixed_params_file, ".pdiparams")):
        if p is not None and not str(p).endswith(suffix):
            raise ValueError(
                f"convert_to_mixed_precision: {p!r} must end with "
                f"{suffix!r} (the artifact is the .pdmodel/.pdiparams/"
                ".pdmeta.json triplet; outputs are written at exactly the "
                "paths given)")
    if mixed_precision is not None and str(mixed_precision).lower() not in (
            "precisiontype.half", "precisiontype.bfloat16", "bfloat16",
            "bf16", "float16", "fp16"):
        raise ValueError(
            f"unsupported mixed_precision {mixed_precision!r}: the TPU "
            "conversion targets bfloat16")

    src = _artifact_prefix(model_file)
    dst = _artifact_prefix(mixed_model_file)
    exported, weights, meta = load_artifact(src, params_file)

    orig_dtypes = [jnp.asarray(w).dtype for w in weights]
    keep = [not jnp.issubdtype(d, jnp.floating) for d in orig_dtypes]
    casted = [w if k else jnp.asarray(w).astype(jnp.bfloat16)
              for w, k in zip(weights, keep)]

    def wrapped(ws, *inputs):
        restored = [w if k else w.astype(d)
                    for w, k, d in zip(ws, keep, orig_dtypes)]
        return exported.call(restored, *inputs)

    n_w = len(weights)
    in_avals = list(exported.in_avals)[n_w:]
    w_avals = [jax.ShapeDtypeStruct(w.shape, w.dtype) for w in casted]
    try:
        mixed = jax_export.export(jax.jit(wrapped),
                                  platforms=("cpu", "tpu"))(w_avals, *in_avals)
    except Exception:
        mixed = jax_export.export(jax.jit(wrapped))(w_avals, *in_avals)

    os.makedirs(os.path.dirname(os.path.abspath(dst)) or ".", exist_ok=True)
    os.makedirs(os.path.dirname(os.path.abspath(
        _artifact_prefix(mixed_params_file))) or ".", exist_ok=True)
    with open(dst + ".pdmodel", "wb") as f:
        f.write(mixed.serialize())
    from ..jit import _pack_weights

    packed, params_meta = _pack_weights(
        casted, [pm["name"] for pm in meta["params"]])
    with open(_artifact_prefix(mixed_params_file) + ".pdiparams", "wb") as f:
        np.savez(f, **packed)
    new_meta = dict(meta, params=params_meta, version=_ARTIFACT_VERSION)
    with open(dst + ".pdmeta.json", "w") as f:
        json.dump(new_meta, f)
    return mixed_model_file


class PredictorPool:
    """A pool of cloned predictors (parity: paddle_infer PredictorPool —
    per-thread predictors sharing the program)."""

    def __init__(self, config: Config, size: int = 1):
        self._predictors = [Predictor(config)]
        for _ in range(size - 1):
            self._predictors.append(self._predictors[0].clone())

    def retrieve(self, idx: int) -> Predictor:
        return self._predictors[idx]


class XpuConfig:
    """Device-specific config placeholder (reference: kunlun XPU knobs;
    the TPU analogue is XLA flags, set via env)."""

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, k, v)


from .serving import (ContinuousBatchingEngine, PagePool,  # noqa: E402
                      int8_kv_enabled)
from . import fleet  # noqa: E402

__all__ = [
    "Config", "Predictor", "Tensor", "PrecisionType", "PlaceType",
    "DataType", "create_predictor", "get_version",
    "ContinuousBatchingEngine", "PagePool", "int8_kv_enabled", "fleet",
    "get_num_bytes_of_data_type", "get_trt_compile_version",
    "get_trt_runtime_version", "convert_to_mixed_precision",
    "PredictorPool", "XpuConfig", "_get_phi_kernel_name",
]
