"""Binary wire format for the multi-process fleet.

Everything that crosses a process boundary — RPC requests/replies, the
``extract()``/``inject()`` host-KV snapshots, structured terminal
outcomes — rides ONE frame format::

    magic 'PTF1' | codec u8 | payload_len u32 | crc32 u32 | payload

The payload is the same data model under two interchangeable codecs:
msgpack when the interpreter has it (the default — ext type 1 carries
ndarrays as ``dtype|shape|raw bytes``, ext type 2 preserves tuples,
which matters because int8-KV leaves are ``(codes, scales)`` tuples and
a list round-trip would break the bitwise inject contract), and a
pure-stdlib packer with the identical model as a no-dependency
fallback.  The codec byte travels in the frame header so the two ends
never have to agree out of band.

ndarrays round-trip BITWISE: int8 KV codes + per-row f32 scales arrive
exactly as extracted (the EQuARX-style quantized wire — the codes
already halve the bytes a fp16 snapshot would cost).  A truncated or
corrupt frame raises :class:`FrameError` loudly; nothing downstream
ever sees a partially-decoded snapshot.
"""

from __future__ import annotations

import struct
import time
import zlib

import numpy as np

try:
    import msgpack as _msgpack
except Exception:  # pragma: no cover - the container ships msgpack
    _msgpack = None

MAGIC = b"PTF1"
_HEADER = struct.Struct(">4sBII")          # magic, codec, len, crc32
HEADER_SIZE = _HEADER.size
MAX_FRAME = 1 << 31                        # sanity bound, not a limit

CODEC_MSGPACK = 1
CODEC_STDLIB = 2
DEFAULT_CODEC = CODEC_MSGPACK if _msgpack is not None else CODEC_STDLIB


class FrameError(ValueError):
    """A frame failed validation (truncated, bad magic, CRC mismatch,
    malformed payload).  Raised loudly instead of returning garbage."""


def available_codecs():
    return ((CODEC_MSGPACK, CODEC_STDLIB) if _msgpack is not None
            else (CODEC_STDLIB,))


# -- stdlib payload codec ----------------------------------------------------
#
# Tagged, length-prefixed, big-endian.  Tags: N/T/F none+bool, i i64,
# f f64, s str, b bytes, a ndarray, t tuple, l list, d dict.

_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")
_U32 = struct.Struct(">I")


def _std_pack_into(obj, out):
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, (int, np.integer)):
        out.append(b"i" + _I64.pack(int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.append(b"f" + _F64.pack(float(obj)))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(b"s" + _U32.pack(len(raw)) + raw)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out.append(b"b" + _U32.pack(len(raw)) + raw)
    elif isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        dt = str(a.dtype).encode("ascii")
        out.append(b"a" + _U32.pack(len(dt)) + dt + _U32.pack(a.ndim))
        for dim in a.shape:
            out.append(_U32.pack(dim))
        raw = a.tobytes()
        out.append(_U32.pack(len(raw)) + raw)
    elif isinstance(obj, tuple):
        out.append(b"t" + _U32.pack(len(obj)))
        for x in obj:
            _std_pack_into(x, out)
    elif isinstance(obj, list):
        out.append(b"l" + _U32.pack(len(obj)))
        for x in obj:
            _std_pack_into(x, out)
    elif isinstance(obj, dict):
        out.append(b"d" + _U32.pack(len(obj)))
        for k, v in obj.items():
            _std_pack_into(k, out)
            _std_pack_into(v, out)
    else:
        raise TypeError(f"wire: cannot encode {type(obj).__name__!r}")


class _StdUnpacker:
    def __init__(self, buf):
        self.buf = buf
        self.off = 0

    def _take(self, n):
        end = self.off + n
        if end > len(self.buf):
            raise FrameError("wire: truncated payload")
        chunk = self.buf[self.off:end]
        self.off = end
        return chunk

    def _u32(self):
        return _U32.unpack(self._take(4))[0]

    def unpack(self):
        tag = self._take(1)
        if tag == b"N":
            return None
        if tag == b"T":
            return True
        if tag == b"F":
            return False
        if tag == b"i":
            return _I64.unpack(self._take(8))[0]
        if tag == b"f":
            return _F64.unpack(self._take(8))[0]
        if tag == b"s":
            return self._take(self._u32()).decode("utf-8")
        if tag == b"b":
            return bytes(self._take(self._u32()))
        if tag == b"a":
            dt = np.dtype(self._take(self._u32()).decode("ascii"))
            shape = tuple(self._u32() for _ in range(self._u32()))
            raw = self._take(self._u32())
            return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
        if tag == b"t":
            return tuple(self.unpack() for _ in range(self._u32()))
        if tag == b"l":
            return [self.unpack() for _ in range(self._u32())]
        if tag == b"d":
            n = self._u32()
            return {self.unpack(): self.unpack() for _ in range(n)}
        raise FrameError(f"wire: unknown tag {tag!r}")


def _std_encode(obj):
    out = []
    _std_pack_into(obj, out)
    return b"".join(out)


def _std_decode(buf):
    up = _StdUnpacker(buf)
    obj = up.unpack()
    if up.off != len(buf):
        raise FrameError("wire: trailing bytes after payload")
    return obj


# -- msgpack payload codec ---------------------------------------------------

_EXT_NDARRAY = 1
_EXT_TUPLE = 2


def _mp_default(obj):
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        header = _std_encode([str(a.dtype), list(a.shape)])
        return _msgpack.ExtType(
            _EXT_NDARRAY, _U32.pack(len(header)) + header + a.tobytes())
    if isinstance(obj, tuple):
        return _msgpack.ExtType(_EXT_TUPLE, _mp_encode(list(obj)))
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    raise TypeError(f"wire: cannot encode {type(obj).__name__!r}")


def _mp_ext_hook(code, data):
    if code == _EXT_NDARRAY:
        hlen = _U32.unpack(data[:4])[0]
        dt, shape = _std_decode(data[4:4 + hlen])
        raw = data[4 + hlen:]
        return (np.frombuffer(raw, dtype=np.dtype(dt))
                .reshape(tuple(shape)).copy())
    if code == _EXT_TUPLE:
        return tuple(_mp_decode(data))
    raise FrameError(f"wire: unknown ext type {code}")


def _mp_encode(obj):
    # strict_types so tuples hit the default hook instead of silently
    # becoming lists (the int8 (codes, scales) leaves must stay tuples)
    return _msgpack.packb(obj, default=_mp_default, strict_types=True,
                          use_bin_type=True)


def _mp_decode(buf):
    return _msgpack.unpackb(buf, ext_hook=_mp_ext_hook, raw=False,
                            strict_map_key=False)


# -- frame layer -------------------------------------------------------------

def encode_payload(obj, codec=None):
    codec = DEFAULT_CODEC if codec is None else codec
    if codec == CODEC_MSGPACK:
        if _msgpack is None:
            raise FrameError("wire: msgpack codec unavailable")
        return _mp_encode(obj)
    if codec == CODEC_STDLIB:
        return _std_encode(obj)
    raise FrameError(f"wire: unknown codec {codec}")


def decode_payload(buf, codec):
    try:
        if codec == CODEC_MSGPACK:
            if _msgpack is None:
                raise FrameError("wire: msgpack codec unavailable")
            return _mp_decode(buf)
        if codec == CODEC_STDLIB:
            return _std_decode(buf)
    except FrameError:
        raise
    except Exception as exc:
        raise FrameError(f"wire: malformed payload ({exc!r})") from exc
    raise FrameError(f"wire: unknown codec {codec}")


def encode_frame(obj, codec=None):
    codec = DEFAULT_CODEC if codec is None else codec
    payload = encode_payload(obj, codec)
    return _HEADER.pack(MAGIC, codec, len(payload),
                        zlib.crc32(payload)) + payload


def parse_header(header):
    """Validate a 13-byte frame header -> (codec, payload_len, crc)."""
    if len(header) < HEADER_SIZE:
        raise FrameError(
            f"wire: truncated header ({len(header)}/{HEADER_SIZE} bytes)")
    magic, codec, length, crc = _HEADER.unpack(header[:HEADER_SIZE])
    if magic != MAGIC:
        raise FrameError(f"wire: bad magic {magic!r}")
    if length > MAX_FRAME:
        raise FrameError(f"wire: frame length {length} exceeds bound")
    return codec, length, crc


def decode_frame(buf):
    """Decode one complete frame from ``buf`` (exact size required)."""
    codec, length, crc = parse_header(buf)
    payload = buf[HEADER_SIZE:]
    if len(payload) != length:
        raise FrameError(
            f"wire: truncated frame ({len(payload)}/{length} payload bytes)")
    if zlib.crc32(payload) != crc:
        raise FrameError("wire: CRC mismatch (corrupt frame)")
    return decode_payload(payload, codec)


def read_frame(read_exact):
    """Read one frame via ``read_exact(n) -> bytes`` (pipe/socket)."""
    header = read_exact(HEADER_SIZE)
    codec, length, crc = parse_header(header)
    payload = read_exact(length)
    if len(payload) != length:
        raise FrameError(
            f"wire: truncated frame ({len(payload)}/{length} payload bytes)")
    if zlib.crc32(payload) != crc:
        raise FrameError("wire: CRC mismatch (corrupt frame)")
    return decode_payload(payload, codec)


# -- request serialization ---------------------------------------------------
#
# The migration payload: a live _Request (waiting or extracted-with-KV)
# shipped between replica processes.  ``on_token`` never crosses the
# wire — token streaming is the transport's event channel, and the
# receiving server re-attaches its own buffer callback on inject.
# Deadlines are engine-local perf_counter() absolutes, so they travel
# as remaining-seconds and get re-anchored on the receiving clock.

def request_to_wire(req, clock=time.perf_counter):
    d = {
        "rid": int(req.rid),
        "prompt": [int(t) for t in req.prompt],
        "generated": [int(t) for t in req.generated],
        "seq_tokens": [int(t) for t in req.seq_tokens],
        "length": int(req.length),
        "prefill_pos": int(req.prefill_pos),
        "temperature": float(req.temperature),
        "top_k": int(req.top_k),
        "top_p": float(req.top_p),
        "deadline_remaining": (None if req.deadline is None
                               else float(req.deadline - clock())),
        "swapped": None,
    }
    if req.swapped is not None:
        s = req.swapped
        d["swapped"] = {
            "k": s["k"], "v": s["v"], "n": int(s["n"]),
            "prefill_pos": int(s["prefill_pos"]),
            "length": int(s["length"]),
        }
    return d


def request_from_wire(d, clock=time.perf_counter):
    from ..serving import _Request

    req = _Request(int(d["rid"]), d["prompt"],
                   temperature=d["temperature"], top_k=d["top_k"],
                   top_p=d["top_p"])
    req.generated = [int(t) for t in d["generated"]]
    req.seq_tokens = [int(t) for t in d["seq_tokens"]]
    req.length = int(d["length"])
    req.prefill_pos = int(d["prefill_pos"])
    if d.get("deadline_remaining") is not None:
        req.deadline = clock() + float(d["deadline_remaining"])
    s = d.get("swapped")
    if s is not None:
        req.swapped = {"k": s["k"], "v": s["v"], "n": int(s["n"]),
                       "prefill_pos": int(s["prefill_pos"]),
                       "length": int(s["length"])}
    return req
