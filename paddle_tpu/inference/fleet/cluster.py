"""Elastic multi-process fleet: supervisor, child lifecycle, upgrades.

:class:`FleetSupervisor` owns a :class:`~.router.FleetRouter` whose
replicas are :class:`~.transport.RemoteEngine` proxies over real child
processes (or in-process loopback children for tests and the
``PTPU_FLEET_PROC=0`` escape hatch) and adds everything a fleet of
mortal processes needs on top of the router's dispatch machinery:

- **heartbeat leases** — every successful RPC refreshes a link's
  ``last_ok_time``; an idle link is pinged.  A child that exited, or
  whose lease aged out, is SIGKILL'd, declared dead through
  ``FleetRouter.kill_replica`` (its requests replay through the
  existing exactly-once machinery), and respawned with warmup.
- **autoscaling** — scale-up on SLO burn rates
  (``SloEngine.decision_input()``) or a raised brownout level;
  drain-then-scale-down on sustained full idleness (policy table in
  docs/SERVING.md "Process topology").
- **rolling weight upgrades** — per replica: mark draining, drain to
  the KV-migration point (``extract`` → ship over the int8-riding wire
  → ``inject`` on a peer, stream callbacks re-homed, router inflight
  reassigned), ``reload_weights`` from the model spec, re-warm,
  readmit.  One stage per fleet tick, so traffic keeps flowing on the
  peers throughout and the upgrade window is measurable — and gated
  (tools/bench_gate.py UPGRADE) at zero lost and zero duplicated
  requests.

The supervisor duck-types the router surface ``run_soak`` drives
(submit/step/replicas/outcomes/...), so every existing soak harness
runs unchanged against a fleet of real processes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

from ... import telemetry as _telemetry
from ...distributed.store import TCPStore
from ...telemetry import flight as _flight
from .overload import _OFF_SPELLINGS
from .router import RID_STRIDE, FleetRouter
from .transport import (LoopbackTransport, RemoteEngine, ReplicaServer,
                        SocketTransport, TransportError)

_ENV_PROC = "PTPU_FLEET_PROC"

_HEARTBEAT_AGE = _telemetry.gauge(
    "fleet_heartbeat_age_seconds",
    "seconds since each replica link's last successful RPC",
    labelnames=("replica",))
_LEASE_EXPIRED = _telemetry.counter(
    "fleet_lease_expired_total",
    "heartbeat leases that expired (replica declared dead)")
_RESPAWNS = _telemetry.counter(
    "fleet_respawns_total", "replica child processes respawned")
_MIGRATIONS = _telemetry.counter(
    "fleet_migrations_total",
    "live requests migrated between replicas (KV rode the wire)",
    labelnames=("reason",))
_MIGRATION_BYTES = _telemetry.counter(
    "fleet_migration_bytes_total",
    "serialized request/KV bytes shipped during migrations")
_UPGRADED = _telemetry.counter(
    "fleet_upgraded_replicas_total",
    "replicas taken through a rolling weight upgrade")
_AUTOSCALE = _telemetry.counter(
    "fleet_autoscale_total", "autoscaler actions", labelnames=("direction",))
_PROCS = _telemetry.gauge(
    "fleet_replica_procs", "live replica child processes")
_PREFIX_WARM = _telemetry.counter(
    "fleet_prefix_warm_pages_total",
    "prefix-cache pages shipped to a drain destination before retiring "
    "the source")
_LEASE_EPOCH = _telemetry.gauge(
    "fleet_lease_epoch", "current lease fencing epoch per replica",
    labelnames=("replica",))


def fleet_proc_enabled():
    """``PTPU_FLEET_PROC=0`` is the escape hatch: multi-process fleets
    fall back to the in-process simulation (bitwise-identical to the
    pre-transport behavior), no code change needed."""
    return os.environ.get(_ENV_PROC, "").strip().lower() \
        not in _OFF_SPELLINGS


class HeartbeatLost(ConnectionError):
    """A replica's heartbeat lease expired (=> transient taxonomy)."""


# ---------------------------------------------------------------------------
# Model spec (what crosses the spawn boundary)
# ---------------------------------------------------------------------------
def make_model_spec(config_kw, *, seed=0, version_seed_stride=0,
                    engine_kw=None, flight_dir=None, metrics=False):
    """A plain-JSON replica spec: the child rebuilds its own weights
    from this, deterministically.  ``version_seed_stride`` controls
    what a rolling upgrade MEANS: 0 (default) reloads bitwise-identical
    weights (seed unchanged — migration and replay stay bitwise
    provable); N != 0 derives version v's seed as
    ``seed + v * stride`` (a genuinely different checkpoint)."""
    return {
        "model": "llama",
        "config": dict(config_kw),
        "seed": int(seed),
        "version_seed_stride": int(version_seed_stride),
        "engine_kw": dict(engine_kw or {}),
        "flight_dir": flight_dir,
        "metrics": bool(metrics),
    }


def build_model_from_spec(spec, version=None):
    """Deterministic model build shared by the worker process and the
    in-process loopback children — the ONE place spec -> weights is
    defined, so a respawned child and its predecessor cannot diverge."""
    import paddle_tpu as paddle
    from ...models.llama import LlamaConfig, LlamaForCausalLM

    seed = int(spec.get("seed", 0))
    if version:
        seed += int(version) * int(spec.get("version_seed_stride", 0))
    paddle.seed(seed)
    return LlamaForCausalLM(LlamaConfig(**spec["config"]))


# ---------------------------------------------------------------------------
# Child backends
# ---------------------------------------------------------------------------
class LocalChild:
    """An in-process 'child': a live engine behind a ReplicaServer and
    a LoopbackTransport, with a fake negative pid.  The same RPC frames
    flow, so lease/respawn/autoscale/upgrade logic is testable in tier-1
    time without forking interpreters — and it IS the
    ``PTPU_FLEET_PROC=0`` fallback."""

    def __init__(self, spec, replica_id, *, transport_kw=None):
        from ..serving import ContinuousBatchingEngine

        model = build_model_from_spec(spec)
        engine = ContinuousBatchingEngine(
            model, rid_base=replica_id * RID_STRIDE,
            **spec.get("engine_kw", {}))
        self.server = ReplicaServer(
            engine, replica_id=replica_id,
            model_factory=lambda version=None:
                build_model_from_spec(spec, version=version))
        self.transport = LoopbackTransport(
            self.server, seed=replica_id, **(transport_kw or {}))
        self.pid = -(replica_id + 1)
        self.returncode = None

    def poll(self):
        return self.returncode

    def kill(self):
        """SIGKILL equivalent: the server goes dark mid-anything."""
        if self.returncode is None:
            self.returncode = -int(signal.SIGKILL)
        self.server.dead = True

    def terminate(self):
        if self.returncode is None:
            self.returncode = 0
        self.server.dead = True

    def wait(self, timeout=None):
        return self.returncode

    def close_logs(self):
        pass


class ProcChild:
    """A real worker subprocess: spawn, handshake, socket transport.
    stdout/stderr land in ``<workdir>/replica_<id>.log`` (no pipe to
    fill, and the log survives the child for forensics)."""

    HANDSHAKE = "PTPU_WORKER_READY "

    def __init__(self, spec, replica_id, *, workdir,
                 spawn_timeout=180.0, transport_kw=None):
        from ...testing.chaos import subprocess_env

        spec = dict(spec, replica_id=replica_id)
        os.makedirs(workdir, exist_ok=True)
        self.log_path = os.path.join(workdir, f"replica_{replica_id}.log")
        self._log = open(self.log_path, "ab", buffering=0)
        spec_path = os.path.join(workdir, f"replica_{replica_id}.spec.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.inference.fleet.worker",
             "--spec-file", spec_path],
            stdout=subprocess.PIPE, stderr=self._log,
            env=subprocess_env(), cwd=os.getcwd())
        self.pid = self.proc.pid
        info = self._handshake(spawn_timeout)
        self.port = info["port"]
        self.scrape_port = info.get("scrape_port")
        # past the handshake, stdout is quiet; route the fd into the
        # log file and stop reading the pipe
        self.proc.stdout.close()
        self.transport = SocketTransport(
            "127.0.0.1", self.port, seed=replica_id,
            **(transport_kw or {}))

    def _handshake(self, timeout):
        import select

        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not select.select(
                    [self.proc.stdout], [], [], max(remaining, 0.0))[0]:
                self.proc.kill()
                raise TransportError(
                    f"worker pid {self.pid}: no handshake in {timeout}s "
                    f"(log: {self.log_path})")
            line = self.proc.stdout.readline()
            if not line:
                rc = self.proc.wait()
                raise TransportError(
                    f"worker pid {self.pid} exited {rc} before handshake "
                    f"(log: {self.log_path})")
            text = line.decode("utf-8", "replace")
            self._log.write(line)
            if text.startswith(self.HANDSHAKE):
                return json.loads(text[len(self.HANDSHAKE):])

    def poll(self):
        return self.proc.poll()

    def kill(self):
        try:
            self.proc.kill()          # SIGKILL
        except OSError:
            pass

    def terminate(self):
        try:
            self.proc.terminate()     # SIGTERM (flight bundle path)
        except OSError:
            pass

    def wait(self, timeout=None):
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def close_logs(self):
        try:
            self._log.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Autoscaler
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class AutoscaleConfig:
    """Scale policy (docs/SERVING.md "Process topology" policy table).
    Scale-up triggers on overload SIGNALS (burn rate / brownout), not
    raw queue depth — the same signals the admission controller and
    brownout ladder act on, so the three never fight.  Scale-down waits
    for sustained FULL idleness and drains before stopping."""

    min_replicas: int = 1
    max_replicas: int = 8
    up_fast_burn: float = 1.0     # any objective's fast burn >= this
    up_brownout_level: int = 1    # brownout at/above this level
    idle_ticks_down: int = 64     # fully-idle ticks before draining one
    cooldown_ticks: int = 16      # min ticks between actions


class Autoscaler:
    def __init__(self, cfg=None):
        self.cfg = cfg or AutoscaleConfig()
        self.idle_ticks = 0
        self.last_action_tick = None
        self.decisions = []           # (tick, direction, reason)

    def decide(self, tick, n_replicas, *, decision_input=None,
               brownout_level=0, idle=False):
        """-> ("up"|"down"|None, reason)."""
        cfg = self.cfg
        self.idle_ticks = self.idle_ticks + 1 if idle else 0
        if (self.last_action_tick is not None
                and tick - self.last_action_tick < cfg.cooldown_ticks):
            return None, "cooldown"
        if n_replicas < cfg.max_replicas:
            if brownout_level >= cfg.up_brownout_level:
                return self._act(tick, "up",
                                 f"brownout_level={brownout_level}")
            for obj in (decision_input or {}).values():
                burn = obj.get("fast_burn") or 0.0
                if burn >= cfg.up_fast_burn:
                    return self._act(tick, "up", f"fast_burn={burn:.2f}")
        if (n_replicas > cfg.min_replicas
                and self.idle_ticks >= cfg.idle_ticks_down):
            return self._act(tick, "down",
                             f"idle_ticks={self.idle_ticks}")
        return None, None

    def _act(self, tick, direction, reason):
        self.last_action_tick = tick
        self.idle_ticks = 0
        self.decisions.append((tick, direction, reason))
        _AUTOSCALE.inc(labels=(direction,))
        return direction, reason


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------
class FleetSupervisor:
    """Child lifecycle + routing for an elastic multi-process fleet.

    Drives the :class:`FleetRouter` it owns and duck-types its surface,
    so ``run_soak`` and the bench harnesses treat a fleet of real
    processes exactly like the in-process simulation.  Each
    ``step()``: lease check -> upgrade stage -> autoscale -> concurrent
    step fan-out (``prestep``) -> router tick."""

    def __init__(self, spec, n_replicas, *, proc=True,
                 policy="least_loaded", overload=None,
                 max_queue_depth=None, lease_seconds=30.0,
                 heartbeat_every=2.0, workdir=None, transport_kw=None,
                 chaos=None, autoscale=None, max_respawns=8,
                 respawn=True, warmup_new=True, hosts=None, store=None,
                 host_lease_seconds=2.0, push=None):
        self.spec = dict(spec)
        # PTPU_FLEET_PROC=0 forces the in-process loopback children
        # everywhere, no code change — the bitwise escape hatch
        self.proc = bool(proc) and fleet_proc_enabled()
        self.lease_seconds = float(lease_seconds)
        self.heartbeat_every = float(heartbeat_every)
        self.workdir = workdir or tempfile.mkdtemp(prefix="ptpu_fleet_")
        self.transport_kw = dict(transport_kw or {})
        self._chaos = chaos or {}     # ordinal -> wrap(transport) factory
        self.autoscaler = (Autoscaler(autoscale)
                           if isinstance(autoscale, AutoscaleConfig)
                           else autoscale)
        self.max_respawns = int(max_respawns)
        self.respawn = bool(respawn)
        self.warmup_new = bool(warmup_new)
        self.children = {}            # router idx -> child
        self.tick = 0
        self.respawns = 0
        self.lease_deaths = 0
        self.migrated_requests = 0
        self.migration_bytes = 0
        self._next_ordinal = 0
        self._reaped = set()          # dead idxs the supervisor handled
        self._upgrade = None
        self.upgrades = []            # completed upgrade summaries
        self._slo_engine = None
        # cross-host topology (fleet.hosts): agents, host leases, fenced
        # epochs.  hosts=None (or PTPU_FLEET_HOSTS=0) keeps the PR 18
        # single-host spawn path bitwise.
        self.n_target = int(n_replicas)
        self.host_lease_seconds = float(host_lease_seconds)
        self.host_handles = {}        # host_id -> hosts.HostHandle
        self.store = store
        self._own_store = False
        self._hosts_mod = None
        self.directory = None
        self._epoch_counter = 0
        self._want_respawn = 0        # respawns deferred: no live host
        self.host_severs = 0
        self.host_heals = 0
        self.adopted_workers = 0
        self.rescued = 0
        self.rebalanced = 0
        self.prefix_warm_pages = 0
        n_hosts = int(hosts) if hosts else 0
        if n_hosts:
            from . import hosts as _hosts_mod

            if not _hosts_mod.fleet_hosts_enabled():
                n_hosts = 0           # single-host escape hatch
            else:
                self._hosts_mod = _hosts_mod
                self._init_hosts(n_hosts)
        # push token streaming: default-on across hosts (that is where
        # TTFT is quantized by the supervisor tick), PTPU_PUSH_STREAM
        # overrides either way
        raw = os.environ.get("PTPU_PUSH_STREAM", "").strip().lower()
        if raw:
            self._push = raw not in _OFF_SPELLINGS
        else:
            self._push = bool(self.host_handles) if push is None \
                else bool(push)
        engines = []
        spawned = []
        for _ in range(n_replicas):
            child, engine = self._spawn()
            spawned.append(child)
            engines.append(engine)
        self.router = FleetRouter(engines, policy=policy,
                                  max_queue_depth=max_queue_depth,
                                  overload=overload)
        for idx, child in enumerate(spawned):
            self._register_child(idx, child)
        if self.host_handles:
            self.router.shed_rescue = self._rescue_shed
        _PROCS.set(float(len(self.children)))

    def _init_hosts(self, n_hosts):
        """Start ``n_hosts`` agents, then DISCOVER them back through the
        store (the rendezvous contract: the supervisor reads records the
        agents wrote, it is never configured with addresses)."""
        mod = self._hosts_mod
        if self.store is None:
            self.store = TCPStore(is_master=True)
            self._own_store = True
        self.directory = mod.HostDirectory(self.store)
        for i in range(n_hosts):
            host_id = f"host{i}"
            if self.proc:
                handle = mod.spawn_proc_agent(
                    self.spec, host_id, self.directory, store=self.store,
                    workdir=self.workdir,
                    transport_kw=self.transport_kw)
            else:
                handle = mod.spawn_local_agent(
                    self.spec, host_id, self.directory,
                    transport_kw=self.transport_kw)
            self.host_handles[host_id] = handle
        # rendezvous: every agent's record must be readable back
        self.directory.wait_hosts(n_hosts)
        self._set_host_gauge()

    def _set_host_gauge(self):
        if not self._telemetry_on():
            return
        alive = sum(1 for h in self.host_handles.values()
                    if h.state == "alive")
        self._hosts_mod._HOSTS.set(float(alive), labels=("alive",))
        self._hosts_mod._HOSTS.set(
            float(len(self.host_handles) - alive), labels=("severed",))

    @staticmethod
    def _telemetry_on():
        return _telemetry.get_registry().enabled

    # -- spawning -----------------------------------------------------------
    def _next_epoch(self):
        """Monotone fencing token: every (re)lease of a replica gets a
        strictly higher epoch, stamped into every frame its transport
        sends.  A frame from an older lease is rejected server-side
        (StaleLease) and a reply made under an older lease is dropped
        client-side — split-brain safety by construction."""
        self._epoch_counter += 1
        return self._epoch_counter

    def _pick_host(self):
        """Placement: fewest placed replicas among live hosts (spread
        across failure domains), ordinal-tie-broken for determinism.
        None when every host is severed."""
        alive = [h for h in self.host_handles.values()
                 if h.state == "alive"]
        if not alive:
            return None
        return min(alive,
                   key=lambda h: (len(h.replicas) + h.pending, h.ordinal))

    def _spawn(self):
        ordinal = self._next_ordinal
        self._next_ordinal += 1
        if self.host_handles:
            host = self._pick_host()
            if host is None:
                raise TransportError("no live host to place a replica on")
            child = self._hosts_mod.spawn_on_host(
                host, self.spec, ordinal, transport_kw=self.transport_kw)
            host.pending += 1
        elif self.proc:
            child = ProcChild(self.spec, ordinal, workdir=self.workdir,
                              transport_kw=self.transport_kw)
        else:
            child = LocalChild(self.spec, ordinal,
                               transport_kw=self.transport_kw)
        wrap = self._chaos.get(ordinal)
        if wrap is not None:
            child.transport = wrap(child.transport)
        if self.host_handles:
            # fence the lease BEFORE first contact: the hello frame
            # already carries the new epoch
            child.transport.epoch = self._next_epoch()
        engine = RemoteEngine(child.transport)
        if self._push:
            engine.enable_push()
        return child, engine

    def _register_child(self, idx, child):
        """Router-index bookkeeping shared by initial spawn, respawn,
        and heal adoption: child table, host membership, epoch gauge."""
        self.children[idx] = child
        host_id = getattr(child, "host_id", None)
        if host_id is not None:
            self.router.replicas[idx].host = host_id
            host = self.host_handles.get(host_id)
            if host is not None:
                host.replicas.add(idx)
                host.pending = max(0, host.pending - 1)
        if self._telemetry_on():
            _LEASE_EPOCH.set(
                float(getattr(child.transport, "epoch", 0) or 0),
                labels=(str(idx),))

    def _spawn_replacement(self):
        try:
            child, engine = self._spawn()
        except TransportError:
            if not self.host_handles:
                raise
            # every host is severed (or the picked one died mid-spawn):
            # defer — _host_tick respawns as soon as a host is live
            self._want_respawn += 1
            return None
        if self.warmup_new:
            engine.warmup()
        idx = self.router.add_replica(engine)
        self._register_child(idx, child)
        self.respawns += 1
        _RESPAWNS.inc()
        _PROCS.set(float(self._live_children()))
        return idx

    def _live_children(self):
        return sum(1 for idx, c in self.children.items()
                   if c.poll() is None
                   and not self.router.replicas[idx].retired)

    # -- router duck-type surface -------------------------------------------
    @property
    def replicas(self):
        return self.router.replicas

    @property
    def overload(self):
        return self.router.overload

    @property
    def cancelled(self):
        return self.router.cancelled

    @property
    def shed(self):
        return self.router.shed

    @property
    def requeues(self):
        return self.router.requeues

    @property
    def served(self):
        return self.router.served

    @property
    def _pending(self):
        return self.router._pending

    @property
    def _inflight(self):
        return self.router._inflight

    @property
    def _policy_name(self):
        return self.router._policy_name

    def submit(self, prompt, **kw):
        # reap already-exited children BEFORE admission: poll() is one
        # WNOHANG waitpid, and catching the corpse here (full forensics
        # + respawn) beats the router's dispatch-time safety net, which
        # only sees an opaque transport fault
        self._reap_exited()
        return self.router.submit(prompt, **kw)

    def _reap_exited(self):
        for idx, child in list(self.children.items()):
            handle = self.router.replicas[idx]
            if (handle.healthy and not handle.retired
                    and child.poll() is not None):
                age = (time.monotonic()
                       - handle.engine.transport.last_ok_time)
                self._declare_dead(idx, child, child.poll(), age)

    def cancel(self, rid, reason="client"):
        return self.router.cancel(rid, reason=reason)

    def outcomes(self):
        return self.router.outcomes()

    def load(self):
        out = self.router.load()
        out["procs"] = self._live_children()
        out["respawns"] = self.respawns
        return out

    def drained(self):
        return self.router.drained()

    def run_until_complete(self, max_ticks=100000):
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            if self.drained() and self._upgrade is None:
                return done
        raise TimeoutError("fleet did not drain")

    def attach_slo(self, slo_engine):
        """run_soak hands the live SLO engine over so the autoscaler
        can read decision_input() burn rates."""
        self._slo_engine = slo_engine

    # -- the fleet tick -----------------------------------------------------
    def step(self):
        self.tick += 1
        if self.host_handles:
            self._host_tick()
        self._lease_tick()
        self._upgrade_tick()
        self._autoscale_tick()
        if self.host_handles:
            self._rebalance_tick()
        self._prestep()
        return self.router.step()

    def _routable(self, handle):
        if not handle.healthy or handle.retired:
            return False
        ov = self.router.overload
        if ov is not None and ov.breakers[handle.idx].poll() == "open":
            return False
        return True

    def _prestep(self):
        """Fan the step RPC out to every routable replica BEFORE the
        router's sequential collection pass: child processes decode
        concurrently on real wall clock.  An uncollected prestep is
        self-healing — ``RemoteEngine.step`` collects the outstanding
        call instead of double-sending."""
        for handle in self.router.replicas:
            if self._routable(handle):
                try:
                    handle.engine.prestep()
                except Exception:     # collection will classify it
                    pass

    # -- heartbeat leases ---------------------------------------------------
    def _lease_tick(self):
        now = time.monotonic()
        registry_on = _telemetry.get_registry().enabled
        for idx, child in list(self.children.items()):
            handle = self.router.replicas[idx]
            if not handle.healthy or handle.retired:
                if (not handle.healthy and not handle.retired
                        and idx not in self._reaped):
                    # the router declared this replica dead on its own
                    # (a dispatch-/step-time transport fault beat the
                    # lease check) — the supervisor still owns the
                    # corpse: reap the child and respawn
                    self._reaped.add(idx)
                    child.kill()
                    child.wait(timeout=10.0)
                    _PROCS.set(float(self._live_children()))
                    if self.respawn and self.respawns < self.max_respawns:
                        self._spawn_replacement()
                continue
            exit_code = child.poll()
            age = now - handle.engine.transport.last_ok_time
            if registry_on:
                _HEARTBEAT_AGE.set(age, labels=(str(idx),))
            if exit_code is None and age > self.heartbeat_every:
                try:
                    handle.engine.ping(timeout=self.heartbeat_every)
                    age = 0.0
                except Exception:
                    age = now - handle.engine.transport.last_ok_time
            if exit_code is not None or age > self.lease_seconds:
                self._declare_dead(idx, child, exit_code, age)

    def _declare_dead(self, idx, child, exit_code, age):
        """Missed lease or exited child: SIGKILL (idempotent), declare
        dead through the router (requests replay exactly-once), record
        the forensics, respawn."""
        self._reaped.add(idx)
        child.kill()
        child.wait(timeout=10.0)
        self.lease_deaths += 1
        _LEASE_EXPIRED.inc()
        reason = (f"heartbeat lease expired ({age:.1f}s"
                  f" > {self.lease_seconds}s)"
                  if exit_code is None
                  else f"child exited with code {exit_code}")
        self.router.kill_replica(
            idx, HeartbeatLost(reason), raise_if_empty=False,
            context={"exit_code": child.poll(),
                     "heartbeat_age": round(age, 3),
                     "pid": child.pid,
                     "supervisor": True})
        _PROCS.set(float(self._live_children()))
        host = self.host_handles.get(
            getattr(self.children.get(idx), "host_id", None))
        if host is not None:
            host.replicas.discard(idx)
        if self.respawn and self.respawns < self.max_respawns:
            self._spawn_replacement()

    # -- host leases (cross-host topology) ----------------------------------
    def sever_host(self, host_id):
        """Chaos seam: partition ``host_id`` away from the supervisor
        (links drop, heartbeats stop reaching the store).  Detection and
        fencing still run through :meth:`_host_tick` — nothing here
        touches fleet state directly."""
        self.host_handles[host_id].sever()

    def heal_host(self, host_id):
        self.host_handles[host_id].heal()

    def _host_tick(self):
        """Host-lease check: a host is live while its heartbeat counter
        ADVANCES (monotone store counter, never a wall-clock timestamp)
        or its agent answers a direct ping.  Both silent past
        ``host_lease_seconds`` => severed: fence + replay every replica
        it held, fleet-wide, in one tick.  A severed host whose beats
        resume AND whose agent answers again is healed: its surviving
        workers are re-leased at a higher epoch (they self-quarantine on
        first contact) and adopted back or retired."""
        now = time.monotonic()
        for host in self.host_handles.values():
            advanced = False
            try:
                beats = self.directory.beats(host.ordinal)
                if beats > host.last_beats:
                    host.last_beats = beats
                    advanced = True
            except Exception:         # noqa: BLE001
                pass                  # store unreachable from HERE
            if not advanced:
                # stalled counter: confirm over the direct agent link
                try:
                    host.client.ping(timeout=1.0)
                    advanced = True
                except Exception:     # noqa: BLE001
                    pass
            if advanced:
                host.last_advance = now
                if host.state == "severed":
                    self._host_healed(host)
            elif host.state == "alive" \
                    and now - host.last_advance >= self.host_lease_seconds:
                self._host_severed(host)
        while self._want_respawn > 0 and self.respawn \
                and self.respawns < self.max_respawns \
                and self._pick_host() is not None:
            self._want_respawn -= 1
            self._spawn_replacement()
        self._set_host_gauge()

    def _host_severed(self, host):
        """One lost host, one tick: every replica on it is fenced to a
        dead lease (its epoch can never be stamped again) and declared
        dead through the router, so all its requests replay elsewhere
        through the existing exactly-once machinery."""
        host.state = "severed"
        self.host_severs += 1
        self._hosts_mod._SEVERED.inc()
        _flight.maybe_dump("host_severed", {
            "host": host.host_id, "ordinal": host.ordinal,
            "replicas": sorted(host.replicas)})
        for idx in sorted(host.replicas):
            handle = self.router.replicas[idx]
            if not handle.healthy or handle.retired:
                continue
            self._reaped.add(idx)
            child = self.children.get(idx)
            if child is not None:
                child.kill()          # best-effort; the epoch fences it
            self.router.kill_replica(
                idx, self._hosts_mod.HostLost(
                    f"host {host.host_id} severed"),
                raise_if_empty=False,
                context={"host": host.host_id, "supervisor": True})
            if self.respawn and self.respawns < self.max_respawns:
                self._spawn_replacement()
        host.replicas.clear()
        _PROCS.set(float(self._live_children()))

    def _host_healed(self, host):
        """The partition healed.  Surviving workers are stranded at
        their old (dead) epoch: re-contacting them with a freshly minted
        higher epoch quarantines them first (all old-lease work is
        cancelled server-side, never surfaced), then they rejoin the
        fleet if it is below target size — otherwise they are retired
        via the agent."""
        host.state = "alive"
        self.host_heals += 1
        self._hosts_mod._HEALED.inc()
        try:
            survivors = host.client.list_workers()["workers"]
        except Exception:             # noqa: BLE001
            host.state = "severed"    # not actually reachable yet
            return
        _flight.maybe_dump("host_healed", {
            "host": host.host_id, "survivors": sorted(survivors)})
        for wid in sorted(survivors, key=int):
            winfo = survivors[wid]
            if not winfo.get("alive", True):
                continue
            n_live = sum(1 for h in self.router.replicas
                         if h.healthy and not h.retired)
            if n_live >= self.n_target:
                try:
                    host.client.kill_worker(int(wid))
                except Exception:     # noqa: BLE001
                    pass
                continue
            try:
                idx = self._adopt_worker(host, int(wid), winfo)
            except Exception:         # noqa: BLE001
                try:
                    host.client.kill_worker(int(wid))
                except Exception:     # noqa: BLE001
                    pass
                continue
            self.adopted_workers += 1
            self._hosts_mod._ADOPTED.inc()
            _flight.maybe_dump("worker_adopted", {
                "host": host.host_id, "worker": int(wid),
                "replica": idx})

    def _adopt_worker(self, host, wid, winfo):
        """Open a fresh partition-gated link to a healed host's
        surviving worker at a freshly minted epoch (the hello frame
        quarantines it) and add it to the fleet."""
        from ...testing.chaos import PartitionedLink

        mod = self._hosts_mod
        if host.agent is not None:
            raw = host.agent.worker_transport(wid, seed=wid,
                                              **self.transport_kw)
        else:
            raw = SocketTransport(host.record.get("address", "127.0.0.1"),
                                  winfo["port"], seed=wid,
                                  **self.transport_kw)
        link = PartitionedLink(raw)
        host.links.append(link)
        link.epoch = self._next_epoch()
        engine = RemoteEngine(link)   # hello at the new epoch: quarantine
        if self._push:
            engine.enable_push()
        if self.warmup_new:
            engine.warmup()
        idx = self.router.add_replica(engine)
        child = mod.HostedChild(host, wid, winfo, link)
        self._register_child(idx, child)
        _PROCS.set(float(self._live_children()))
        return idx

    # -- shedding-becomes-migration + queue rebalance -----------------------
    def _rescue_shed(self, entry, reason):
        """Installed as ``router.shed_rescue`` on cross-host fleets:
        before the overload ladder sheds a queued request, look for a
        replica with REAL headroom (under half its queue cap, on a live
        host) — overflow-priced, so a rescue can never itself create the
        overload it is escaping.  True => the request was dispatched
        there instead of shed."""
        best, best_key = None, None
        for h in self.router.replicas:
            if not self._routable(h) or h.draining:
                continue
            if h.host is not None \
                    and self.host_handles.get(h.host) is not None \
                    and self.host_handles[h.host].state != "alive":
                continue
            load = h.engine.load()
            if 2 * load["queue_depth"] >= self.router.max_queue_depth:
                continue              # headroom, not merely room
            key = (load["queue_depth"] + 0.5 * load["occupied_slots"]
                   + (1.0 - load["kv_free_fraction"]), h.idx)
            if best_key is None or key < best_key:
                best, best_key = h, key
        if best is None:
            return False
        if not self.router.dispatch_to(entry, best.idx):
            return False
        _MIGRATIONS.inc(labels=("shed_rescue",))
        return True

    def _rebalance_tick(self):
        """Steal-based queue rebalance across hosts: when one replica is
        at its queue cap while a replica on ANOTHER host has meaningful
        headroom, live-migrate queued/swapped requests (KV snapshot over
        the wire) instead of letting backpressure push the ladder toward
        shedding.  One donor->recipient batch per tick, deterministic."""
        donor, recipient = None, None
        depths = {}
        for h in self.router.replicas:
            if not self._routable(h) or h.draining:
                continue
            depths[h.idx] = h.engine.load()["queue_depth"]
        if not depths:
            return
        d_idx = max(depths, key=lambda i: (depths[i], -i))
        if depths[d_idx] < self.router.max_queue_depth:
            return                    # nobody saturated: nothing to do
        donor = self.router.replicas[d_idx]
        for h in self.router.replicas:
            if h.idx == d_idx or h.idx not in depths:
                continue
            if h.host is not None and h.host == donor.host:
                continue              # rebalance is ACROSS hosts
            if depths[h.idx] + 2 > depths[d_idx]:
                continue
            if recipient is None \
                    or depths[h.idx] < depths[recipient.idx]:
                recipient = h
        if recipient is None:
            return
        n = max(1, (depths[d_idx] - depths[recipient.idx]) // 2)
        try:
            stolen = donor.engine.steal_requests(n)
        except Exception:             # noqa: BLE001
            return
        for req in stolen:
            rid = int(req["rid"])
            try:
                recipient.engine.inject_wire(req)
            except Exception:         # noqa: BLE001
                # the request is out of the donor but not into the
                # recipient: requeue through the router (replay path)
                entry = self.router._inflight.pop(rid, None)
                if entry is not None:
                    self.router.requeues += 1
                    self.router._pending.append(
                        (rid, entry[1], entry[2], entry[3]))
                continue
            self.router.reassign(rid, recipient.idx)
            recipient.engine.adopt_stream(
                rid, donor.engine.release_stream(rid))
            nbytes = _wire_size(req)
            self.rebalanced += 1
            self.migrated_requests += 1
            self.migration_bytes += nbytes
            _MIGRATIONS.inc(labels=("rebalance",))
            _MIGRATION_BYTES.inc(nbytes)

    # -- autoscaling --------------------------------------------------------
    def _autoscale_tick(self):
        if self.autoscaler is None:
            return
        ov = self.router.overload
        brownout = ov.brownout.level if ov is not None else 0
        decision_input = (self._slo_engine.decision_input()
                          if self._slo_engine is not None else None)
        idle = (not self.router._pending and not self.router._inflight
                and all((h.engine.load()["queue_depth"] == 0
                         and h.engine.load()["occupied_slots"] == 0)
                        for h in self.router.replicas
                        if h.healthy and not h.retired))
        n_live = sum(1 for h in self.router.replicas
                     if h.healthy and not h.retired)
        direction, reason = self.autoscaler.decide(
            self.tick, n_live, decision_input=decision_input,
            brownout_level=brownout, idle=idle)
        if direction == "up":
            self._spawn_replacement()
        elif direction == "down":
            self._scale_down()

    def _scale_down(self):
        """Drain-then-stop the newest live replica.  It is marked
        draining immediately (no new dispatches) and retired on a later
        tick once empty — scale-down never sheds work."""
        for handle in reversed(self.router.replicas):
            if handle.healthy and not handle.retired \
                    and not handle.draining:
                handle.draining = True
                return

    def _retire_if_drained(self):
        for handle in self.router.replicas:
            if not (handle.draining and handle.healthy
                    and not handle.retired):
                continue
            if self._upgrade is not None \
                    and self._upgrade.get("idx") == handle.idx:
                continue              # upgrade-draining, not scale-down
            load = handle.engine.load()
            if (load["queue_depth"] == 0 and load["occupied_slots"] == 0
                    and self.router._replica_inflight(handle.idx) == 0):
                child = self.children.get(handle.idx)
                peers = [h for h in self.router.replicas
                         if h is not handle and h.healthy
                         and not h.retired and not h.draining]
                self._warm_prefix(handle, peers)
                handle.retired = True
                handle.draining = False
                if child is not None:
                    try:
                        handle.engine.shutdown()
                    except Exception:
                        pass
                    child.terminate()
                    child.wait(timeout=10.0)
                    child.close_logs()
                _PROCS.set(float(self._live_children()))

    # -- rolling upgrades ---------------------------------------------------
    def start_rolling_upgrade(self, version, *, queue=None):
        """Begin a rolling weight upgrade to ``version``.  One stage
        advances per fleet tick (drain+migrate -> reload -> warmup ->
        readmit, then the next replica), so the fleet keeps serving
        throughout; progress via :meth:`upgrade_status`."""
        if self._upgrade is not None:
            raise RuntimeError("a rolling upgrade is already in flight")
        if queue is None:
            queue = [h.idx for h in self.router.replicas
                     if h.healthy and not h.retired]
        self._upgrade = {
            "version": version, "queue": list(queue), "idx": None,
            "stage": "next", "upgraded": [], "migrated": 0,
            "migrate_bytes": 0, "started_tick": self.tick,
            "finished_tick": None,
        }
        return self._upgrade

    def upgrade_status(self):
        if self._upgrade is not None:
            return dict(self._upgrade)
        return self.upgrades[-1] if self.upgrades else None

    def _upgrade_tick(self):
        self._retire_if_drained()
        up = self._upgrade
        if up is None:
            return
        stage = up["stage"]
        if stage == "next":
            while up["queue"]:
                idx = up["queue"].pop(0)
                handle = self.router.replicas[idx]
                if handle.healthy and not handle.retired:
                    up["idx"] = idx
                    handle.draining = True
                    up["stage"] = "migrate"
                    return
            up["finished_tick"] = self.tick
            up["stage"] = "done"
            self.upgrades.append(up)
            self._upgrade = None
            return
        idx = up["idx"]
        handle = self.router.replicas[idx]
        if not handle.healthy:
            # the replica died mid-upgrade; its work already replayed
            # through kill_replica — move on
            up["stage"] = "next"
            return
        try:
            if stage == "migrate":
                self._migrate_off(handle, up)
                up["stage"] = "reload"
            elif stage == "reload":
                handle.engine.reload_weights(version=up["version"])
                up["stage"] = "warmup"
            elif stage == "warmup":
                handle.engine.warmup()
                up["stage"] = "readmit"
            elif stage == "readmit":
                handle.draining = False
                up["upgraded"].append(idx)
                _UPGRADED.inc()
                up["stage"] = "next"
        except Exception as exc:      # noqa: BLE001
            # an upgrade stage failing is a replica failure: declare it
            # dead (work replays), respawn at the NEW version via the
            # normal lease path, and continue the rollout
            self.router.kill_replica(
                idx, exc, raise_if_empty=False,
                context={"during_upgrade_stage": stage,
                         "supervisor": True})
            self._reaped.add(idx)
            child = self.children.get(idx)
            if child is not None:
                child.kill()
                child.wait(timeout=10.0)
            if self.respawn and self.respawns < self.max_respawns:
                self._spawn_replacement()
            up["stage"] = "next"

    def _migrate_off(self, handle, up):
        """Drain ``handle`` to its KV-migration point and re-home every
        request on a peer: running requests ship their host KV snapshot
        (int8 codes + scales when int8_kv — the quantized wire), stream
        callbacks move with them, and the router's inflight table is
        reassigned so completions land correctly.  With no live peer
        the requests requeue through the router instead — migration
        never loses work, it just degrades to replay."""
        data = handle.engine.drain_requests()
        reqs = list(data["running"]) + list(data["waiting"])
        peers = [h for h in self.router.replicas
                 if h is not handle and h.healthy
                 and not h.retired and not h.draining]
        self._warm_prefix(handle, peers)
        if not reqs:
            return
        if not peers:
            # single-replica fleet: hold the requests in the router and
            # let them re-dispatch (to this replica, post-upgrade)
            for req in reqs:
                rid = int(req["rid"])
                entry = self.router._inflight.pop(rid, None)
                if entry is not None:
                    self.router.requeues += 1
                    self.router._pending.append(
                        (rid, entry[1], entry[2], entry[3]))
            return
        for req in reqs:
            rid = int(req["rid"])
            peer = min(peers, key=lambda h:
                       (h.engine.load()["queue_depth"]
                        + h.engine.load()["occupied_slots"], h.idx))
            peer.engine.inject_wire(req)
            self.router.reassign(rid, peer.idx)
            peer.engine.adopt_stream(rid, handle.engine.release_stream(rid))
            nbytes = _wire_size(req)
            self.migrated_requests += 1
            self.migration_bytes += nbytes
            up["migrated"] += 1
            up["migrate_bytes"] += nbytes
            _MIGRATIONS.inc(labels=("upgrade",))
            _MIGRATION_BYTES.inc(nbytes)

    def _warm_prefix(self, handle, peers):
        """Prefix-cache-preserving drain: before ``handle`` goes away,
        copy its prefix-page registry to the least-loaded live peer so
        the fleet's cache hit-rate survives the drain.  Best-effort —
        a cold or cacheless replica simply exports nothing."""
        if not peers:
            return 0
        if not (self.host_handles
                or self.spec.get("engine_kw", {}).get(
                    "enable_prefix_cache")):
            return 0
        try:
            entries = handle.engine.export_prefix()
            if not entries:
                return 0
            peer = min(peers, key=lambda h:
                       (h.engine.load()["queue_depth"]
                        + h.engine.load()["occupied_slots"], h.idx))
            warmed = peer.engine.import_prefix(entries)
        except Exception:       # noqa: BLE001 — warming never blocks a drain
            return 0
        if warmed:
            self.prefix_warm_pages += warmed
            _PREFIX_WARM.inc(warmed)
        return warmed

    # -- shutdown -----------------------------------------------------------
    def close(self):
        for idx, child in self.children.items():
            handle = self.router.replicas[idx]
            if child.poll() is None and handle.healthy:
                try:
                    handle.engine.shutdown()
                except Exception:
                    pass
            child.terminate()
        for child in self.children.values():
            if child.wait(timeout=5.0) is None:
                child.kill()
                child.wait(timeout=5.0)
            child.close_logs()
        for handle in self.router.replicas:
            try:
                handle.engine.close()
            except Exception:
                pass
        for host in self.host_handles.values():
            if host.client is not None:
                try:
                    host.client.shutdown()
                except Exception:
                    pass
                try:
                    host.client.close()
                except Exception:
                    pass
            if host.proc_agent is not None:
                try:
                    host.proc_agent.terminate()
                    if host.proc_agent.wait(timeout=5.0) is None:
                        host.proc_agent.kill()
                        host.proc_agent.wait(timeout=5.0)
                    host.proc_agent.close_logs()
                except Exception:
                    pass
            if host.agent is not None:
                try:
                    host.agent.close()
                except Exception:
                    pass
            for pid in list(host.worker_pids):
                try:
                    os.kill(pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
        if self._own_store and self.store is not None:
            try:
                self.store.close()
            except Exception:
                pass
        _PROCS.set(0.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def summary(self):
        return {
            "procs": self._live_children(),
            "proc_backend": self.proc,
            "respawns": self.respawns,
            "lease_deaths": self.lease_deaths,
            "migrated_requests": self.migrated_requests,
            "migration_bytes": self.migration_bytes,
            "upgrades": [
                {k: u[k] for k in ("version", "upgraded", "migrated",
                                   "migrate_bytes", "started_tick",
                                   "finished_tick")}
                for u in self.upgrades],
            "autoscale": (list(self.autoscaler.decisions)
                          if self.autoscaler else []),
            "hosts": {hid: h.state
                      for hid, h in self.host_handles.items()},
            "host_severs": self.host_severs,
            "host_heals": self.host_heals,
            "adopted_workers": self.adopted_workers,
            "rescued": self.router.rescued,
            "rebalanced": self.rebalanced,
            "prefix_warm_pages": self.prefix_warm_pages,
            "lease_epoch": self._epoch_counter,
            "push": self._push,
        }


def _wire_size(obj):
    from . import wire
    return len(wire.encode_frame(obj))
