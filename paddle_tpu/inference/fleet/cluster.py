"""Elastic multi-process fleet: supervisor, child lifecycle, upgrades.

:class:`FleetSupervisor` owns a :class:`~.router.FleetRouter` whose
replicas are :class:`~.transport.RemoteEngine` proxies over real child
processes (or in-process loopback children for tests and the
``PTPU_FLEET_PROC=0`` escape hatch) and adds everything a fleet of
mortal processes needs on top of the router's dispatch machinery:

- **heartbeat leases** — every successful RPC refreshes a link's
  ``last_ok_time``; an idle link is pinged.  A child that exited, or
  whose lease aged out, is SIGKILL'd, declared dead through
  ``FleetRouter.kill_replica`` (its requests replay through the
  existing exactly-once machinery), and respawned with warmup.
- **autoscaling** — scale-up on SLO burn rates
  (``SloEngine.decision_input()``) or a raised brownout level;
  drain-then-scale-down on sustained full idleness (policy table in
  docs/SERVING.md "Process topology").
- **rolling weight upgrades** — per replica: mark draining, drain to
  the KV-migration point (``extract`` → ship over the int8-riding wire
  → ``inject`` on a peer, stream callbacks re-homed, router inflight
  reassigned), ``reload_weights`` from the model spec, re-warm,
  readmit.  One stage per fleet tick, so traffic keeps flowing on the
  peers throughout and the upgrade window is measurable — and gated
  (tools/bench_gate.py UPGRADE) at zero lost and zero duplicated
  requests.

The supervisor duck-types the router surface ``run_soak`` drives
(submit/step/replicas/outcomes/...), so every existing soak harness
runs unchanged against a fleet of real processes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

from ... import telemetry as _telemetry
from ...telemetry import flight as _flight
from .overload import _OFF_SPELLINGS
from .router import RID_STRIDE, FleetRouter
from .transport import (LoopbackTransport, RemoteEngine, ReplicaServer,
                        SocketTransport, TransportError)

_ENV_PROC = "PTPU_FLEET_PROC"

_HEARTBEAT_AGE = _telemetry.gauge(
    "fleet_heartbeat_age_seconds",
    "seconds since each replica link's last successful RPC",
    labelnames=("replica",))
_LEASE_EXPIRED = _telemetry.counter(
    "fleet_lease_expired_total",
    "heartbeat leases that expired (replica declared dead)")
_RESPAWNS = _telemetry.counter(
    "fleet_respawns_total", "replica child processes respawned")
_MIGRATIONS = _telemetry.counter(
    "fleet_migrations_total",
    "live requests migrated between replicas (KV rode the wire)")
_MIGRATION_BYTES = _telemetry.counter(
    "fleet_migration_bytes_total",
    "serialized request/KV bytes shipped during migrations")
_UPGRADED = _telemetry.counter(
    "fleet_upgraded_replicas_total",
    "replicas taken through a rolling weight upgrade")
_AUTOSCALE = _telemetry.counter(
    "fleet_autoscale_total", "autoscaler actions", labelnames=("direction",))
_PROCS = _telemetry.gauge(
    "fleet_replica_procs", "live replica child processes")


def fleet_proc_enabled():
    """``PTPU_FLEET_PROC=0`` is the escape hatch: multi-process fleets
    fall back to the in-process simulation (bitwise-identical to the
    pre-transport behavior), no code change needed."""
    return os.environ.get(_ENV_PROC, "").strip().lower() \
        not in _OFF_SPELLINGS


class HeartbeatLost(ConnectionError):
    """A replica's heartbeat lease expired (=> transient taxonomy)."""


# ---------------------------------------------------------------------------
# Model spec (what crosses the spawn boundary)
# ---------------------------------------------------------------------------
def make_model_spec(config_kw, *, seed=0, version_seed_stride=0,
                    engine_kw=None, flight_dir=None, metrics=False):
    """A plain-JSON replica spec: the child rebuilds its own weights
    from this, deterministically.  ``version_seed_stride`` controls
    what a rolling upgrade MEANS: 0 (default) reloads bitwise-identical
    weights (seed unchanged — migration and replay stay bitwise
    provable); N != 0 derives version v's seed as
    ``seed + v * stride`` (a genuinely different checkpoint)."""
    return {
        "model": "llama",
        "config": dict(config_kw),
        "seed": int(seed),
        "version_seed_stride": int(version_seed_stride),
        "engine_kw": dict(engine_kw or {}),
        "flight_dir": flight_dir,
        "metrics": bool(metrics),
    }


def build_model_from_spec(spec, version=None):
    """Deterministic model build shared by the worker process and the
    in-process loopback children — the ONE place spec -> weights is
    defined, so a respawned child and its predecessor cannot diverge."""
    import paddle_tpu as paddle
    from ...models.llama import LlamaConfig, LlamaForCausalLM

    seed = int(spec.get("seed", 0))
    if version:
        seed += int(version) * int(spec.get("version_seed_stride", 0))
    paddle.seed(seed)
    return LlamaForCausalLM(LlamaConfig(**spec["config"]))


# ---------------------------------------------------------------------------
# Child backends
# ---------------------------------------------------------------------------
class LocalChild:
    """An in-process 'child': a live engine behind a ReplicaServer and
    a LoopbackTransport, with a fake negative pid.  The same RPC frames
    flow, so lease/respawn/autoscale/upgrade logic is testable in tier-1
    time without forking interpreters — and it IS the
    ``PTPU_FLEET_PROC=0`` fallback."""

    def __init__(self, spec, replica_id, *, transport_kw=None):
        from ..serving import ContinuousBatchingEngine

        model = build_model_from_spec(spec)
        engine = ContinuousBatchingEngine(
            model, rid_base=replica_id * RID_STRIDE,
            **spec.get("engine_kw", {}))
        self.server = ReplicaServer(
            engine, replica_id=replica_id,
            model_factory=lambda version=None:
                build_model_from_spec(spec, version=version))
        self.transport = LoopbackTransport(
            self.server, seed=replica_id, **(transport_kw or {}))
        self.pid = -(replica_id + 1)
        self.returncode = None

    def poll(self):
        return self.returncode

    def kill(self):
        """SIGKILL equivalent: the server goes dark mid-anything."""
        if self.returncode is None:
            self.returncode = -int(signal.SIGKILL)
        self.server.dead = True

    def terminate(self):
        if self.returncode is None:
            self.returncode = 0
        self.server.dead = True

    def wait(self, timeout=None):
        return self.returncode

    def close_logs(self):
        pass


class ProcChild:
    """A real worker subprocess: spawn, handshake, socket transport.
    stdout/stderr land in ``<workdir>/replica_<id>.log`` (no pipe to
    fill, and the log survives the child for forensics)."""

    HANDSHAKE = "PTPU_WORKER_READY "

    def __init__(self, spec, replica_id, *, workdir,
                 spawn_timeout=180.0, transport_kw=None):
        from ...testing.chaos import subprocess_env

        spec = dict(spec, replica_id=replica_id)
        os.makedirs(workdir, exist_ok=True)
        self.log_path = os.path.join(workdir, f"replica_{replica_id}.log")
        self._log = open(self.log_path, "ab", buffering=0)
        spec_path = os.path.join(workdir, f"replica_{replica_id}.spec.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.inference.fleet.worker",
             "--spec-file", spec_path],
            stdout=subprocess.PIPE, stderr=self._log,
            env=subprocess_env(), cwd=os.getcwd())
        self.pid = self.proc.pid
        info = self._handshake(spawn_timeout)
        self.port = info["port"]
        self.scrape_port = info.get("scrape_port")
        # past the handshake, stdout is quiet; route the fd into the
        # log file and stop reading the pipe
        self.proc.stdout.close()
        self.transport = SocketTransport(
            "127.0.0.1", self.port, seed=replica_id,
            **(transport_kw or {}))

    def _handshake(self, timeout):
        import select

        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not select.select(
                    [self.proc.stdout], [], [], max(remaining, 0.0))[0]:
                self.proc.kill()
                raise TransportError(
                    f"worker pid {self.pid}: no handshake in {timeout}s "
                    f"(log: {self.log_path})")
            line = self.proc.stdout.readline()
            if not line:
                rc = self.proc.wait()
                raise TransportError(
                    f"worker pid {self.pid} exited {rc} before handshake "
                    f"(log: {self.log_path})")
            text = line.decode("utf-8", "replace")
            self._log.write(line)
            if text.startswith(self.HANDSHAKE):
                return json.loads(text[len(self.HANDSHAKE):])

    def poll(self):
        return self.proc.poll()

    def kill(self):
        try:
            self.proc.kill()          # SIGKILL
        except OSError:
            pass

    def terminate(self):
        try:
            self.proc.terminate()     # SIGTERM (flight bundle path)
        except OSError:
            pass

    def wait(self, timeout=None):
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def close_logs(self):
        try:
            self._log.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Autoscaler
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class AutoscaleConfig:
    """Scale policy (docs/SERVING.md "Process topology" policy table).
    Scale-up triggers on overload SIGNALS (burn rate / brownout), not
    raw queue depth — the same signals the admission controller and
    brownout ladder act on, so the three never fight.  Scale-down waits
    for sustained FULL idleness and drains before stopping."""

    min_replicas: int = 1
    max_replicas: int = 8
    up_fast_burn: float = 1.0     # any objective's fast burn >= this
    up_brownout_level: int = 1    # brownout at/above this level
    idle_ticks_down: int = 64     # fully-idle ticks before draining one
    cooldown_ticks: int = 16      # min ticks between actions


class Autoscaler:
    def __init__(self, cfg=None):
        self.cfg = cfg or AutoscaleConfig()
        self.idle_ticks = 0
        self.last_action_tick = None
        self.decisions = []           # (tick, direction, reason)

    def decide(self, tick, n_replicas, *, decision_input=None,
               brownout_level=0, idle=False):
        """-> ("up"|"down"|None, reason)."""
        cfg = self.cfg
        self.idle_ticks = self.idle_ticks + 1 if idle else 0
        if (self.last_action_tick is not None
                and tick - self.last_action_tick < cfg.cooldown_ticks):
            return None, "cooldown"
        if n_replicas < cfg.max_replicas:
            if brownout_level >= cfg.up_brownout_level:
                return self._act(tick, "up",
                                 f"brownout_level={brownout_level}")
            for obj in (decision_input or {}).values():
                burn = obj.get("fast_burn") or 0.0
                if burn >= cfg.up_fast_burn:
                    return self._act(tick, "up", f"fast_burn={burn:.2f}")
        if (n_replicas > cfg.min_replicas
                and self.idle_ticks >= cfg.idle_ticks_down):
            return self._act(tick, "down",
                             f"idle_ticks={self.idle_ticks}")
        return None, None

    def _act(self, tick, direction, reason):
        self.last_action_tick = tick
        self.idle_ticks = 0
        self.decisions.append((tick, direction, reason))
        _AUTOSCALE.inc(labels=(direction,))
        return direction, reason


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------
class FleetSupervisor:
    """Child lifecycle + routing for an elastic multi-process fleet.

    Drives the :class:`FleetRouter` it owns and duck-types its surface,
    so ``run_soak`` and the bench harnesses treat a fleet of real
    processes exactly like the in-process simulation.  Each
    ``step()``: lease check -> upgrade stage -> autoscale -> concurrent
    step fan-out (``prestep``) -> router tick."""

    def __init__(self, spec, n_replicas, *, proc=True,
                 policy="least_loaded", overload=None,
                 max_queue_depth=None, lease_seconds=30.0,
                 heartbeat_every=2.0, workdir=None, transport_kw=None,
                 chaos=None, autoscale=None, max_respawns=8,
                 respawn=True, warmup_new=True):
        self.spec = dict(spec)
        # PTPU_FLEET_PROC=0 forces the in-process loopback children
        # everywhere, no code change — the bitwise escape hatch
        self.proc = bool(proc) and fleet_proc_enabled()
        self.lease_seconds = float(lease_seconds)
        self.heartbeat_every = float(heartbeat_every)
        self.workdir = workdir or tempfile.mkdtemp(prefix="ptpu_fleet_")
        self.transport_kw = dict(transport_kw or {})
        self._chaos = chaos or {}     # ordinal -> wrap(transport) factory
        self.autoscaler = (Autoscaler(autoscale)
                           if isinstance(autoscale, AutoscaleConfig)
                           else autoscale)
        self.max_respawns = int(max_respawns)
        self.respawn = bool(respawn)
        self.warmup_new = bool(warmup_new)
        self.children = {}            # router idx -> child
        self.tick = 0
        self.respawns = 0
        self.lease_deaths = 0
        self.migrated_requests = 0
        self.migration_bytes = 0
        self._next_ordinal = 0
        self._reaped = set()          # dead idxs the supervisor handled
        self._upgrade = None
        self.upgrades = []            # completed upgrade summaries
        self._slo_engine = None
        engines = []
        spawned = []
        for _ in range(n_replicas):
            child, engine = self._spawn()
            spawned.append(child)
            engines.append(engine)
        self.router = FleetRouter(engines, policy=policy,
                                  max_queue_depth=max_queue_depth,
                                  overload=overload)
        for idx, child in enumerate(spawned):
            self.children[idx] = child
        _PROCS.set(float(len(self.children)))

    # -- spawning -----------------------------------------------------------
    def _spawn(self):
        ordinal = self._next_ordinal
        self._next_ordinal += 1
        if self.proc:
            child = ProcChild(self.spec, ordinal, workdir=self.workdir,
                              transport_kw=self.transport_kw)
        else:
            child = LocalChild(self.spec, ordinal,
                               transport_kw=self.transport_kw)
        wrap = self._chaos.get(ordinal)
        if wrap is not None:
            child.transport = wrap(child.transport)
        engine = RemoteEngine(child.transport)
        return child, engine

    def _spawn_replacement(self):
        child, engine = self._spawn()
        if self.warmup_new:
            engine.warmup()
        idx = self.router.add_replica(engine)
        self.children[idx] = child
        self.respawns += 1
        _RESPAWNS.inc()
        _PROCS.set(float(self._live_children()))
        return idx

    def _live_children(self):
        return sum(1 for idx, c in self.children.items()
                   if c.poll() is None
                   and not self.router.replicas[idx].retired)

    # -- router duck-type surface -------------------------------------------
    @property
    def replicas(self):
        return self.router.replicas

    @property
    def overload(self):
        return self.router.overload

    @property
    def cancelled(self):
        return self.router.cancelled

    @property
    def shed(self):
        return self.router.shed

    @property
    def requeues(self):
        return self.router.requeues

    @property
    def served(self):
        return self.router.served

    @property
    def _pending(self):
        return self.router._pending

    @property
    def _inflight(self):
        return self.router._inflight

    @property
    def _policy_name(self):
        return self.router._policy_name

    def submit(self, prompt, **kw):
        # reap already-exited children BEFORE admission: poll() is one
        # WNOHANG waitpid, and catching the corpse here (full forensics
        # + respawn) beats the router's dispatch-time safety net, which
        # only sees an opaque transport fault
        self._reap_exited()
        return self.router.submit(prompt, **kw)

    def _reap_exited(self):
        for idx, child in list(self.children.items()):
            handle = self.router.replicas[idx]
            if (handle.healthy and not handle.retired
                    and child.poll() is not None):
                age = (time.monotonic()
                       - handle.engine.transport.last_ok_time)
                self._declare_dead(idx, child, child.poll(), age)

    def cancel(self, rid, reason="client"):
        return self.router.cancel(rid, reason=reason)

    def outcomes(self):
        return self.router.outcomes()

    def load(self):
        out = self.router.load()
        out["procs"] = self._live_children()
        out["respawns"] = self.respawns
        return out

    def drained(self):
        return self.router.drained()

    def run_until_complete(self, max_ticks=100000):
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            if self.drained() and self._upgrade is None:
                return done
        raise TimeoutError("fleet did not drain")

    def attach_slo(self, slo_engine):
        """run_soak hands the live SLO engine over so the autoscaler
        can read decision_input() burn rates."""
        self._slo_engine = slo_engine

    # -- the fleet tick -----------------------------------------------------
    def step(self):
        self.tick += 1
        self._lease_tick()
        self._upgrade_tick()
        self._autoscale_tick()
        self._prestep()
        return self.router.step()

    def _routable(self, handle):
        if not handle.healthy or handle.retired:
            return False
        ov = self.router.overload
        if ov is not None and ov.breakers[handle.idx].poll() == "open":
            return False
        return True

    def _prestep(self):
        """Fan the step RPC out to every routable replica BEFORE the
        router's sequential collection pass: child processes decode
        concurrently on real wall clock.  An uncollected prestep is
        self-healing — ``RemoteEngine.step`` collects the outstanding
        call instead of double-sending."""
        for handle in self.router.replicas:
            if self._routable(handle):
                try:
                    handle.engine.prestep()
                except Exception:     # collection will classify it
                    pass

    # -- heartbeat leases ---------------------------------------------------
    def _lease_tick(self):
        now = time.monotonic()
        registry_on = _telemetry.get_registry().enabled
        for idx, child in list(self.children.items()):
            handle = self.router.replicas[idx]
            if not handle.healthy or handle.retired:
                if (not handle.healthy and not handle.retired
                        and idx not in self._reaped):
                    # the router declared this replica dead on its own
                    # (a dispatch-/step-time transport fault beat the
                    # lease check) — the supervisor still owns the
                    # corpse: reap the child and respawn
                    self._reaped.add(idx)
                    child.kill()
                    child.wait(timeout=10.0)
                    _PROCS.set(float(self._live_children()))
                    if self.respawn and self.respawns < self.max_respawns:
                        self._spawn_replacement()
                continue
            exit_code = child.poll()
            age = now - handle.engine.transport.last_ok_time
            if registry_on:
                _HEARTBEAT_AGE.set(age, labels=(str(idx),))
            if exit_code is None and age > self.heartbeat_every:
                try:
                    handle.engine.ping(timeout=self.heartbeat_every)
                    age = 0.0
                except Exception:
                    age = now - handle.engine.transport.last_ok_time
            if exit_code is not None or age > self.lease_seconds:
                self._declare_dead(idx, child, exit_code, age)

    def _declare_dead(self, idx, child, exit_code, age):
        """Missed lease or exited child: SIGKILL (idempotent), declare
        dead through the router (requests replay exactly-once), record
        the forensics, respawn."""
        self._reaped.add(idx)
        child.kill()
        child.wait(timeout=10.0)
        self.lease_deaths += 1
        _LEASE_EXPIRED.inc()
        reason = (f"heartbeat lease expired ({age:.1f}s"
                  f" > {self.lease_seconds}s)"
                  if exit_code is None
                  else f"child exited with code {exit_code}")
        self.router.kill_replica(
            idx, HeartbeatLost(reason), raise_if_empty=False,
            context={"exit_code": child.poll(),
                     "heartbeat_age": round(age, 3),
                     "pid": child.pid,
                     "supervisor": True})
        _PROCS.set(float(self._live_children()))
        if self.respawn and self.respawns < self.max_respawns:
            self._spawn_replacement()

    # -- autoscaling --------------------------------------------------------
    def _autoscale_tick(self):
        if self.autoscaler is None:
            return
        ov = self.router.overload
        brownout = ov.brownout.level if ov is not None else 0
        decision_input = (self._slo_engine.decision_input()
                          if self._slo_engine is not None else None)
        idle = (not self.router._pending and not self.router._inflight
                and all((h.engine.load()["queue_depth"] == 0
                         and h.engine.load()["occupied_slots"] == 0)
                        for h in self.router.replicas
                        if h.healthy and not h.retired))
        n_live = sum(1 for h in self.router.replicas
                     if h.healthy and not h.retired)
        direction, reason = self.autoscaler.decide(
            self.tick, n_live, decision_input=decision_input,
            brownout_level=brownout, idle=idle)
        if direction == "up":
            self._spawn_replacement()
        elif direction == "down":
            self._scale_down()

    def _scale_down(self):
        """Drain-then-stop the newest live replica.  It is marked
        draining immediately (no new dispatches) and retired on a later
        tick once empty — scale-down never sheds work."""
        for handle in reversed(self.router.replicas):
            if handle.healthy and not handle.retired \
                    and not handle.draining:
                handle.draining = True
                return

    def _retire_if_drained(self):
        for handle in self.router.replicas:
            if not (handle.draining and handle.healthy
                    and not handle.retired):
                continue
            if self._upgrade is not None \
                    and self._upgrade.get("idx") == handle.idx:
                continue              # upgrade-draining, not scale-down
            load = handle.engine.load()
            if (load["queue_depth"] == 0 and load["occupied_slots"] == 0
                    and self.router._replica_inflight(handle.idx) == 0):
                child = self.children.get(handle.idx)
                handle.retired = True
                handle.draining = False
                if child is not None:
                    try:
                        handle.engine.shutdown()
                    except Exception:
                        pass
                    child.terminate()
                    child.wait(timeout=10.0)
                    child.close_logs()
                _PROCS.set(float(self._live_children()))

    # -- rolling upgrades ---------------------------------------------------
    def start_rolling_upgrade(self, version, *, queue=None):
        """Begin a rolling weight upgrade to ``version``.  One stage
        advances per fleet tick (drain+migrate -> reload -> warmup ->
        readmit, then the next replica), so the fleet keeps serving
        throughout; progress via :meth:`upgrade_status`."""
        if self._upgrade is not None:
            raise RuntimeError("a rolling upgrade is already in flight")
        if queue is None:
            queue = [h.idx for h in self.router.replicas
                     if h.healthy and not h.retired]
        self._upgrade = {
            "version": version, "queue": list(queue), "idx": None,
            "stage": "next", "upgraded": [], "migrated": 0,
            "migrate_bytes": 0, "started_tick": self.tick,
            "finished_tick": None,
        }
        return self._upgrade

    def upgrade_status(self):
        if self._upgrade is not None:
            return dict(self._upgrade)
        return self.upgrades[-1] if self.upgrades else None

    def _upgrade_tick(self):
        self._retire_if_drained()
        up = self._upgrade
        if up is None:
            return
        stage = up["stage"]
        if stage == "next":
            while up["queue"]:
                idx = up["queue"].pop(0)
                handle = self.router.replicas[idx]
                if handle.healthy and not handle.retired:
                    up["idx"] = idx
                    handle.draining = True
                    up["stage"] = "migrate"
                    return
            up["finished_tick"] = self.tick
            up["stage"] = "done"
            self.upgrades.append(up)
            self._upgrade = None
            return
        idx = up["idx"]
        handle = self.router.replicas[idx]
        if not handle.healthy:
            # the replica died mid-upgrade; its work already replayed
            # through kill_replica — move on
            up["stage"] = "next"
            return
        try:
            if stage == "migrate":
                self._migrate_off(handle, up)
                up["stage"] = "reload"
            elif stage == "reload":
                handle.engine.reload_weights(version=up["version"])
                up["stage"] = "warmup"
            elif stage == "warmup":
                handle.engine.warmup()
                up["stage"] = "readmit"
            elif stage == "readmit":
                handle.draining = False
                up["upgraded"].append(idx)
                _UPGRADED.inc()
                up["stage"] = "next"
        except Exception as exc:      # noqa: BLE001
            # an upgrade stage failing is a replica failure: declare it
            # dead (work replays), respawn at the NEW version via the
            # normal lease path, and continue the rollout
            self.router.kill_replica(
                idx, exc, raise_if_empty=False,
                context={"during_upgrade_stage": stage,
                         "supervisor": True})
            self._reaped.add(idx)
            child = self.children.get(idx)
            if child is not None:
                child.kill()
                child.wait(timeout=10.0)
            if self.respawn and self.respawns < self.max_respawns:
                self._spawn_replacement()
            up["stage"] = "next"

    def _migrate_off(self, handle, up):
        """Drain ``handle`` to its KV-migration point and re-home every
        request on a peer: running requests ship their host KV snapshot
        (int8 codes + scales when int8_kv — the quantized wire), stream
        callbacks move with them, and the router's inflight table is
        reassigned so completions land correctly.  With no live peer
        the requests requeue through the router instead — migration
        never loses work, it just degrades to replay."""
        data = handle.engine.drain_requests()
        reqs = list(data["running"]) + list(data["waiting"])
        if not reqs:
            return
        peers = [h for h in self.router.replicas
                 if h is not handle and h.healthy
                 and not h.retired and not h.draining]
        if not peers:
            # single-replica fleet: hold the requests in the router and
            # let them re-dispatch (to this replica, post-upgrade)
            for req in reqs:
                rid = int(req["rid"])
                entry = self.router._inflight.pop(rid, None)
                if entry is not None:
                    self.router.requeues += 1
                    self.router._pending.append(
                        (rid, entry[1], entry[2], entry[3]))
            return
        for req in reqs:
            rid = int(req["rid"])
            peer = min(peers, key=lambda h:
                       (h.engine.load()["queue_depth"]
                        + h.engine.load()["occupied_slots"], h.idx))
            peer.engine.inject_wire(req)
            self.router.reassign(rid, peer.idx)
            peer.engine.adopt_stream(rid, handle.engine.release_stream(rid))
            nbytes = _wire_size(req)
            self.migrated_requests += 1
            self.migration_bytes += nbytes
            up["migrated"] += 1
            up["migrate_bytes"] += nbytes
            _MIGRATIONS.inc()
            _MIGRATION_BYTES.inc(nbytes)

    # -- shutdown -----------------------------------------------------------
    def close(self):
        for idx, child in self.children.items():
            handle = self.router.replicas[idx]
            if child.poll() is None and handle.healthy:
                try:
                    handle.engine.shutdown()
                except Exception:
                    pass
            child.terminate()
        for child in self.children.values():
            if child.wait(timeout=5.0) is None:
                child.kill()
                child.wait(timeout=5.0)
            child.close_logs()
        for handle in self.router.replicas:
            try:
                handle.engine.close()
            except Exception:
                pass
        _PROCS.set(0.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def summary(self):
        return {
            "procs": self._live_children(),
            "proc_backend": self.proc,
            "respawns": self.respawns,
            "lease_deaths": self.lease_deaths,
            "migrated_requests": self.migrated_requests,
            "migration_bytes": self.migration_bytes,
            "upgrades": [
                {k: u[k] for k in ("version", "upgraded", "migrated",
                                   "migrate_bytes", "started_tick",
                                   "finished_tick")}
                for u in self.upgrades],
            "autoscale": (list(self.autoscaler.decisions)
                          if self.autoscaler else []),
        }


def _wire_size(obj):
    from . import wire
    return len(wire.encode_frame(obj))
