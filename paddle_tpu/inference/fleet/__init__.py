"""paddle_tpu.inference.fleet — fleet-scale serving (docs/SERVING.md).

The single-process ContinuousBatchingEngine becomes a production
topology:

- :mod:`.router` — ``FleetRouter``: N replicas behind pluggable
  admission policies (round-robin / least-loaded on live telemetry /
  prefix-affinity), per-replica backpressure, and requeue-on-death.
- :mod:`.disagg` — ``DisaggregatedEngine``: prefill and decode split
  onto separate workers with an explicit, bitwise KV handoff seam.
- :mod:`.spec_decode` — ``DraftRunner``: draft-model speculative
  decoding through the engine (draft K, verify in one target forward,
  accept-prefix; greedy output bitwise-identical to plain decode).
- :mod:`.soak` — the Poisson soak harness behind
  ``tools/serve_bench.py`` and the bench_gate serving gates.
- :mod:`.wire` / :mod:`.transport` — the length-prefixed msgpack frame
  format and the RPC transport (loopback + socket) that turn replicas
  into real OS processes, with retries, idempotent call ids, and a
  chaos seam (``testing.chaos.ChaosTransport``).
- :mod:`.cluster` — ``FleetSupervisor``: child-process lifecycle over
  the router — heartbeat leases, SIGKILL + exactly-once replay +
  respawn, SLO-driven autoscaling, and zero-loss rolling weight
  upgrades over the KV-migration wire (``PTPU_FLEET_PROC=0`` falls
  back to in-process loopback children, bitwise).
- :mod:`.hosts` — cross-host topology (``PTPU_FLEET_HOSTS``): per-host
  agents rendezvous through the distributed TCPStore, the supervisor
  places replicas across hosts and fences each (re)lease with a
  monotone epoch, network partitions sever whole hosts (fence + replay,
  then quarantine-and-adopt on heal), and overload shedding upgrades to
  live cross-host migration when a peer has headroom.

The int8 paged-KV mode lives in the engine itself
(``inference.serving``, ``PTPU_INT8_KV``); it composes with every
topology here because the page payload format is invisible to routing,
handoff, and verification.
"""
from .cluster import (AutoscaleConfig, Autoscaler, FleetSupervisor,  # noqa: F401
                      build_model_from_spec, fleet_proc_enabled,
                      make_model_spec)
from .disagg import DisaggregatedEngine  # noqa: F401
from .hosts import (AgentClient, HostAgent, HostDirectory, HostHandle,  # noqa: F401
                    HostLost, fleet_hosts_enabled, spawn_local_agent,
                    spawn_proc_agent)
from .overload import (Overloaded, OverloadConfig, RemoteReplicaError,  # noqa: F401
                       TransientReplicaError, classify_step_exception,
                       outcome_from_wire, outcome_to_wire,
                       overload_enabled)
from .router import POLICIES, FleetRouter, ReplicaHandle, make_replicas  # noqa: F401
from .soak import (build_workload, fleet_soak, overload_block,  # noqa: F401
                   partition_block, run_soak, soak_block, upgrade_block)
from .spec_decode import DraftRunner  # noqa: F401
from .transport import (LoopbackTransport, RemoteEngine, ReplicaServer,  # noqa: F401
                        SocketTransport, Transport, TransportError,
                        TransportSevered, TransportTimeout)

__all__ = [
    "FleetRouter", "ReplicaHandle", "POLICIES", "make_replicas",
    "DisaggregatedEngine", "DraftRunner", "build_workload", "run_soak",
    "fleet_soak", "soak_block", "overload_block", "upgrade_block",
    "partition_block", "Overloaded",
    "OverloadConfig", "TransientReplicaError", "RemoteReplicaError",
    "classify_step_exception", "overload_enabled", "outcome_to_wire",
    "outcome_from_wire", "Transport", "LoopbackTransport",
    "SocketTransport", "RemoteEngine", "ReplicaServer", "TransportError",
    "TransportTimeout", "TransportSevered", "FleetSupervisor",
    "Autoscaler", "AutoscaleConfig", "make_model_spec",
    "build_model_from_spec", "fleet_proc_enabled",
    "HostAgent", "AgentClient", "HostDirectory", "HostHandle",
    "HostLost", "fleet_hosts_enabled", "spawn_local_agent",
    "spawn_proc_agent",
]
