"""paddle_tpu.inference.fleet — fleet-scale serving (docs/SERVING.md).

The single-process ContinuousBatchingEngine becomes a production
topology:

- :mod:`.router` — ``FleetRouter``: N replicas behind pluggable
  admission policies (round-robin / least-loaded on live telemetry /
  prefix-affinity), per-replica backpressure, and requeue-on-death.
- :mod:`.disagg` — ``DisaggregatedEngine``: prefill and decode split
  onto separate workers with an explicit, bitwise KV handoff seam.
- :mod:`.spec_decode` — ``DraftRunner``: draft-model speculative
  decoding through the engine (draft K, verify in one target forward,
  accept-prefix; greedy output bitwise-identical to plain decode).
- :mod:`.soak` — the Poisson soak harness behind
  ``tools/serve_bench.py`` and the bench_gate serving gates.

The int8 paged-KV mode lives in the engine itself
(``inference.serving``, ``PTPU_INT8_KV``); it composes with every
topology here because the page payload format is invisible to routing,
handoff, and verification.
"""
from .disagg import DisaggregatedEngine  # noqa: F401
from .overload import (Overloaded, OverloadConfig, TransientReplicaError,  # noqa: F401
                       classify_step_exception, overload_enabled)
from .router import POLICIES, FleetRouter, ReplicaHandle, make_replicas  # noqa: F401
from .soak import (build_workload, fleet_soak, overload_block, run_soak,  # noqa: F401
                   soak_block)
from .spec_decode import DraftRunner  # noqa: F401

__all__ = [
    "FleetRouter", "ReplicaHandle", "POLICIES", "make_replicas",
    "DisaggregatedEngine", "DraftRunner", "build_workload", "run_soak",
    "fleet_soak", "soak_block", "overload_block", "Overloaded",
    "OverloadConfig", "TransientReplicaError", "classify_step_exception",
    "overload_enabled",
]
