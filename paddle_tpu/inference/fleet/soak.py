"""Serving soak harness: Poisson arrivals, mixed prompts, N replicas.

Drives synthetic traffic through an engine, a DisaggregatedEngine, or a
FleetRouter and reduces the run to the ``"serving"`` JSON block that
``tools/serve_bench.py`` emits and ``tools/bench_gate.py`` gates
(docs/SERVING.md soak recipe).

**Simulated-parallel clock.** In deployment each replica is its own
mesh; in this process they tick sequentially on one host. Wall time
would therefore show ~1x scaling no matter how good the router is, so
the soak advances a simulated clock instead: each fleet tick costs
``max`` over the replicas' measured step times (they would run
concurrently) plus the router's own host time (it is serial). Goodput
and TTFT percentiles are computed on that clock; ``wall_seconds`` is
also reported so nothing hides. A single-replica run's simulated clock
equals its wall clock, making ``goodput_x_single`` an honest scaling
ratio. The block records ``"simulated_parallel": true`` whenever more
than one replica contributed.
"""
from __future__ import annotations

import time
from collections import deque

import numpy as np

from ... import telemetry as _telemetry
from ...telemetry import flight as _flight
from .overload import Overloaded

__all__ = ["build_workload", "run_soak", "percentile", "fleet_soak",
           "soak_block", "overload_block", "overload_workload",
           "default_objectives", "upgrade_block", "partition_block"]

#: a TTFT observed more than this many fleet ticks ago ages out of the
#: per-tick ``values:ttft_p50/p99_recent`` signals — the SLO engine's
#: burn windows then drain and a fired TTFT alert can CLEAR once the
#: overload passes (docs/TELEMETRY.md)
TTFT_RECENT_TICKS = 50

_BREAKER_CODES = {"closed": 0, "half_open": 1, "open": 2}


def percentile(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (None when empty)."""
    if not sorted_vals:
        return None
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def build_workload(n_requests, arrival_rate, prompt_lens, vocab_size,
                   shared_prefix=0, sampled_fraction=0.0,
                   deadline_seconds=None, batch_fraction=0.0, seed=0):
    """Synthetic request list [(arrival_time, prompt, kwargs)] sorted by
    arrival: Poisson arrivals at ``arrival_rate`` req/sec (simulated
    seconds), prompt lengths drawn from ``prompt_lens``, an optional
    shared system prefix (the prefix-affinity workload), an optional
    sampled-request fraction, optional per-request deadlines, and an
    optional ``batch``-priority fraction (the overload scenario's mixed
    traffic — batch requests hit every watermark first)."""
    rng = np.random.default_rng(seed)
    prefix = [int(t) for t in rng.integers(1, vocab_size, shared_prefix)]
    t = 0.0
    out = []
    for _ in range(n_requests):
        t += float(rng.exponential(1.0 / arrival_rate))
        n = int(rng.choice(prompt_lens))
        tail_n = max(1, n - shared_prefix)
        prompt = prefix + [int(x) for x in
                           rng.integers(1, vocab_size, tail_n)]
        kw = {}
        if sampled_fraction and rng.random() < sampled_fraction:
            kw.update(temperature=0.7, top_k=8, top_p=0.95)
        if deadline_seconds is not None:
            kw["deadline_seconds"] = deadline_seconds
        if batch_fraction and rng.random() < batch_fraction:
            kw["priority"] = "batch"
        out.append((t, prompt, kw))
    return out


def overload_workload(capacity_req_per_sec, n_requests, prompt_lens,
                      vocab_size, *, rate_x_capacity=2.0,
                      batch_fraction=0.4, seed=0, **kw):
    """The overload scenario's arrival schedule: sustained Poisson
    arrivals at ``rate_x_capacity`` times the fleet's measured service
    capacity (req/sim-second), with a mixed interactive/batch split —
    the traffic shape admission control and shedding exist for."""
    return build_workload(
        n_requests, rate_x_capacity * capacity_req_per_sec, prompt_lens,
        vocab_size, batch_fraction=batch_fraction, seed=seed, **kw)


def _spec_stats(eng):
    if getattr(eng, "spec_draft_tokens", 0):
        return {"ticks": eng.spec_ticks,
                "drafted": eng.spec_draft_tokens,
                "accepted": eng.spec_accepted_tokens,
                "acceptance_rate": round(eng.spec_acceptance_rate, 4)}
    return None


def _engine_stats(eng):
    """Per-engine counters, transparent to DisaggregatedEngine."""
    if hasattr(eng, "engine_stats"):
        # a RemoteEngine proxy: the counters live in the replica
        # process — one stats RPC computes this dict server-side
        return eng.engine_stats()
    if hasattr(eng, "prefill") and hasattr(eng, "decode"):
        p, d = eng.prefill, eng.decode
        return {"disaggregated": True,
                "preemptions": p.preemptions + d.preemptions,
                "prefix_hit_pages": p.prefix_cache_hits,
                "cancellations": p.cancellations + d.cancellations,
                "handoffs": eng.handoffs,
                "handoff_bytes": eng.handoff_bytes,
                "int8_kv": d.int8_kv,
                "int8_weights": d.int8_weights,
                "weight_bytes": dict(d.weight_bytes),
                "spec": _spec_stats(d)}
    return {"disaggregated": False,
            "preemptions": eng.preemptions,
            "prefix_hit_pages": eng.prefix_cache_hits,
            "cancellations": eng.cancellations,
            "handoffs": 0, "handoff_bytes": 0,
            "int8_kv": eng.int8_kv,
            "int8_weights": eng.int8_weights,
            "weight_bytes": dict(eng.weight_bytes),
            "spec": _spec_stats(eng)}


def run_soak(target, workload, warmup=True, max_ticks=200000,
             rebase_overload_clock=True, recorder=None, slo=None,
             timeline_path=None, on_tick=None, token_cb=None):
    """Drive ``workload`` through ``target`` (engine / disagg /
    FleetRouter) and return the raw soak stats dict. Cold start
    (construction is the caller's; compile is ours via ``warmup()``) is
    measured per engine and reported as the max across replicas — in
    deployment replicas spin up concurrently.

    Every submitted request reaches exactly one terminal outcome:
    served (``completed``), ``cancelled``, ``shed`` (overload load
    shedding), or ``rejected`` (a structured ``Overloaded`` raised at
    admission — nothing was queued). ``outcomes_conserved`` asserts the
    conservation; a ``False`` there means a request was lost or hung.

    When the target is a FleetRouter with overload control, its
    controller is rebased onto THIS soak's simulated-parallel clock
    (``rebase_overload_clock=False`` keeps wall time): admission
    prediction, breaker backoff, and brownout hysteresis then measure
    fleet time, and the run is reproducible.

    **Telemetry.** ``recorder`` (a
    :class:`~paddle_tpu.telemetry.TimeSeriesRecorder`) — or
    ``timeline_path``/``slo``, which create one — records one timeline
    sample per fleet tick on the simulated clock: queue depth, inflight,
    brownout level, per-replica breaker states, recent-TTFT percentiles,
    running goodput, and cumulative outcome counters. ``slo`` is a list
    of :class:`~paddle_tpu.telemetry.SloObjective` (or a prebuilt
    engine) evaluated live after every sample; its fire/clear events
    land in ``stats["slo"]`` and the flight recorder's forensics window.
    The run ends with a ``soak_end`` flight bundle when a flight
    recorder is installed.

    ``on_tick(tick_index)`` fires after every fleet tick — the seam the
    multi-process chaos scenarios use to SIGKILL a replica or start a
    rolling upgrade mid-soak.  ``token_cb(rid, tok)`` observes every
    streamed token (duplicate-delivery accounting for the UPGRADE
    gate).  A target exposing ``attach_slo`` (the FleetSupervisor)
    receives the live SLO engine so its autoscaler can read burn
    rates."""
    router = hasattr(target, "replicas")
    engines = ([h.engine for h in target.replicas] if router
               else [target])
    sim = [0.0]
    ov = getattr(target, "overload", None) if router else None
    if ov is not None and rebase_overload_clock:
        ov.set_clock(lambda: sim[0])
    own_recorder = False
    if recorder is None and (timeline_path is not None
                             or slo is not None):
        recorder = _telemetry.recorder(jsonl_path=timeline_path)
        own_recorder = True
    if recorder is not None:
        recorder.set_clock(lambda: sim[0])
    slo_engine = None
    if slo is not None:
        slo_engine = (slo if hasattr(slo, "evaluate")
                      else _telemetry.SloEngine(
                          recorder, slo,
                          registry=_telemetry.get_registry(),
                          flight=_flight.get()))
    if slo_engine is not None and hasattr(target, "attach_slo"):
        target.attach_slo(slo_engine)
    cold = []
    if warmup:
        for e in engines:
            cold.append(e.warmup())
    n_requests = len(workload)
    pending = deque(sorted(workload, key=lambda w: w[0]))
    arrival = {}
    plen = {}
    first_seen = {}
    ttfts = []
    done = {}
    rejected = {}                 # reason -> count (Overloaded raises)
    retry_afters = []
    wall0 = time.perf_counter()

    def on_token(rid, tok):
        first_seen.setdefault(rid, None)
        if token_cb is not None:
            token_cb(rid, tok)

    def n_terminal():
        return (len(done)
                + len(getattr(target, "cancelled", {}) or {})
                + len(getattr(target, "shed", {}) or {}))

    tick_no = [0]
    gen_running = [0]
    ttft_recent = deque()         # (tick, ttft) — aged out by tick

    def take_sample():
        """One timeline sample on the sim clock (per fleet tick)."""
        while ttft_recent and \
                ttft_recent[0][0] < tick_no[0] - TTFT_RECENT_TICKS:
            ttft_recent.popleft()
        values = {}
        recent = sorted(t for _, t in ttft_recent)
        if recent:
            values["ttft_p50_recent"] = percentile(recent, 0.50)
            values["ttft_p99_recent"] = percentile(recent, 0.99)
        values["goodput_tokens_per_sec"] = (
            round(gen_running[0] / sim[0], 3) if sim[0] > 0 else 0.0)
        if router:
            values["queue_depth"] = len(target._pending)
            values["inflight"] = len(target._inflight)
            values["healthy_replicas"] = sum(
                1 for h in target.replicas if h.healthy)
        if ov is not None:
            values["brownout_level"] = ov.brownout.level
            # per-replica rollup: breaker state as a plottable code
            for i, br in enumerate(ov.breakers):
                values[f"breaker_state_r{i}"] = _BREAKER_CODES.get(
                    br.state, -1)
        counters = {
            "soak_completed_total": len(done),
            "soak_shed_total": len(getattr(target, "shed", {}) or {}),
            "soak_rejected_total": sum(rejected.values()),
            "soak_generated_tokens_total": gen_running[0],
        }
        recorder.sample(values=values, counters=counters,
                        tags={"tick": tick_no[0]})
        if slo_engine is not None:
            slo_engine.evaluate()

    for _tick in range(max_ticks):
        # admit every arrival the simulated clock has reached; when the
        # fleet is fully idle, jump the clock to the next arrival
        # instead of spinning empty ticks
        if pending and n_terminal() >= len(arrival):
            sim[0] = max(sim[0], pending[0][0])
        while pending and pending[0][0] <= sim[0]:
            arr, prompt, kw = pending.popleft()
            if not router:
                # priority classes are a router concept; a bare engine's
                # submit() surface doesn't take one
                kw = {k: v for k, v in kw.items() if k != "priority"}
            try:
                rid = target.submit(prompt, on_token=on_token, **kw)
            except Overloaded as o:
                # structured terminal outcome: rejected at admission
                rejected[o.reason] = rejected.get(o.reason, 0) + 1
                retry_afters.append(o.retry_after)
                continue
            arrival[rid] = arr
            plen[rid] = len(prompt)
        before_first = set(first_seen)
        if router:
            busy0 = [h.busy_seconds for h in target.replicas]
            t0 = time.perf_counter()
            out = target.step()
            wall = time.perf_counter() - t0
            deltas = [h.busy_seconds - b
                      for h, b in zip(target.replicas, busy0)]
            # replicas tick in parallel in deployment; router host work
            # is serial on top
            cost = (max(deltas) if deltas else 0.0) + max(
                0.0, wall - sum(deltas))
        else:
            t0 = time.perf_counter()
            out = target.step()
            cost = time.perf_counter() - t0
        sim[0] += cost
        tick_no[0] = _tick
        for rid in set(first_seen) - before_first:
            if rid in arrival:
                ttft = sim[0] - arrival[rid]
                ttfts.append(ttft)
                ttft_recent.append((_tick, ttft))
        gen_running[0] += sum(max(0, len(ids) - plen.get(rid, 0))
                              for rid, ids in out.items())
        done.update(out)
        if recorder is not None:
            take_sample()
        if on_tick is not None:
            on_tick(_tick)
        if not pending and n_terminal() >= len(arrival):
            break
    else:
        raise TimeoutError("soak did not drain")

    def cooling():
        if ov is not None and ov.brownout.level > 0:
            return True
        return bool(slo_engine is not None and slo_engine.active)

    if cooling():
        # post-drain cool-down: the pressure is gone — give the brownout
        # ladder its hysteresis ticks to step fully back up, so
        # "restored on recovery" is an observable property of the run
        # (bounded: each level needs brownout_down_ticks calm ticks),
        # and give the SLO engine's burn windows their ticks to drain so
        # a fired alert CLEARS on recovery (recent TTFTs age out after
        # TTFT_RECENT_TICKS, then the windows empty and burn drops to 0)
        limit = 16
        if ov is not None:
            limit = max(limit, (ov.cfg.brownout_down_ticks + 1)
                        * (ov.cfg.brownout_levels + 1) * 4)
        if slo_engine is not None:
            limit = max(limit, TTFT_RECENT_TICKS + 8 + 4 * max(
                (o.fast_samples for o in slo_engine.objectives),
                default=8))
        for _ in range(limit):
            if not cooling():
                break
            t0 = time.perf_counter()
            target.step()
            sim[0] += time.perf_counter() - t0
            tick_no[0] += 1
            if recorder is not None:
                take_sample()
    sim_t = sim[0]
    wall_seconds = time.perf_counter() - wall0
    cancelled = dict(getattr(target, "cancelled", {}) or {})
    shed = dict(getattr(target, "shed", {}) or {})
    n_rejected = sum(rejected.values())
    # goodput counts GENERATED tokens only (completions return
    # prompt+generated; the prompt was the caller's)
    gen_tokens = sum(max(0, len(ids) - plen.get(rid, 0))
                     for rid, ids in done.items())
    ttfts.sort()
    per_engine = [_engine_stats(e) for e in engines]
    shed_reasons = {}
    for reason in shed.values():
        shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
    stats = {
        "requests": n_requests,
        "completed": len(done),
        "cancelled": len(cancelled),
        "shed": len(shed),
        "rejected": n_rejected,
        "shed_reasons": shed_reasons,
        "reject_reasons": dict(rejected),
        "retry_after_mean": (round(sum(retry_afters)
                                   / len(retry_afters), 6)
                             if retry_afters else None),
        "outcomes_conserved": (len(done) + len(cancelled) + len(shed)
                               + n_rejected == n_requests),
        "replicas": len(engines),
        "generated_tokens": gen_tokens,
        "sim_seconds": round(sim_t, 6),
        "wall_seconds": round(wall_seconds, 6),
        "simulated_parallel": len(engines) > 1,
        "goodput_tokens_per_sec": (round(gen_tokens / sim_t, 2)
                                   if sim_t > 0 else None),
        "ttft": {
            "count": len(ttfts),
            "p50": percentile(ttfts, 0.50),
            "p95": percentile(ttfts, 0.95),
            "p99": percentile(ttfts, 0.99),
            "mean": (sum(ttfts) / len(ttfts)) if ttfts else None,
        },
        "cold_start_seconds": (round(max(cold), 4) if cold else None),
        "cold_start_seconds_total": (round(sum(cold), 4) if cold
                                     else None),
        "engines": per_engine,
    }
    if router:
        stats["router"] = {
            "policy": target._policy_name,
            "dispatched": [h.dispatched for h in target.replicas],
            "deaths": sum(1 for h in target.replicas if not h.healthy),
            "requeues": target.requeues,
        }
        if ov is not None:
            stats["overload"] = ov.summary()
    if recorder is not None:
        stats["timeline"] = {
            "samples": recorder.seq,
            "dropped": recorder.dropped,
            "path": recorder.jsonl_path,
        }
    if slo_engine is not None:
        stats["slo"] = slo_engine.summary()
    _flight.maybe_dump("soak_end", {
        "requests": n_requests, "completed": len(done),
        "shed": len(shed), "rejected": n_rejected,
        "sim_seconds": round(sim_t, 6)})
    if own_recorder:
        recorder.close()
    return stats, done


def fleet_soak(model, n_replicas, workload, *, policy="least_loaded",
               disagg=False, draft_model=None, engine_kw=None,
               disagg_kw=None, max_ticks=200000, overload=None,
               chaos_wrap=None, recorder=None, slo=None,
               timeline_path=None):
    """Build ``n_replicas`` engines (or disaggregated pairs) over
    ``model``, route them (FleetRouter when n>1), drive ``workload``,
    return the soak stats. One entry point for tools/serve_bench.py and
    ``bench.py --serve``. ``overload`` passes an
    :class:`.overload.OverloadConfig` to the router; ``chaos_wrap`` is
    an optional ``{replica_idx: fn}`` map wrapping chosen engines in a
    fault injector (``paddle_tpu.testing.chaos.ChaosReplica``) before
    routing — the overload scenario's flapping replica."""
    from ..serving import ContinuousBatchingEngine
    from .disagg import DisaggregatedEngine
    from .router import RID_STRIDE, FleetRouter

    engine_kw = dict(engine_kw or {})
    engines = []
    for i in range(n_replicas):
        if disagg:
            engines.append(DisaggregatedEngine(
                model, rid_base=i * RID_STRIDE, draft_model=draft_model,
                **dict(disagg_kw or {}), **engine_kw))
        else:
            engines.append(ContinuousBatchingEngine(
                model, rid_base=i * RID_STRIDE, draft_model=draft_model,
                **engine_kw))
    for idx, fn in (chaos_wrap or {}).items():
        engines[idx] = fn(engines[idx])
    target = (engines[0] if n_replicas == 1 and overload is None
              and not chaos_wrap
              else FleetRouter(engines, policy=policy, overload=overload))
    return run_soak(target, workload, max_ticks=max_ticks,
                    recorder=recorder, slo=slo,
                    timeline_path=timeline_path)


def default_objectives(ttft_budget=None, goodput_floor=None,
                       shed_rate_ceiling=None):
    """The stock soak objectives (docs/TELEMETRY.md declaration
    syntax), built from the same budgets the bench gates use."""
    out = []
    if ttft_budget is not None:
        out.append(_telemetry.SloObjective(
            "ttft_p99", "values:ttft_p99_recent", float(ttft_budget),
            op="le", description="p99 TTFT over the recent-tick window "
            "stays within the serving budget"))
    if goodput_floor is not None:
        out.append(_telemetry.SloObjective(
            "goodput_floor", "values:goodput_tokens_per_sec",
            float(goodput_floor), op="ge",
            description="running goodput stays above the floor"))
    if shed_rate_ceiling is not None:
        out.append(_telemetry.SloObjective(
            "shed_rate", "counters:soak_shed_total:rate",
            float(shed_rate_ceiling), op="le",
            description="shed per-second rate stays under the ceiling"))
    return out


def overload_block(model, *, replicas, workload, overload_cfg,
                   policy="least_loaded", engine_kw=None,
                   chaos_wrap=None, ttft_budget=None,
                   shed_ceiling=0.5, flap_bound=8,
                   rate_x_capacity=None, max_ticks=400000,
                   timeline_path=None, slo=None):
    """The gateable ``"overload"`` JSON block (docs/SERVING.md
    "Overload & degradation"; ``tools/bench_gate.py`` OVERLOAD gate):
    drive an overload-scenario workload (typically 2x measured capacity,
    mixed priorities, optionally one chaos-flapping replica) through a
    FleetRouter with the given :class:`.overload.OverloadConfig` and
    reduce the run to its embedded-budget gate fields —

    - ``conserved``: every submitted request reached exactly one
      terminal outcome (served | cancelled | shed | rejected); zero
      lost/hung requests is the hard floor;
    - ``p99_ttft_seconds`` of ADMITTED requests vs ``p99_ttft_budget``;
    - ``shed_fraction`` ((shed + rejected) / submitted) vs
      ``shed_ceiling`` — refusing a bounded slice of 2x traffic is the
      design, refusing most of it is a regression;
    - ``breaker_opens`` vs ``breaker_flap_bound`` — a flapping replica
      must cost a bounded number of breaker flaps, not one per fault;
    - ``brownout.restored`` — the ladder must step fully back up after
      the pressure clears (the run cools down post-drain until it does).

    When ``timeline_path``/``slo`` (or ``ttft_budget``) is given the
    soak records a per-tick timeline and runs the SLO engine live; the
    block then embeds ``"timeline"`` and ``"slo"`` sub-blocks. Alerts
    here are EXPECTED (the scenario runs past capacity by design) — the
    bench_gate SLO gate applies to clean ``"serving"`` blocks only.
    """
    if slo is None and ttft_budget is not None and timeline_path:
        slo = default_objectives(ttft_budget=ttft_budget)
    stats, _done = fleet_soak(
        model, replicas, workload, policy=policy, engine_kw=engine_kw,
        overload=overload_cfg, chaos_wrap=chaos_wrap,
        max_ticks=max_ticks, slo=slo, timeline_path=timeline_path)
    ov = stats.get("overload") or {}
    brown = dict(ov.get("brownout") or {})
    submitted = stats["requests"]
    refused = stats["shed"] + stats["rejected"]
    block = {
        "enabled": True,
        "replicas": replicas,
        "policy": policy,
        "submitted": submitted,
        "served": stats["completed"],
        "cancelled": stats["cancelled"],
        "shed": stats["shed"],
        "rejected": stats["rejected"],
        "shed_reasons": stats["shed_reasons"],
        "reject_reasons": stats["reject_reasons"],
        "conserved": bool(stats["outcomes_conserved"]),
        "goodput_tokens_per_sec": stats["goodput_tokens_per_sec"],
        "sim_seconds": stats["sim_seconds"],
        "ttft": stats["ttft"],
        "p99_ttft_seconds": stats["ttft"]["p99"],
        "shed_fraction": (round(refused / submitted, 4)
                          if submitted else 0.0),
        "shed_ceiling": float(shed_ceiling),
        "breaker_opens": int(ov.get("breaker_opens") or 0),
        "breaker_flap_bound": int(flap_bound),
        "breakers": ov.get("breakers"),
        "brownout": brown,
        "retry_after_mean": stats["retry_after_mean"],
    }
    for extra in ("timeline", "slo"):
        if extra in stats:
            block[extra] = stats[extra]
    if ttft_budget is not None:
        block["p99_ttft_budget"] = float(ttft_budget)
    if rate_x_capacity is not None:
        block["rate_x_capacity"] = float(rate_x_capacity)
    return block


def soak_block(model, *, replicas, workload, policy="least_loaded",
               disagg=False, draft_model=None, engine_kw=None,
               disagg_kw=None, baseline=None, scaling_target=None,
               ttft_budget=None, timeline_path=None, slo=None):
    """One gateable ``"serving"`` JSON block (docs/SERVING.md contract):
    the soak stats plus the gate fields — ``p99_ttft_seconds`` vs
    ``p99_ttft_budget``, ``goodput_x_single`` vs ``scaling_target``
    (both gates engage only when their bound is present), the replica
    ``cold_start_seconds`` (gated vs the previous round at the same
    scan mode, like the compile gate), and the scan mode itself.
    ``baseline`` is a prior single-replica block to scale against.

    With ``timeline_path`` (or explicit ``slo`` objectives) the soak
    records a per-tick timeline; a ``ttft_budget`` then also declares
    the stock TTFT SLO and the engine runs live, so the block's embedded
    ``"slo"`` sub-block is gateable: a CLEAN soak that still fires a
    fast-burn alert fails the round (tools/bench_gate.py SLO gate)."""
    from ...models.gpt import scan_layers_enabled

    if slo is None and ttft_budget is not None and timeline_path:
        slo = default_objectives(ttft_budget=ttft_budget)
    stats, _done = fleet_soak(
        model, replicas, workload, policy=policy, disagg=disagg,
        draft_model=draft_model, engine_kw=engine_kw, disagg_kw=disagg_kw,
        slo=slo, timeline_path=timeline_path)
    block = dict(stats)
    block["enabled"] = True
    block["policy"] = policy if replicas > 1 else None
    block["scan_layers"] = scan_layers_enabled()
    block["p99_ttft_seconds"] = stats["ttft"]["p99"]
    if baseline is not None:
        base_gp = baseline.get("goodput_tokens_per_sec")
        if base_gp and block.get("goodput_tokens_per_sec"):
            block["goodput_x_single"] = round(
                block["goodput_tokens_per_sec"] / base_gp, 3)
    if scaling_target is not None:
        block["scaling_target"] = float(scaling_target)
    if ttft_budget is not None:
        block["p99_ttft_budget"] = float(ttft_budget)
    return block


def upgrade_block(supervisor, workload, *, version=1, upgrade_tick=4,
                  kill_tick=None, kill_replica=0,
                  window_goodput_floor=None, window_ttft_budget=None,
                  max_ticks=400000):
    """The gateable ``"upgrade"`` JSON block (docs/SERVING.md "Process
    topology"; ``tools/bench_gate.py`` UPGRADE gate): drive ``workload``
    through a running :class:`.cluster.FleetSupervisor`, SIGKILL one
    replica mid-soak (``kill_tick``), start a rolling weight upgrade to
    ``version`` at ``upgrade_tick``, and reduce the run to its
    reference-free gate fields.

    The gate is reference-free because the invariants are absolute, not
    relative to a prior round:

    - ``conserved`` / ``lost_requests``: every submitted request reaches
      exactly one terminal outcome across kills, migrations, and
      reloads — zero lost requests is the whole point of the rollout
      machinery;
    - ``duplicate_stream_tokens`` / ``lost_stream_tokens``: every
      generated token is delivered to its stream callback exactly once,
      counted independently of the router's own suppression (the
      ``token_cb`` seam tallies raw deliveries; the engines report raw
      generation);
    - ``upgrade.complete`` and the upgraded-replica roster: the rollout
      must actually finish while serving;
    - the upgrade *window* (start tick -> finish tick) is cut out of the
      per-tick timeline: its goodput as a fraction of the whole-run
      goodput vs ``window_goodput_floor``, and the worst recent-p99
      TTFT inside the window vs ``window_ttft_budget``.  Both window
      gates engage only when their budget is embedded (passed here) —
      goodput counts COMPLETED requests' tokens, which is lumpy at
      small scale, so the floor is an explicit opt-in for runs big
      enough to make it meaningful; ``peak_outstanding`` lets the gate
      skip windows that were legitimately idle.
    """
    recorder = _telemetry.recorder()
    delivered = {}
    up_state = {"started": None, "finished": None, "peak_outstanding": 0}

    def token_cb(rid, tok):
        delivered[rid] = delivered.get(rid, 0) + 1

    def on_tick(tick):
        if kill_tick is not None and tick == kill_tick:
            child = supervisor.children.get(kill_replica)
            if child is not None:
                child.kill()
        if tick == upgrade_tick and up_state["started"] is None:
            supervisor.start_rolling_upgrade(version)
            up_state["started"] = tick
        if (up_state["started"] is not None
                and up_state["finished"] is None):
            # load actually present during the window: an idle-fleet
            # upgrade legitimately generates nothing, a stalled one
            # starves real work — the gate needs to tell them apart
            up_state["peak_outstanding"] = max(
                up_state["peak_outstanding"],
                len(supervisor._pending) + len(supervisor._inflight))
            if supervisor._upgrade is None:
                up_state["finished"] = tick

    stats, done = run_soak(supervisor, workload, max_ticks=max_ticks,
                           recorder=recorder, on_tick=on_tick,
                           token_cb=token_cb)
    # the soak can drain before the staged rollout (one stage per tick)
    # finishes — keep ticking the idle fleet until the upgrade lands, so
    # "complete" measures the machinery, not the workload length
    for _ in range(1000):
        if up_state["started"] is None or supervisor._upgrade is None:
            break
        supervisor.step()
    recorder.close()
    summary = supervisor.summary()
    upgrades = summary.get("upgrades") or []
    up = dict(upgrades[-1]) if upgrades else None
    complete = bool(up is not None and up.get("finished_tick") is not None
                    and up_state["started"] is not None)

    # token exactly-once accounting: deliveries counted at the callback
    # seam vs tokens the engines actually generated for COMPLETED
    # requests (cancelled streams legitimately deliver a partial prefix)
    delivered_total = sum(n for rid, n in delivered.items()
                          if rid in done)
    generated = stats["generated_tokens"]
    duplicates = max(0, delivered_total - generated)
    lost_tokens = max(0, generated - delivered_total)

    # cut the upgrade window out of the timeline
    window = {}
    samples = recorder.window()
    if up_state["started"] is not None:
        end_tick = (up_state["finished"]
                    if up_state["finished"] is not None else 10 ** 9)
        in_win = [s for s in samples
                  if up_state["started"] <= s.get("tags", {}).get(
                      "tick", -1) <= end_tick]
        if len(in_win) >= 2:
            t0, t1 = in_win[0]["ts"], in_win[-1]["ts"]
            g0 = in_win[0]["counters"].get(
                "soak_generated_tokens_total", 0)
            g1 = in_win[-1]["counters"].get(
                "soak_generated_tokens_total", 0)
            win_goodput = ((g1 - g0) / (t1 - t0)) if t1 > t0 else None
            overall = stats["goodput_tokens_per_sec"]
            ttfts = [s["values"]["ttft_p99_recent"] for s in in_win
                     if "ttft_p99_recent" in s.get("values", {})]
            window = {
                "start_tick": up_state["started"],
                "end_tick": up_state["finished"],
                "ticks": len(in_win),
                "peak_outstanding": up_state["peak_outstanding"],
                "generated_tokens": int(g1 - g0),
                "sim_seconds": round(t1 - t0, 6),
                "goodput_tokens_per_sec": (round(win_goodput, 2)
                                           if win_goodput is not None
                                           else None),
                "goodput_fraction": (round(win_goodput / overall, 4)
                                     if win_goodput is not None
                                     and overall else None),
                "p99_ttft_seconds": (round(max(ttfts), 6) if ttfts
                                     else None),
            }
            if window_goodput_floor is not None:
                window["goodput_floor_fraction"] = float(
                    window_goodput_floor)
            if window_ttft_budget is not None:
                window["p99_ttft_budget"] = float(window_ttft_budget)

    submitted = stats["requests"]
    terminal = (stats["completed"] + stats["cancelled"] + stats["shed"]
                + stats["rejected"])
    block = {
        "enabled": True,
        "backend": "proc" if supervisor.proc else "inproc",
        "replicas": stats["replicas"],
        "policy": supervisor._policy_name,
        "submitted": submitted,
        "served": stats["completed"],
        "cancelled": stats["cancelled"],
        "shed": stats["shed"],
        "rejected": stats["rejected"],
        "conserved": bool(stats["outcomes_conserved"]),
        "lost_requests": max(0, submitted - terminal),
        "generated_tokens": generated,
        "delivered_stream_tokens": delivered_total,
        "duplicate_stream_tokens": duplicates,
        "lost_stream_tokens": lost_tokens,
        "goodput_tokens_per_sec": stats["goodput_tokens_per_sec"],
        "sim_seconds": stats["sim_seconds"],
        "wall_seconds": stats["wall_seconds"],
        "ttft": stats["ttft"],
        "upgrade": {
            "version": version,
            "requested_tick": upgrade_tick,
            "started_tick": up_state["started"],
            "finished_tick": up_state["finished"],
            "complete": complete,
            "upgraded_replicas": (up or {}).get("upgraded", []),
            "migrated_requests": (up or {}).get("migrated", 0),
            "migration_bytes": (up or {}).get("migrate_bytes", 0),
        },
        "kill": ({
            "tick": kill_tick,
            "replica": kill_replica,
            "respawns": summary["respawns"],
            "lease_deaths": summary["lease_deaths"],
        } if kill_tick is not None else None),
        "supervisor": summary,
    }
    if window:
        block["window"] = window
    return block


def partition_block(supervisor, workload, *, host=None, sever_tick=4,
                    heal_tick=None, kill_agent=False,
                    upgrade_version=None, upgrade_tick=None,
                    max_ticks=400000, settle_ticks=2000):
    """The gateable ``"partition"`` JSON block (docs/SERVING.md
    "Cross-host topology"; ``tools/bench_gate.py`` PARTITION gate):
    drive ``workload`` through a hosts-mode
    :class:`.cluster.FleetSupervisor`, partition one whole host away
    mid-soak (``sever_tick``), optionally SIGKILL its agent
    (``kill_agent``), heal the partition (``heal_tick``, or after the
    soak drains), optionally overlap a rolling upgrade, and reduce the
    run to reference-free gate fields.

    The invariants are absolute:

    - ``conserved`` / ``lost_requests``: every admitted request reaches
      exactly one terminal outcome even though a whole host's replicas
      were fenced and their work replayed;
    - ``duplicate_stream_tokens``: the fencing epochs mean no rid is
      ever served by two replicas — a stale lease's late tokens are
      dropped at both ends, so the callback seam must see **zero**
      duplicate deliveries (and zero losses) across the partition;
    - ``fleet_live_at_drain``: the fleet is back at target size with
      every replica healthy once the run settles — replay + respawn
      actually reconverged;
    - ``partition.healed``: with a surviving agent the severed host
      returns to ``alive`` (its stranded workers are quarantined via
      the epoch bump, then adopted or retired); with ``kill_agent``
      the host legitimately stays severed and this field is not gated.
    """
    recorder = _telemetry.recorder()
    delivered = {}
    state = {"severed": None, "healed": None, "up_started": None}
    if host is None:
        host = next(iter(supervisor.host_handles), None)
    if host is None:
        raise ValueError("partition_block needs a hosts-mode supervisor "
                         "(FleetSupervisor(..., hosts=N))")

    def token_cb(rid, tok):
        delivered[rid] = delivered.get(rid, 0) + 1

    def on_tick(tick):
        if tick == sever_tick and state["severed"] is None:
            supervisor.sever_host(host)
            if kill_agent:
                supervisor.host_handles[host].kill_agent()
            state["severed"] = tick
        if (heal_tick is not None and tick >= heal_tick
                and state["severed"] is not None
                and state["healed"] is None and not kill_agent):
            supervisor.heal_host(host)
            state["healed"] = tick
        if (upgrade_tick is not None and tick == upgrade_tick
                and state["up_started"] is None):
            supervisor.start_rolling_upgrade(upgrade_version or 1)
            state["up_started"] = tick

    stats, done = run_soak(supervisor, workload, max_ticks=max_ticks,
                           recorder=recorder, on_tick=on_tick,
                           token_cb=token_cb)
    # post-soak: heal a partition the soak outlived, finish any staged
    # rollout, and let the fleet settle back to target size — the gate
    # measures the recovery machinery, not the workload length
    if (state["severed"] is not None and state["healed"] is None
            and not kill_agent):
        supervisor.heal_host(host)
        state["healed"] = "post_drain"
    for _ in range(settle_ticks):
        live = sum(1 for h in supervisor.router.replicas
                   if h.healthy and not h.retired)
        up_done = (state["up_started"] is None
                   or supervisor._upgrade is None)
        host_ok = (kill_agent or state["severed"] is None
                   or supervisor.host_handles[host].state == "alive")
        if live >= supervisor.n_target and up_done and host_ok:
            break
        supervisor.step()
        time.sleep(0.001)
    recorder.close()
    summary = supervisor.summary()

    live = sum(1 for h in supervisor.router.replicas
               if h.healthy and not h.retired)
    # fencing evidence from both ends of every link that still answers
    fenced_replies = sum(
        getattr(h.engine, "fenced_replies", 0) or 0
        for h in supervisor.router.replicas)
    server_fenced = quarantines = 0
    for h in supervisor.router.replicas:
        if not (h.healthy and not h.retired):
            continue
        try:
            st = h.engine.lease()
        except Exception:
            continue
        server_fenced += int(st.get("fenced", 0) or 0)
        quarantines += int(st.get("quarantines", 0) or 0)

    delivered_total = sum(n for rid, n in delivered.items()
                          if rid in done)
    generated = stats["generated_tokens"]
    submitted = stats["requests"]
    terminal = (stats["completed"] + stats["cancelled"] + stats["shed"]
                + stats["rejected"])
    healed = (state["severed"] is None
              or supervisor.host_handles[host].state == "alive")
    block = {
        "enabled": True,
        "backend": "proc" if supervisor.proc else "inproc",
        "replicas": stats["replicas"],
        "hosts": summary["hosts"],
        "policy": supervisor._policy_name,
        "submitted": submitted,
        "served": stats["completed"],
        "cancelled": stats["cancelled"],
        "shed": stats["shed"],
        "rejected": stats["rejected"],
        "conserved": bool(stats["outcomes_conserved"]),
        "lost_requests": max(0, submitted - terminal),
        "generated_tokens": generated,
        "delivered_stream_tokens": delivered_total,
        "duplicate_stream_tokens": max(0, delivered_total - generated),
        "lost_stream_tokens": max(0, generated - delivered_total),
        "goodput_tokens_per_sec": stats["goodput_tokens_per_sec"],
        "sim_seconds": stats["sim_seconds"],
        "wall_seconds": stats["wall_seconds"],
        "ttft": stats["ttft"],
        "fleet_live_at_drain": bool(live >= supervisor.n_target),
        "partition": {
            "host": host,
            "sever_tick": state["severed"],
            "heal_tick": state["healed"],
            "agent_killed": bool(kill_agent),
            "healed": bool(healed),
            "host_severs": summary["host_severs"],
            "host_heals": summary["host_heals"],
            "adopted_workers": summary["adopted_workers"],
            "fenced_replies": fenced_replies,
            "server_fenced_calls": server_fenced,
            "quarantines": quarantines,
            "lease_epoch": summary["lease_epoch"],
        },
        "migration": {
            "rescued": summary["rescued"],
            "rebalanced": summary["rebalanced"],
            "migrated_requests": summary["migrated_requests"],
            "migration_bytes": summary["migration_bytes"],
            "prefix_warm_pages": summary["prefix_warm_pages"],
        },
        "upgrade": ({
            "version": upgrade_version or 1,
            "requested_tick": upgrade_tick,
            "started_tick": state["up_started"],
            "complete": bool(state["up_started"] is not None
                             and supervisor._upgrade is None),
        } if upgrade_tick is not None else None),
        "respawns": summary["respawns"],
        "supervisor": summary,
    }
    return block
