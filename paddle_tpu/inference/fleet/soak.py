"""Serving soak harness: Poisson arrivals, mixed prompts, N replicas.

Drives synthetic traffic through an engine, a DisaggregatedEngine, or a
FleetRouter and reduces the run to the ``"serving"`` JSON block that
``tools/serve_bench.py`` emits and ``tools/bench_gate.py`` gates
(docs/SERVING.md soak recipe).

**Simulated-parallel clock.** In deployment each replica is its own
mesh; in this process they tick sequentially on one host. Wall time
would therefore show ~1x scaling no matter how good the router is, so
the soak advances a simulated clock instead: each fleet tick costs
``max`` over the replicas' measured step times (they would run
concurrently) plus the router's own host time (it is serial). Goodput
and TTFT percentiles are computed on that clock; ``wall_seconds`` is
also reported so nothing hides. A single-replica run's simulated clock
equals its wall clock, making ``goodput_x_single`` an honest scaling
ratio. The block records ``"simulated_parallel": true`` whenever more
than one replica contributed.
"""
from __future__ import annotations

import time
from collections import deque

import numpy as np

__all__ = ["build_workload", "run_soak", "percentile", "fleet_soak",
           "soak_block"]


def percentile(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (None when empty)."""
    if not sorted_vals:
        return None
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def build_workload(n_requests, arrival_rate, prompt_lens, vocab_size,
                   shared_prefix=0, sampled_fraction=0.0,
                   deadline_seconds=None, seed=0):
    """Synthetic request list [(arrival_time, prompt, kwargs)] sorted by
    arrival: Poisson arrivals at ``arrival_rate`` req/sec (simulated
    seconds), prompt lengths drawn from ``prompt_lens``, an optional
    shared system prefix (the prefix-affinity workload), an optional
    sampled-request fraction, and optional per-request deadlines."""
    rng = np.random.default_rng(seed)
    prefix = [int(t) for t in rng.integers(1, vocab_size, shared_prefix)]
    t = 0.0
    out = []
    for _ in range(n_requests):
        t += float(rng.exponential(1.0 / arrival_rate))
        n = int(rng.choice(prompt_lens))
        tail_n = max(1, n - shared_prefix)
        prompt = prefix + [int(x) for x in
                           rng.integers(1, vocab_size, tail_n)]
        kw = {}
        if sampled_fraction and rng.random() < sampled_fraction:
            kw.update(temperature=0.7, top_k=8, top_p=0.95)
        if deadline_seconds is not None:
            kw["deadline_seconds"] = deadline_seconds
        out.append((t, prompt, kw))
    return out


def _spec_stats(eng):
    if getattr(eng, "spec_draft_tokens", 0):
        return {"ticks": eng.spec_ticks,
                "drafted": eng.spec_draft_tokens,
                "accepted": eng.spec_accepted_tokens,
                "acceptance_rate": round(eng.spec_acceptance_rate, 4)}
    return None


def _engine_stats(eng):
    """Per-engine counters, transparent to DisaggregatedEngine."""
    if hasattr(eng, "prefill") and hasattr(eng, "decode"):
        p, d = eng.prefill, eng.decode
        return {"disaggregated": True,
                "preemptions": p.preemptions + d.preemptions,
                "prefix_hit_pages": p.prefix_cache_hits,
                "cancellations": p.cancellations + d.cancellations,
                "handoffs": eng.handoffs,
                "handoff_bytes": eng.handoff_bytes,
                "int8_kv": d.int8_kv,
                "spec": _spec_stats(d)}
    return {"disaggregated": False,
            "preemptions": eng.preemptions,
            "prefix_hit_pages": eng.prefix_cache_hits,
            "cancellations": eng.cancellations,
            "handoffs": 0, "handoff_bytes": 0,
            "int8_kv": eng.int8_kv,
            "spec": _spec_stats(eng)}


def run_soak(target, workload, warmup=True, max_ticks=200000):
    """Drive ``workload`` through ``target`` (engine / disagg /
    FleetRouter) and return the raw soak stats dict. Cold start
    (construction is the caller's; compile is ours via ``warmup()``) is
    measured per engine and reported as the max across replicas — in
    deployment replicas spin up concurrently."""
    router = hasattr(target, "replicas")
    engines = ([h.engine for h in target.replicas] if router
               else [target])
    cold = []
    if warmup:
        for e in engines:
            cold.append(e.warmup())
    n_requests = len(workload)
    pending = deque(sorted(workload, key=lambda w: w[0]))
    arrival = {}
    plen = {}
    first_seen = {}
    ttfts = []
    sim_t = 0.0
    done = {}
    wall0 = time.perf_counter()

    def on_token(rid, tok):
        first_seen.setdefault(rid, None)

    for _tick in range(max_ticks):
        # admit every arrival the simulated clock has reached; when the
        # fleet is fully idle, jump the clock to the next arrival
        # instead of spinning empty ticks
        n_cancelled = len(getattr(target, "cancelled", {}) or {})
        if pending and len(done) + n_cancelled >= len(arrival):
            sim_t = max(sim_t, pending[0][0])
        while pending and pending[0][0] <= sim_t:
            arr, prompt, kw = pending.popleft()
            rid = target.submit(prompt, on_token=on_token, **kw)
            arrival[rid] = arr
            plen[rid] = len(prompt)
        before_first = set(first_seen)
        if router:
            busy0 = [h.busy_seconds for h in target.replicas]
            t0 = time.perf_counter()
            out = target.step()
            wall = time.perf_counter() - t0
            deltas = [h.busy_seconds - b
                      for h, b in zip(target.replicas, busy0)]
            # replicas tick in parallel in deployment; router host work
            # is serial on top
            cost = (max(deltas) if deltas else 0.0) + max(
                0.0, wall - sum(deltas))
        else:
            t0 = time.perf_counter()
            out = target.step()
            cost = time.perf_counter() - t0
        sim_t += cost
        for rid in set(first_seen) - before_first:
            if rid in arrival:
                ttfts.append(sim_t - arrival[rid])
        done.update(out)
        cancelled = dict(getattr(target, "cancelled", {}) or {})
        if not pending and len(done) + len(cancelled) >= n_requests:
            break
    else:
        raise TimeoutError("soak did not drain")
    wall_seconds = time.perf_counter() - wall0
    cancelled = dict(getattr(target, "cancelled", {}) or {})
    # goodput counts GENERATED tokens only (completions return
    # prompt+generated; the prompt was the caller's)
    gen_tokens = sum(max(0, len(ids) - plen.get(rid, 0))
                     for rid, ids in done.items())
    ttfts.sort()
    per_engine = [_engine_stats(e) for e in engines]
    stats = {
        "requests": n_requests,
        "completed": len(done),
        "cancelled": len(cancelled),
        "replicas": len(engines),
        "generated_tokens": gen_tokens,
        "sim_seconds": round(sim_t, 6),
        "wall_seconds": round(wall_seconds, 6),
        "simulated_parallel": len(engines) > 1,
        "goodput_tokens_per_sec": (round(gen_tokens / sim_t, 2)
                                   if sim_t > 0 else None),
        "ttft": {
            "count": len(ttfts),
            "p50": percentile(ttfts, 0.50),
            "p95": percentile(ttfts, 0.95),
            "p99": percentile(ttfts, 0.99),
            "mean": (sum(ttfts) / len(ttfts)) if ttfts else None,
        },
        "cold_start_seconds": (round(max(cold), 4) if cold else None),
        "cold_start_seconds_total": (round(sum(cold), 4) if cold
                                     else None),
        "engines": per_engine,
    }
    if router:
        stats["router"] = {
            "policy": target._policy_name,
            "dispatched": [h.dispatched for h in target.replicas],
            "deaths": sum(1 for h in target.replicas if not h.healthy),
            "requeues": target.requeues,
        }
    return stats, done


def fleet_soak(model, n_replicas, workload, *, policy="least_loaded",
               disagg=False, draft_model=None, engine_kw=None,
               disagg_kw=None, max_ticks=200000):
    """Build ``n_replicas`` engines (or disaggregated pairs) over
    ``model``, route them (FleetRouter when n>1), drive ``workload``,
    return the soak stats. One entry point for tools/serve_bench.py and
    ``bench.py --serve``."""
    from ..serving import ContinuousBatchingEngine
    from .disagg import DisaggregatedEngine
    from .router import RID_STRIDE, FleetRouter

    engine_kw = dict(engine_kw or {})
    engines = []
    for i in range(n_replicas):
        if disagg:
            engines.append(DisaggregatedEngine(
                model, rid_base=i * RID_STRIDE, draft_model=draft_model,
                **dict(disagg_kw or {}), **engine_kw))
        else:
            engines.append(ContinuousBatchingEngine(
                model, rid_base=i * RID_STRIDE, draft_model=draft_model,
                **engine_kw))
    target = (engines[0] if n_replicas == 1
              else FleetRouter(engines, policy=policy))
    return run_soak(target, workload, max_ticks=max_ticks)


def soak_block(model, *, replicas, workload, policy="least_loaded",
               disagg=False, draft_model=None, engine_kw=None,
               disagg_kw=None, baseline=None, scaling_target=None,
               ttft_budget=None):
    """One gateable ``"serving"`` JSON block (docs/SERVING.md contract):
    the soak stats plus the gate fields — ``p99_ttft_seconds`` vs
    ``p99_ttft_budget``, ``goodput_x_single`` vs ``scaling_target``
    (both gates engage only when their bound is present), the replica
    ``cold_start_seconds`` (gated vs the previous round at the same
    scan mode, like the compile gate), and the scan mode itself.
    ``baseline`` is a prior single-replica block to scale against."""
    from ...models.gpt import scan_layers_enabled

    stats, _done = fleet_soak(
        model, replicas, workload, policy=policy, disagg=disagg,
        draft_model=draft_model, engine_kw=engine_kw, disagg_kw=disagg_kw)
    block = dict(stats)
    block["enabled"] = True
    block["policy"] = policy if replicas > 1 else None
    block["scan_layers"] = scan_layers_enabled()
    block["p99_ttft_seconds"] = stats["ttft"]["p99"]
    if baseline is not None:
        base_gp = baseline.get("goodput_tokens_per_sec")
        if base_gp and block.get("goodput_tokens_per_sec"):
            block["goodput_x_single"] = round(
                block["goodput_tokens_per_sec"] / base_gp, 3)
    if scaling_target is not None:
        block["scaling_target"] = float(scaling_target)
    if ttft_budget is not None:
        block["p99_ttft_budget"] = float(ttft_budget)
    return block
