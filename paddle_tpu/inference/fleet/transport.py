"""Pluggable RPC transport: the fleet's replicas as REAL processes.

One replica link = one :class:`Transport` (client half, owned by a
:class:`RemoteEngine` proxy inside the parent) talking to one
:class:`ReplicaServer` (server half, wrapping a live
``ContinuousBatchingEngine`` — in a child process over the socket
transport, or in-process behind the loopback for tests and the
``PTPU_FLEET_PROC=0`` escape hatch).  Frames are the length-prefixed
msgpack format from :mod:`.wire`.

Failure semantics, end to end:

- every call gets a fresh monotone id; retries RE-SEND the same id with
  exponential backoff + deterministic jitter.  The server keeps a
  bounded cache of id -> encoded reply, so a duplicated or re-sent
  frame replays the cached reply instead of re-executing — submits and
  steps stay exactly-once under drop/duplicate/corrupt chaos.
- transport faults raise :class:`TransportError` (a ``ConnectionError``
  subclass) / :class:`TransportTimeout` / :class:`TransportSevered`, so
  ``classify_step_exception`` sees them as TRANSIENT and the router's
  breakers back off + replay instead of killing the replica.
- a corrupt frame in either direction raises :class:`.wire.FrameError`
  loudly at the decode site and is retried by the caller; garbage never
  reaches an engine.

Streaming: ``on_token`` callbacks cannot cross a process boundary, so
the server buffers ``(rid, token)`` events and every ``step`` /
``stream`` reply drains them; :class:`RemoteEngine` replays the events
into the client-side callbacks, preserving the router's ``_delivered``
exactly-once suppression machinery unchanged.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from collections import OrderedDict, deque

from ... import telemetry as _telemetry
from . import wire
from .overload import outcome_from_wire, outcome_to_wire

_CALLS = _telemetry.counter(
    "transport_calls_total", "fleet RPC calls by method and outcome",
    labelnames=("method", "outcome"))
_RETRIES = _telemetry.counter(
    "transport_retries_total", "fleet RPC attempts beyond the first")
_BYTES = _telemetry.counter(
    "transport_bytes_total", "fleet RPC frame bytes by direction",
    labelnames=("direction",))


class TransportError(ConnectionError):
    """Base transport fault (ConnectionError => transient taxonomy)."""


class TransportTimeout(TransportError):
    """The per-call deadline elapsed without a matching reply."""


class TransportSevered(TransportError):
    """The link is gone: peer dead, socket closed, or chaos-severed."""


class SimulatedCrash(BaseException):
    """Raised by the test-only ``crash`` RPC; deliberately NOT an
    Exception so the server dispatch cannot swallow it — it unwinds to
    the worker's top level and exercises the unhandled-crash flight
    path for real."""


#: per-method call timeouts (seconds).  warmup/reload compile real
#: programs; steps decode real tokens; everything else is bookkeeping.
DEFAULT_TIMEOUTS = {
    "hello": 120.0,
    "warmup": 600.0,
    "reload_weights": 600.0,
    "step": 300.0,
    "drain": 300.0,
    "extract": 120.0,
    "inject": 120.0,
}
DEFAULT_TIMEOUT = 60.0


class _Call:
    __slots__ = ("id", "method", "frame", "needs_send")

    def __init__(self, call_id, method, frame, needs_send):
        self.id = call_id
        self.method = method
        self.frame = frame
        self.needs_send = needs_send


class Transport:
    """Client half of one replica link.

    Subclasses implement ``_send(frame_bytes)`` and
    ``_recv_bytes(timeout) -> bytes`` (one complete frame).  The retry /
    timeout / jitter machinery lives here so every transport shares the
    exact same failure semantics.  ``begin()``/``finish()`` split a call
    so a supervisor can issue ``step`` to the whole fleet concurrently
    and collect replies afterwards (real wall-clock parallelism)."""

    def __init__(self, *, timeout=DEFAULT_TIMEOUT, timeouts=None,
                 max_retries=3, backoff=0.05, backoff_max=2.0,
                 jitter=0.25, seed=0, codec=None,
                 clock=time.monotonic, sleep=time.sleep):
        self.timeout = float(timeout)
        self.timeouts = dict(DEFAULT_TIMEOUTS)
        if timeouts:
            self.timeouts.update(timeouts)
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.codec = codec
        self.clock = clock
        self.sleep = sleep
        self._next_id = 1
        self._lock = threading.Lock()
        self.retries = 0
        self.calls = 0
        self.backoffs = []            # realized backoff schedule (tests)
        self.last_ok_time = clock()   # heartbeat-lease anchor
        self.last_load = None         # server-attached load snapshot

    # -- subclass surface ---------------------------------------------------
    def _send(self, frame):
        raise NotImplementedError

    def _recv_bytes(self, timeout):
        raise NotImplementedError

    def close(self):
        pass

    # -- call machinery -----------------------------------------------------
    def _backoff_for(self, attempt):
        """attempt >= 1.  Deterministic jitter: a hash mix of the link
        seed and the call ordinal, NOT random — reproducible runs, but
        distinct links (and distinct calls) still decorrelate."""
        base = min(self.backoff * (2.0 ** (attempt - 1)), self.backoff_max)
        mix = ((self.seed * 2654435761 + self.calls * 40503 + attempt)
               & 0xFFFFFFFF)
        frac = (mix % 997) / 996.0
        delay = base * (1.0 + self.jitter * frac)
        self.backoffs.append(delay)
        return delay

    def begin(self, method, args=None):
        """Send a call without waiting for the reply."""
        with self._lock:
            call_id = self._next_id
            self._next_id += 1
        self.calls += 1
        frame = wire.encode_frame(
            {"id": call_id, "m": method, "a": args or {}}, self.codec)
        needs_send = False
        try:
            self._send(frame)
        except OSError:
            needs_send = True      # finish() retries the send
        return _Call(call_id, method, frame, needs_send)

    def finish(self, call, timeout=None):
        """Wait for (and if needed re-drive) a begun call's reply."""
        if timeout is None:
            timeout = self.timeouts.get(call.method, self.timeout)
        last_exc = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                self.retries += 1
                _RETRIES.inc()
                self.sleep(self._backoff_for(attempt))
                call.needs_send = True
            if call.needs_send:
                try:
                    self._send(call.frame)
                    call.needs_send = False
                except OSError as exc:
                    last_exc = exc
                    continue
            try:
                reply = self._recv_reply(call.id, timeout)
            except (wire.FrameError, OSError) as exc:
                last_exc = exc
                continue
            _CALLS.inc(labels=(call.method, "ok"))
            return self._unwrap(reply)
        _CALLS.inc(labels=(call.method, "error"))
        if isinstance(last_exc, TransportError):
            raise last_exc
        if isinstance(last_exc, (TimeoutError, socket.timeout)):
            raise TransportTimeout(
                f"rpc {call.method!r}: no reply within {timeout}s "
                f"after {self.max_retries + 1} attempts") from last_exc
        raise TransportSevered(
            f"rpc {call.method!r}: link failed after "
            f"{self.max_retries + 1} attempts ({last_exc!r})") from last_exc

    def call(self, method, args=None, timeout=None):
        return self.finish(self.begin(method, args), timeout)

    def _recv_reply(self, call_id, timeout):
        """Read frames until the one matching ``call_id``.  Stale or
        duplicated replies (chaos duplication, an earlier abandoned
        attempt's late reply) are dropped by id — ids are never
        reused, so a mismatch is always safe to discard."""
        deadline = self.clock() + timeout
        while True:
            remaining = deadline - self.clock()
            if remaining <= 0:
                raise TransportTimeout(
                    f"rpc id {call_id}: reply timeout after {timeout}s")
            msg = wire.decode_frame(self._recv_bytes(remaining))
            if isinstance(msg, dict) and msg.get("id") == call_id:
                return msg

    def _unwrap(self, reply):
        self.last_ok_time = self.clock()
        if reply.get("load") is not None:
            self.last_load = reply["load"]
        err = reply.get("err")
        if err is not None:
            raise outcome_from_wire(err)
        return reply.get("ok")


# ---------------------------------------------------------------------------
# Loopback (in-process) transport
# ---------------------------------------------------------------------------
class LoopbackTransport(Transport):
    """In-process transport over a real byte-level frame boundary: the
    request is ENCODED, handed to the server as bytes, and the reply
    decoded — so codec, idempotency, and chaos corruption behave
    exactly as over a socket, minus the kernel."""

    def __init__(self, server, **kw):
        super().__init__(**kw)
        self.server = server
        self._rx = deque()

    def _send(self, frame):
        if self.server.dead:
            raise TransportSevered("loopback: peer is dead")
        _BYTES.inc(len(frame), labels=("tx",))
        reply = self.server.handle_frame(bytes(frame))
        if reply is not None:
            _BYTES.inc(len(reply), labels=("rx",))
            self._rx.append(reply)

    def _recv_bytes(self, timeout):
        if not self._rx:
            raise TransportTimeout("loopback: no reply buffered")
        return self._rx.popleft()


# ---------------------------------------------------------------------------
# Socket transport
# ---------------------------------------------------------------------------
def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportSevered("socket: peer closed the connection")
        buf += chunk
    return bytes(buf)


class SocketTransport(Transport):
    """Length-prefixed frames over TCP (loopback interface by default).
    Connects lazily and reconnects after any fault, so a respawned
    worker on the same port is picked up by the normal retry path."""

    def __init__(self, host, port, *, connect_timeout=10.0, **kw):
        super().__init__(**kw)
        self.host = host
        self.port = int(port)
        self.connect_timeout = float(connect_timeout)
        self._sock = None

    def _ensure_conn(self):
        if self._sock is None:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
        return self._sock

    def _drop_conn(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _send(self, frame):
        try:
            sock = self._ensure_conn()
            sock.sendall(frame)
            _BYTES.inc(len(frame), labels=("tx",))
        except OSError:
            self._drop_conn()
            raise

    def _recv_bytes(self, timeout):
        try:
            sock = self._ensure_conn()
            sock.settimeout(max(timeout, 0.001))
            header = _recv_exact(sock, wire.HEADER_SIZE)
            _, length, _ = wire.parse_header(header)
            payload = _recv_exact(sock, length)
        except socket.timeout as exc:
            raise TransportTimeout("socket: reply timeout") from exc
        except wire.FrameError:
            # unsynced stream — drop the connection so the next attempt
            # starts on a clean frame boundary
            self._drop_conn()
            raise
        except OSError:
            self._drop_conn()
            raise
        _BYTES.inc(len(header) + len(payload), labels=("rx",))
        return header + payload

    def close(self):
        self._drop_conn()


# ---------------------------------------------------------------------------
# Server half
# ---------------------------------------------------------------------------
class ReplicaServer:
    """RPC dispatcher over one live engine.  ``handle_frame(bytes) ->
    bytes`` is transport-agnostic: the loopback calls it directly, the
    socket loop feeds it.  Replies carry the engine's ``load()``
    snapshot so the client's routing view is refreshed by every call
    with zero extra round trips."""

    IDEMPOTENCY_WINDOW = 128

    def __init__(self, engine, *, replica_id=0, model_factory=None,
                 scrape_port=None, codec=None):
        self.engine = engine
        self.replica_id = replica_id
        self.model_factory = model_factory
        self.scrape_port = scrape_port
        self.codec = codec
        self.dead = False
        self.shutting_down = False
        self.weights_version = 0
        self._done = OrderedDict()     # call id -> encoded reply bytes
        self._events = []              # buffered (rid, token) stream
        self.handled = 0
        self.duplicates = 0

    # engine token streaming lands in the buffer; step/stream drain it
    def _event_cb(self, rid, tok):
        self._events.append((int(rid), int(tok)))

    def handle_frame(self, data):
        try:
            msg = wire.decode_frame(data)
        except wire.FrameError as exc:
            # can't know the call id of a corrupt request — answer with
            # an unaddressed error frame; the client drops it and
            # re-sends on its own timeout
            return wire.encode_frame(
                {"id": None, "err": outcome_to_wire(exc)}, self.codec)
        call_id = msg.get("id")
        cached = self._done.get(call_id)
        if cached is not None:
            # duplicate / re-sent frame: replay, do NOT re-execute
            self.duplicates += 1
            self._done.move_to_end(call_id)
            return cached
        self.handled += 1
        try:
            result = self._dispatch(msg.get("m"), msg.get("a") or {})
            reply = {"id": call_id, "ok": result}
        except SimulatedCrash:
            raise
        except Exception as exc:
            reply = {"id": call_id, "err": outcome_to_wire(exc)}
        try:
            reply["load"] = self.engine.load()
        except Exception:
            reply["load"] = None
        out = wire.encode_frame(reply, self.codec)
        if call_id is not None:
            self._done[call_id] = out
            while len(self._done) > self.IDEMPOTENCY_WINDOW:
                self._done.popitem(last=False)
        return out

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, method, a):
        handler = getattr(self, "_rpc_" + str(method), None)
        if handler is None:
            raise ValueError(f"rpc: unknown method {method!r}")
        return handler(a)

    def _rpc_hello(self, a):
        eng = self.engine
        return {
            "replica_id": self.replica_id,
            "pid": os.getpid(),
            "max_slots": eng.max_slots,
            "max_new_tokens": eng.max_new_tokens,
            "page": eng.page,
            "pages_per_seq": eng.pages_per_seq,
            "int8_kv": bool(getattr(eng, "int8_kv", False)),
            "scrape_port": self.scrape_port,
            "weights_version": self.weights_version,
        }

    def _rpc_ping(self, a):
        return {"ok": True, "replica_id": self.replica_id,
                "pid": os.getpid()}

    def _rpc_submit(self, a):
        rid = self.engine.submit(
            a["prompt"],
            temperature=a.get("temperature", 0.0),
            top_k=a.get("top_k", 0),
            top_p=a.get("top_p", 1.0),
            on_token=self._event_cb,
            deadline_seconds=a.get("deadline_seconds"),
            rid=a.get("rid"))
        return int(rid)

    def _drain_events(self):
        ev, self._events = self._events, []
        return ev

    def _drain_cancelled(self):
        c = {int(r): str(reason)
             for r, reason in self.engine.cancelled.items()}
        self.engine.cancelled.clear()
        return c

    def _rpc_step(self, a):
        done = self.engine.step()
        return {"done": {int(r): [int(t) for t in ids]
                         for r, ids in done.items()},
                "events": self._drain_events(),
                "cancelled": self._drain_cancelled()}

    def _rpc_stream(self, a):
        # drain buffered token events without stepping
        return {"events": self._drain_events(),
                "cancelled": self._drain_cancelled()}

    def _rpc_cancel(self, a):
        ok = bool(self.engine.cancel(a["rid"],
                                     reason=a.get("reason", "client")))
        return {"ok": ok, "cancelled": self._drain_cancelled()}

    def _rpc_load(self, a):
        return self.engine.load()

    def _rpc_prefix_match_pages(self, a):
        return int(self.engine.prefix_match_pages(a["tokens"]))

    def _rpc_extract(self, a):
        req = self.engine.extract(a["slot"])
        return wire.request_to_wire(req)

    def _rpc_inject(self, a):
        req = wire.request_from_wire(a["req"])
        req.on_token = self._event_cb
        self.engine.inject(req)
        return int(req.rid)

    def _rpc_drain(self, a):
        """Serialize EVERYTHING queued or running and empty the engine:
        the KV-migration point of a rolling upgrade.  Occupied slots go
        through ``extract()`` (host KV snapshot rides along); waiting
        requests ship as-is."""
        eng = self.engine
        running = []
        for i, r in enumerate(eng._slots):
            if r is not None:
                running.append(wire.request_to_wire(eng.extract(i)))
        waiting = []
        while eng._waiting:
            waiting.append(wire.request_to_wire(eng._waiting.popleft()))
        return {"running": running, "waiting": waiting}

    def _rpc_reload_weights(self, a):
        version = a.get("version")
        model = None
        if self.model_factory is not None:
            model = self.model_factory(version=version)
        self.engine.reload_weights(model)
        if version is not None:
            self.weights_version = version
        return {"weights_version": self.weights_version}

    def _rpc_warmup(self, a):
        self.engine.warmup(sample=a.get("sample", False))
        return {"build_seconds": self.engine.build_seconds}

    def _rpc_stats(self, a):
        from .soak import _engine_stats
        return _engine_stats(self.engine)

    def _rpc_shutdown(self, a):
        self.shutting_down = True
        return {"ok": True}

    def _rpc_crash(self, a):
        raise SimulatedCrash("chaos: crash requested over RPC")


# ---------------------------------------------------------------------------
# Socket serve loop (runs in the worker process)
# ---------------------------------------------------------------------------
class SocketServerLoop:
    """Accept one parent connection at a time and pump frames through a
    :class:`ReplicaServer` until it flags shutdown.  A fresh connection
    after a drop (parent restarted its transport) is business as usual."""

    def __init__(self, server, *, host="127.0.0.1", port=0):
        self.server = server
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(4)
        self.host, self.port = self._listener.getsockname()[:2]

    def serve_forever(self, accept_timeout=1.0):
        self._listener.settimeout(accept_timeout)
        while not self.server.shutting_down:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                self._pump(conn)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
        self._listener.close()

    def _pump(self, conn):
        conn.settimeout(0.5)
        while not self.server.shutting_down:
            try:
                header = _recv_exact(conn, wire.HEADER_SIZE)
            except socket.timeout:
                continue
            except TransportSevered:
                return                     # parent dropped; re-accept
            try:
                _, length, _ = wire.parse_header(header)
                conn.settimeout(10.0)
                payload = _recv_exact(conn, length)
            except wire.FrameError:
                return                     # unsynced stream; re-accept
            except (socket.timeout, TransportSevered):
                return
            finally:
                conn.settimeout(0.5)
            reply = self.server.handle_frame(header + payload)
            if reply is not None:
                try:
                    conn.sendall(reply)
                except OSError:
                    return


# ---------------------------------------------------------------------------
# Client proxy
# ---------------------------------------------------------------------------
class RemoteEngine:
    """Duck-types the engine surface the fleet consumes (submit / step /
    cancel / load / prefix_match_pages / cancelled / extract / inject /
    reload_weights / warmup), so it drops into a ``ReplicaHandle``
    unchanged.  Token events from step replies are replayed into
    client-side callbacks; ``load()`` is served from the snapshot the
    server attaches to every reply (zero extra round trips on the
    routing hot path)."""

    def __init__(self, transport, *, hello=True):
        self.transport = transport
        self.cancelled = {}           # client-side mirror, router drains
        self._cbs = {}                # rid -> client on_token callback
        self._load = None
        self._pending_step = None
        self.pid = None
        self.scrape_port = None
        self.replica_id = None
        self.weights_version = 0
        if hello:
            info = transport.call("hello")
            self.max_slots = info["max_slots"]
            self.max_new_tokens = info["max_new_tokens"]
            self.page = info["page"]
            self.pages_per_seq = info["pages_per_seq"]
            self.int8_kv = info["int8_kv"]
            self.pid = info["pid"]
            self.scrape_port = info.get("scrape_port")
            self.replica_id = info.get("replica_id")
            self.weights_version = info.get("weights_version", 0)
            self._refresh_load()

    # -- bookkeeping --------------------------------------------------------
    def _refresh_load(self):
        if self.transport.last_load is not None:
            self._load = self.transport.last_load

    def _absorb(self, reply):
        """Fold a step/stream/cancel reply's events + cancels into the
        client-side stream state, exactly once per reply."""
        for rid, tok in reply.get("events") or []:
            cb = self._cbs.get(rid)
            if cb is not None:
                cb(rid, tok)
        for rid, reason in (reply.get("cancelled") or {}).items():
            rid = int(rid)
            self.cancelled[rid] = reason
            self._cbs.pop(rid, None)
        self._refresh_load()

    # -- engine surface -----------------------------------------------------
    def submit(self, prompt_ids, temperature=0.0, top_k=0, top_p=1.0,
               on_token=None, deadline_seconds=None, rid=None):
        out = self.transport.call("submit", {
            "prompt": [int(t) for t in prompt_ids],
            "temperature": float(temperature),
            "top_k": int(top_k), "top_p": float(top_p),
            "deadline_seconds": deadline_seconds,
            "rid": rid,
        })
        out = int(out)
        if on_token is not None:
            self._cbs[out] = on_token
        self._refresh_load()
        return out

    def prestep(self):
        """Issue the step RPC without collecting it — the supervisor
        calls this for every routable replica before the router's
        sequential collection pass, so child processes decode
        CONCURRENTLY on real wall clock."""
        if self._pending_step is None:
            self._pending_step = self.transport.begin("step", {})

    def step(self):
        call, self._pending_step = self._pending_step, None
        try:
            if call is not None:
                reply = self.transport.finish(call)
            else:
                reply = self.transport.call("step", {})
        except BaseException:
            self._pending_step = None
            raise
        self._absorb(reply)
        done = {int(r): list(ids)
                for r, ids in (reply.get("done") or {}).items()}
        for rid in done:
            self._cbs.pop(rid, None)
        return done

    def run_until_complete(self, max_ticks=10000):
        """Drive the remote engine until it drains (parity with the
        in-process engine surface; tests and small tools use it)."""
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            load = self.load()
            if not load.get("queue_depth") and \
                    not load.get("occupied_slots"):
                return done
        raise TimeoutError("remote serving loop did not drain")

    def cancel(self, rid, reason="client"):
        reply = self.transport.call("cancel", {"rid": int(rid),
                                               "reason": reason})
        self._absorb(reply)
        self._cbs.pop(int(rid), None)
        return bool(reply["ok"])

    def load(self):
        if self._load is None:
            self._load = self.transport.call("load", {})
        return self._load

    def prefix_match_pages(self, tokens):
        return self.transport.call("prefix_match_pages",
                                   {"tokens": [int(t) for t in tokens]})

    def stream(self):
        self._absorb(self.transport.call("stream", {}))

    # -- migration / upgrade seam -------------------------------------------
    def extract_wire(self, slot):
        return self.transport.call("extract", {"slot": int(slot)})

    def inject_wire(self, req_wire):
        return int(self.transport.call("inject", {"req": req_wire}))

    def drain_requests(self):
        return self.transport.call("drain", {})

    def release_stream(self, rid):
        """Detach and return the client callback for ``rid`` (the
        stream is moving to a peer replica)."""
        return self._cbs.pop(int(rid), None)

    def adopt_stream(self, rid, cb):
        if cb is not None:
            self._cbs[int(rid)] = cb

    def reload_weights(self, model=None, version=None):
        if model is not None:
            raise ValueError(
                "RemoteEngine.reload_weights ships a version tag, not a "
                "live model — the worker rebuilds from its model spec")
        out = self.transport.call("reload_weights", {"version": version})
        self.weights_version = out["weights_version"]
        self._load = None
        return out

    def warmup(self, sample=False):
        out = self.transport.call("warmup", {"sample": sample})
        # match the engine surface: warmup() returns build_seconds
        self.build_seconds = out["build_seconds"]
        return self.build_seconds

    def engine_stats(self):
        try:
            return self.transport.call("stats", {})
        except (TransportError, wire.FrameError, OSError):
            # a dead replica's counters died with it; report the link
            # state instead of failing the whole soak's accounting
            return {"disaggregated": False, "unreachable": True,
                    "preemptions": 0, "prefix_hit_pages": 0,
                    "cancellations": 0, "handoffs": 0,
                    "handoff_bytes": 0, "int8_kv": False,
                    "int8_weights": False, "weight_bytes": {},
                    "spec": None}

    def ping(self, timeout=None):
        return self.transport.call("ping", {}, timeout=timeout)

    def shutdown(self):
        try:
            return self.transport.call("shutdown", {})
        except (TransportError, wire.FrameError, OSError):
            return None

    def close(self):
        self.transport.close()
