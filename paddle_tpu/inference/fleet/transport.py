"""Pluggable RPC transport: the fleet's replicas as REAL processes.

One replica link = one :class:`Transport` (client half, owned by a
:class:`RemoteEngine` proxy inside the parent) talking to one
:class:`ReplicaServer` (server half, wrapping a live
``ContinuousBatchingEngine`` — in a child process over the socket
transport, or in-process behind the loopback for tests and the
``PTPU_FLEET_PROC=0`` escape hatch).  Frames are the length-prefixed
msgpack format from :mod:`.wire`.

Failure semantics, end to end:

- every call gets a fresh monotone id; retries RE-SEND the same id with
  exponential backoff + deterministic jitter.  The server keeps a
  bounded cache of id -> encoded reply, so a duplicated or re-sent
  frame replays the cached reply instead of re-executing — submits and
  steps stay exactly-once under drop/duplicate/corrupt chaos.
- transport faults raise :class:`TransportError` (a ``ConnectionError``
  subclass) / :class:`TransportTimeout` / :class:`TransportSevered`, so
  ``classify_step_exception`` sees them as TRANSIENT and the router's
  breakers back off + replay instead of killing the replica.
- a corrupt frame in either direction raises :class:`.wire.FrameError`
  loudly at the decode site and is retried by the caller; garbage never
  reaches an engine.

Streaming: ``on_token`` callbacks cannot cross a process boundary, so
the server assigns every token a per-rid sequence number and (a) pushes
it immediately to an attached push sink — a second persistent
connection in socket mode, a client-side buffer in loopback mode — and
(b) retains it in a per-rid event log that the pull path (``step`` /
``stream`` replies) drains and can replay from any sequence number.
:class:`RemoteEngine` delivers events exactly once by sequence number:
duplicates (a frame that arrived on both channels, a reconnect replay)
are dropped, gaps are detected and resynced through the pull path, so
delivery survives reconnects without the router's ``_delivered``
machinery ever seeing a duplicate.

Fencing: the supervisor stamps a monotonically increasing lease epoch
into every RPC frame.  A server that sees a HIGHER epoch knows its old
lease was revoked (the supervisor declared it dead and replayed its
work elsewhere): it self-quarantines — cancels all live requests,
drops buffered events and cached replies — before adopting the new
epoch, so a partitioned-then-healed replica can never double-serve a
rid.  A frame with a LOWER epoch is a stale caller (a late frame from
before the partition): it is rejected with :class:`StaleLease` and
never executes.  Split-brain safety is by construction, not timing.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from collections import OrderedDict, deque

from ... import telemetry as _telemetry
from . import wire
from .overload import outcome_from_wire, outcome_to_wire

_CALLS = _telemetry.counter(
    "transport_calls_total", "fleet RPC calls by method and outcome",
    labelnames=("method", "outcome"))
_RETRIES = _telemetry.counter(
    "transport_retries_total", "fleet RPC attempts beyond the first")
_BYTES = _telemetry.counter(
    "transport_bytes_total", "fleet RPC frame bytes by direction",
    labelnames=("direction",))
_FENCED = _telemetry.counter(
    "transport_fenced_calls_total",
    "RPC frames rejected because their lease epoch was stale")
_QUARANTINES = _telemetry.counter(
    "transport_quarantines_total",
    "replica self-quarantines on seeing a newer lease epoch")
_PUSH_FRAMES = _telemetry.counter(
    "transport_stream_push_frames_total",
    "server-pushed token stream frames")
_STREAM_DUP = _telemetry.counter(
    "transport_stream_duplicates_total",
    "stream events dropped as duplicates by sequence number")
_STREAM_RESYNC = _telemetry.counter(
    "transport_stream_resyncs_total",
    "pull-path resyncs after a stream sequence gap")
_IDEM_EVICT = _telemetry.counter(
    "transport_idempotency_evictions_total",
    "idempotency-cache entries evicted past the window",
    labelnames=("cause",))


class TransportError(ConnectionError):
    """Base transport fault (ConnectionError => transient taxonomy)."""


class TransportTimeout(TransportError):
    """The per-call deadline elapsed without a matching reply."""


class TransportSevered(TransportError):
    """The link is gone: peer dead, socket closed, or chaos-severed."""


class StaleLease(RuntimeError):
    """The caller's lease epoch is older than the replica's: the frame
    was fenced off without executing.  Crosses the wire as a
    ``RemoteReplicaError`` whose ``remote_type`` is ``"StaleLease"``
    (see :func:`is_stale_lease`)."""


def is_stale_lease(exc):
    """True if ``exc`` is a fencing reject, local or rehydrated."""
    return (isinstance(exc, StaleLease)
            or getattr(exc, "remote_type", None) == "StaleLease")


class SimulatedCrash(BaseException):
    """Raised by the test-only ``crash`` RPC; deliberately NOT an
    Exception so the server dispatch cannot swallow it — it unwinds to
    the worker's top level and exercises the unhandled-crash flight
    path for real."""


#: per-method call timeouts (seconds).  warmup/reload compile real
#: programs; steps decode real tokens; everything else is bookkeeping.
DEFAULT_TIMEOUTS = {
    "hello": 120.0,
    "warmup": 600.0,
    "reload_weights": 600.0,
    "step": 300.0,
    "drain": 300.0,
    "extract": 120.0,
    "inject": 120.0,
    "steal": 120.0,
    "export_prefix": 120.0,
    "import_prefix": 120.0,
}
DEFAULT_TIMEOUT = 60.0


class _Call:
    __slots__ = ("id", "method", "frame", "needs_send")

    def __init__(self, call_id, method, frame, needs_send):
        self.id = call_id
        self.method = method
        self.frame = frame
        self.needs_send = needs_send


class Transport:
    """Client half of one replica link.

    Subclasses implement ``_send(frame_bytes)`` and
    ``_recv_bytes(timeout) -> bytes`` (one complete frame).  The retry /
    timeout / jitter machinery lives here so every transport shares the
    exact same failure semantics.  ``begin()``/``finish()`` split a call
    so a supervisor can issue ``step`` to the whole fleet concurrently
    and collect replies afterwards (real wall-clock parallelism)."""

    def __init__(self, *, timeout=DEFAULT_TIMEOUT, timeouts=None,
                 max_retries=3, backoff=0.05, backoff_max=2.0,
                 jitter=0.25, seed=0, codec=None,
                 clock=time.monotonic, sleep=time.sleep):
        self.timeout = float(timeout)
        self.timeouts = dict(DEFAULT_TIMEOUTS)
        if timeouts:
            self.timeouts.update(timeouts)
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.codec = codec
        self.clock = clock
        self.sleep = sleep
        self._next_id = 1
        self._lock = threading.Lock()
        self.retries = 0
        self.calls = 0
        self.backoffs = []            # realized backoff schedule (tests)
        self.last_ok_time = clock()   # heartbeat-lease anchor
        self.last_load = None         # server-attached load snapshot
        self.epoch = 0                # lease fencing token, stamped on
                                      # every frame; supervisor-owned
        self.last_ep = None           # epoch the last reply was made at

    # -- subclass surface ---------------------------------------------------
    def _send(self, frame):
        raise NotImplementedError

    def _recv_bytes(self, timeout):
        raise NotImplementedError

    def close(self):
        pass

    def open_push(self, on_msg):
        """Open the server->client push stream channel; returns a handle
        or None when the transport cannot push (base class default)."""
        return None

    # -- call machinery -----------------------------------------------------
    def _backoff_for(self, attempt):
        """attempt >= 1.  Deterministic jitter: a hash mix of the link
        seed and the call ordinal, NOT random — reproducible runs, but
        distinct links (and distinct calls) still decorrelate."""
        base = min(self.backoff * (2.0 ** (attempt - 1)), self.backoff_max)
        mix = ((self.seed * 2654435761 + self.calls * 40503 + attempt)
               & 0xFFFFFFFF)
        frac = (mix % 997) / 996.0
        delay = base * (1.0 + self.jitter * frac)
        self.backoffs.append(delay)
        return delay

    def begin(self, method, args=None):
        """Send a call without waiting for the reply."""
        with self._lock:
            call_id = self._next_id
            self._next_id += 1
        self.calls += 1
        frame = wire.encode_frame(
            {"id": call_id, "m": method, "a": args or {},
             "ep": self.epoch}, self.codec)
        needs_send = False
        try:
            self._send(frame)
        except OSError:
            needs_send = True      # finish() retries the send
        return _Call(call_id, method, frame, needs_send)

    def finish(self, call, timeout=None):
        """Wait for (and if needed re-drive) a begun call's reply."""
        if timeout is None:
            timeout = self.timeouts.get(call.method, self.timeout)
        last_exc = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                self.retries += 1
                _RETRIES.inc()
                self.sleep(self._backoff_for(attempt))
                call.needs_send = True
            if call.needs_send:
                try:
                    self._send(call.frame)
                    call.needs_send = False
                except OSError as exc:
                    last_exc = exc
                    continue
            try:
                reply = self._recv_reply(call.id, timeout)
            except (wire.FrameError, OSError) as exc:
                last_exc = exc
                continue
            _CALLS.inc(labels=(call.method, "ok"))
            return self._unwrap(reply)
        _CALLS.inc(labels=(call.method, "error"))
        if isinstance(last_exc, TransportError):
            raise last_exc
        if isinstance(last_exc, (TimeoutError, socket.timeout)):
            raise TransportTimeout(
                f"rpc {call.method!r}: no reply within {timeout}s "
                f"after {self.max_retries + 1} attempts") from last_exc
        raise TransportSevered(
            f"rpc {call.method!r}: link failed after "
            f"{self.max_retries + 1} attempts ({last_exc!r})") from last_exc

    def call(self, method, args=None, timeout=None):
        return self.finish(self.begin(method, args), timeout)

    def _recv_reply(self, call_id, timeout):
        """Read frames until the one matching ``call_id``.  Stale or
        duplicated replies (chaos duplication, an earlier abandoned
        attempt's late reply) are dropped by id — ids are never
        reused, so a mismatch is always safe to discard."""
        deadline = self.clock() + timeout
        while True:
            remaining = deadline - self.clock()
            if remaining <= 0:
                raise TransportTimeout(
                    f"rpc id {call_id}: reply timeout after {timeout}s")
            msg = wire.decode_frame(self._recv_bytes(remaining))
            if isinstance(msg, dict) and msg.get("id") == call_id:
                return msg

    def _unwrap(self, reply):
        self.last_ok_time = self.clock()
        if reply.get("ep") is not None:
            self.last_ep = int(reply["ep"])
        if reply.get("load") is not None:
            self.last_load = reply["load"]
        err = reply.get("err")
        if err is not None:
            raise outcome_from_wire(err)
        return reply.get("ok")


# ---------------------------------------------------------------------------
# Loopback (in-process) transport
# ---------------------------------------------------------------------------
class LoopbackTransport(Transport):
    """In-process transport over a real byte-level frame boundary: the
    request is ENCODED, handed to the server as bytes, and the reply
    decoded — so codec, idempotency, and chaos corruption behave
    exactly as over a socket, minus the kernel."""

    def __init__(self, server, **kw):
        super().__init__(**kw)
        self.server = server
        self._rx = deque()

    def _send(self, frame):
        if self.server.dead:
            raise TransportSevered("loopback: peer is dead")
        _BYTES.inc(len(frame), labels=("tx",))
        reply = self.server.handle_frame(bytes(frame))
        if reply is not None:
            _BYTES.inc(len(reply), labels=("rx",))
            self._rx.append(reply)

    def _recv_bytes(self, timeout):
        if not self._rx:
            raise TransportTimeout("loopback: no reply buffered")
        return self._rx.popleft()

    def open_push(self, on_msg):
        """Attach the push channel: server-side token events are decoded
        and handed to ``on_msg(msg)`` synchronously (the in-process
        analogue of the socket transport's persistent push connection)."""
        def sink(frame):
            on_msg(wire.decode_frame(frame))

        self.server.push_sink = sink
        return sink


# ---------------------------------------------------------------------------
# Socket transport
# ---------------------------------------------------------------------------
def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportSevered("socket: peer closed the connection")
        buf += chunk
    return bytes(buf)


class SocketTransport(Transport):
    """Length-prefixed frames over TCP (loopback interface by default).
    Connects lazily and reconnects after any fault, so a respawned
    worker on the same port is picked up by the normal retry path."""

    def __init__(self, host, port, *, connect_timeout=10.0, **kw):
        super().__init__(**kw)
        self.host = host
        self.port = int(port)
        self.connect_timeout = float(connect_timeout)
        self._sock = None

    def _ensure_conn(self):
        if self._sock is None:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
        return self._sock

    def _drop_conn(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _send(self, frame):
        try:
            sock = self._ensure_conn()
            sock.sendall(frame)
            _BYTES.inc(len(frame), labels=("tx",))
        except OSError:
            self._drop_conn()
            raise

    def _recv_bytes(self, timeout):
        try:
            sock = self._ensure_conn()
            sock.settimeout(max(timeout, 0.001))
            header = _recv_exact(sock, wire.HEADER_SIZE)
            _, length, _ = wire.parse_header(header)
            payload = _recv_exact(sock, length)
        except socket.timeout as exc:
            raise TransportTimeout("socket: reply timeout") from exc
        except wire.FrameError:
            # unsynced stream — drop the connection so the next attempt
            # starts on a clean frame boundary
            self._drop_conn()
            raise
        except OSError:
            self._drop_conn()
            raise
        _BYTES.inc(len(header) + len(payload), labels=("rx",))
        return header + payload

    def open_push(self, on_msg):
        """Second persistent connection: subscribe, then a daemon reader
        thread hands every pushed frame to ``on_msg(msg)``.  Best
        effort — if the channel dies the reader exits and the pull
        path's sequence-number resync recovers anything missed."""
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sub = wire.encode_frame(
            {"id": 0, "m": "stream_subscribe", "a": {},
             "ep": self.epoch}, self.codec)
        sock.sendall(sub)

        def reader():
            try:
                while True:
                    header = _recv_exact(sock, wire.HEADER_SIZE)
                    _, length, _ = wire.parse_header(header)
                    payload = _recv_exact(sock, length)
                    msg = wire.decode_frame(header + payload)
                    if isinstance(msg, dict) and "push" in msg:
                        on_msg(msg)
            except (OSError, wire.FrameError, TransportError):
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

        t = threading.Thread(target=reader, daemon=True,
                             name="ptpu-push-reader")
        t.start()
        return sock

    def close(self):
        self._drop_conn()


# ---------------------------------------------------------------------------
# Server half
# ---------------------------------------------------------------------------
class ReplicaServer:
    """RPC dispatcher over one live engine.  ``handle_frame(bytes) ->
    bytes`` is transport-agnostic: the loopback calls it directly, the
    socket loop feeds it.  Replies carry the engine's ``load()``
    snapshot so the client's routing view is refreshed by every call
    with zero extra round trips."""

    IDEMPOTENCY_WINDOW = 128
    #: cached extract/drain replies carry full KV snapshots — a retry
    #: storm must not pin unbounded host memory, so the window is also
    #: bounded by retained payload bytes (oldest evicted first)
    IDEMPOTENCY_BYTES = 32 << 20

    def __init__(self, engine, *, replica_id=0, model_factory=None,
                 scrape_port=None, codec=None, idempotency_window=None,
                 idempotency_bytes=None):
        self.engine = engine
        self.replica_id = replica_id
        self.model_factory = model_factory
        self.scrape_port = scrape_port
        self.codec = codec
        self.dead = False
        self.shutting_down = False
        self.weights_version = 0
        self.idempotency_window = int(
            idempotency_window if idempotency_window is not None
            else self.IDEMPOTENCY_WINDOW)
        self.idempotency_bytes = int(
            idempotency_bytes if idempotency_bytes is not None
            else self.IDEMPOTENCY_BYTES)
        self._done = OrderedDict()     # call id -> encoded reply bytes
        self._done_bytes = 0
        self.idem_evictions = {"count": 0, "bytes": 0}
        self._events = []              # pending (rid, seq, token) pull drain
        self._seq = {}                 # rid -> last assigned seq
        self._event_log = {}           # rid -> [(seq, token)] replay log
        self.push_sink = None          # callable(frame_bytes) or None
        self._push_lock = threading.Lock()
        self.lease_epoch = 0           # fencing token (supervisor-owned)
        self.fenced = 0                # frames rejected as stale
        self.quarantines = 0
        self.quarantined_rids = []     # rids cancelled by quarantines
        self.handled = 0
        self.duplicates = 0

    # -- token streaming ----------------------------------------------------
    # every token gets a per-rid sequence number, lands in the pull
    # buffer + replay log, and is pushed immediately when a sink is
    # attached (the persistent push connection / loopback buffer)
    def _event_cb(self, rid, tok):
        rid, tok = int(rid), int(tok)
        seq = self._seq.get(rid, 0) + 1
        self._seq[rid] = seq
        self._events.append((rid, seq, tok))
        self._event_log.setdefault(rid, []).append((seq, tok))
        sink = self.push_sink
        if sink is not None:
            frame = wire.encode_frame(
                {"push": [(rid, seq, tok)], "ep": self.lease_epoch},
                self.codec)
            try:
                with self._push_lock:
                    sink(frame)
                _PUSH_FRAMES.inc()
            except OSError:
                # push channel is best-effort: the pull path replays
                # from the event log, sequence numbers dedup overlap
                self.push_sink = None

    def _retire_stream(self, rid):
        rid = int(rid)
        self._seq.pop(rid, None)
        self._event_log.pop(rid, None)

    def _reset_stream(self, rid):
        rid = int(rid)
        self._seq[rid] = 0
        self._event_log[rid] = []

    def _quarantine(self, new_epoch):
        """The supervisor re-leased at a higher epoch: everything this
        replica was doing under the old lease has been replayed
        elsewhere.  Cancel it all, drop buffered events, cached replies
        and stream state, THEN adopt the new epoch — by construction no
        old-lease work can ever surface under the new one."""
        eng = self.engine
        live = [r.rid for r in eng._slots if r is not None]
        live += [r.rid for r in list(eng._waiting)]
        # a freshly spawned replica adopting its first lease has nothing
        # to drop — that is plain epoch adoption, not a quarantine
        had_state = bool(live or self._events or self._event_log
                         or self._done)
        for rid in live:
            eng.cancel(rid, reason="fenced")
        # the supervisor already replayed these rids on peers — the
        # engine-side cancels are bookkeeping, not terminal outcomes
        eng.cancelled.clear()
        self.quarantined_rids.extend(int(r) for r in live)
        self._events = []
        self._seq.clear()
        self._event_log.clear()
        self._done.clear()
        self._done_bytes = 0
        if had_state:
            self.quarantines += 1
            _QUARANTINES.inc()
        self.lease_epoch = int(new_epoch)

    def handle_frame(self, data):
        try:
            msg = wire.decode_frame(data)
        except wire.FrameError as exc:
            # can't know the call id of a corrupt request — answer with
            # an unaddressed error frame; the client drops it and
            # re-sends on its own timeout
            return wire.encode_frame(
                {"id": None, "err": outcome_to_wire(exc)}, self.codec)
        call_id = msg.get("id")
        ep = msg.get("ep")
        if ep is not None:
            ep = int(ep)
            if ep > self.lease_epoch:
                self._quarantine(ep)
            elif ep < self.lease_epoch:
                # stale caller: fence the frame off BEFORE the
                # idempotency cache — it must never execute or replay
                self.fenced += 1
                _FENCED.inc()
                return wire.encode_frame(
                    {"id": call_id, "ep": self.lease_epoch,
                     "err": outcome_to_wire(StaleLease(
                         f"frame epoch {ep} < lease epoch "
                         f"{self.lease_epoch}"))}, self.codec)
        cached = self._done.get(call_id)
        if cached is not None:
            # duplicate / re-sent frame: replay, do NOT re-execute
            self.duplicates += 1
            self._done.move_to_end(call_id)
            return cached
        self.handled += 1
        try:
            result = self._dispatch(msg.get("m"), msg.get("a") or {})
            reply = {"id": call_id, "ok": result}
        except SimulatedCrash:
            raise
        except Exception as exc:
            reply = {"id": call_id, "err": outcome_to_wire(exc)}
        reply["ep"] = self.lease_epoch
        try:
            reply["load"] = self.engine.load()
        except Exception:
            reply["load"] = None
        out = wire.encode_frame(reply, self.codec)
        if call_id is not None:
            self._done[call_id] = out
            self._done_bytes += len(out)
            while len(self._done) > self.idempotency_window:
                _, old = self._done.popitem(last=False)
                self._done_bytes -= len(old)
                self.idem_evictions["count"] += 1
                _IDEM_EVICT.inc(labels=("count",))
            while self._done_bytes > self.idempotency_bytes \
                    and len(self._done) > 1:
                _, old = self._done.popitem(last=False)
                self._done_bytes -= len(old)
                self.idem_evictions["bytes"] += 1
                _IDEM_EVICT.inc(labels=("bytes",))
        return out

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, method, a):
        handler = getattr(self, "_rpc_" + str(method), None)
        if handler is None:
            raise ValueError(f"rpc: unknown method {method!r}")
        return handler(a)

    def _rpc_hello(self, a):
        eng = self.engine
        return {
            "replica_id": self.replica_id,
            "pid": os.getpid(),
            "max_slots": eng.max_slots,
            "max_new_tokens": eng.max_new_tokens,
            "page": eng.page,
            "pages_per_seq": eng.pages_per_seq,
            "int8_kv": bool(getattr(eng, "int8_kv", False)),
            "scrape_port": self.scrape_port,
            "weights_version": self.weights_version,
        }

    def _rpc_ping(self, a):
        return {"ok": True, "replica_id": self.replica_id,
                "pid": os.getpid(), "epoch": self.lease_epoch}

    def _rpc_lease(self, a):
        """Explicit lease grant/renewal probe.  The epoch itself rides
        the frame header (adoption/fencing happened in
        ``handle_frame`` before we got here); this just reports back."""
        return {"epoch": self.lease_epoch,
                "quarantines": self.quarantines,
                "quarantined_rids": [int(r)
                                     for r in self.quarantined_rids],
                "fenced": self.fenced}

    def _rpc_submit(self, a):
        rid = self.engine.submit(
            a["prompt"],
            temperature=a.get("temperature", 0.0),
            top_k=a.get("top_k", 0),
            top_p=a.get("top_p", 1.0),
            on_token=self._event_cb,
            deadline_seconds=a.get("deadline_seconds"),
            rid=a.get("rid"))
        # a (re)submitted rid starts a fresh stream: seq from 1
        self._reset_stream(rid)
        return int(rid)

    def _drain_events(self, resync=None):
        ev, self._events = self._events, []
        if resync:
            # client detected a sequence gap: replay the event log past
            # its last delivered seq (overlap is deduped client-side)
            for rid, last in resync.items():
                rid, last = int(rid), int(last)
                for seq, tok in self._event_log.get(rid, []):
                    if seq > last:
                        ev.append((rid, seq, tok))
        return ev

    def _drain_cancelled(self):
        c = {int(r): str(reason)
             for r, reason in self.engine.cancelled.items()}
        self.engine.cancelled.clear()
        for rid in c:
            self._retire_stream(rid)
        return c

    def _rpc_step(self, a):
        done = self.engine.step()
        out = {"done": {int(r): [int(t) for t in ids]
                        for r, ids in done.items()},
               "events": self._drain_events(a.get("resync")),
               "cancelled": self._drain_cancelled()}
        for rid in out["done"]:
            self._retire_stream(rid)
        return out

    def _rpc_stream(self, a):
        # drain buffered token events without stepping
        return {"events": self._drain_events(a.get("resync")),
                "cancelled": self._drain_cancelled()}

    def _rpc_cancel(self, a):
        ok = bool(self.engine.cancel(a["rid"],
                                     reason=a.get("reason", "client")))
        return {"ok": ok, "cancelled": self._drain_cancelled()}

    def _rpc_load(self, a):
        return self.engine.load()

    def _rpc_prefix_match_pages(self, a):
        return int(self.engine.prefix_match_pages(a["tokens"]))

    def _rpc_extract(self, a):
        req = self.engine.extract(a["slot"])
        self._retire_stream(req.rid)
        return wire.request_to_wire(req)

    def _rpc_inject(self, a):
        req = wire.request_from_wire(a["req"])
        req.on_token = self._event_cb
        self.engine.inject(req)
        # the stream continues here: post-inject tokens restart at seq 1
        # against a fresh client-side counter (adopt_stream resets it)
        self._reset_stream(req.rid)
        return int(req.rid)

    def _rpc_drain(self, a):
        """Serialize EVERYTHING queued or running and empty the engine:
        the KV-migration point of a rolling upgrade.  Occupied slots go
        through ``extract()`` (host KV snapshot rides along); waiting
        requests ship as-is."""
        eng = self.engine
        running = []
        for i, r in enumerate(eng._slots):
            if r is not None:
                running.append(wire.request_to_wire(eng.extract(i)))
        waiting = []
        while eng._waiting:
            waiting.append(wire.request_to_wire(eng._waiting.popleft()))
        for w in running + waiting:
            self._retire_stream(w["rid"])
        return {"running": running, "waiting": waiting}

    def _rpc_steal(self, a):
        """Pop up to ``n`` WAITING requests off the back of the queue —
        the ones that would wait longest (and be shed first) — for live
        migration to a replica with headroom.  Swapped host-KV
        snapshots ride along; running slots are untouched."""
        eng = self.engine
        n = int(a.get("n", 1))
        out = []
        while eng._waiting and len(out) < n:
            req = eng._waiting.pop()       # back of the queue
            out.append(wire.request_to_wire(req))
            self._retire_stream(req.rid)
        out.reverse()                      # preserve relative order
        return {"stolen": out}

    def _rpc_export_prefix(self, a):
        """Ship the warmest prefix-cache pages (chain key + KV page
        snapshot) so a drain destination starts warm."""
        entries = self.engine.export_prefix_pages(
            max_pages=a.get("max_pages"))
        return {"entries": entries}

    def _rpc_import_prefix(self, a):
        n = self.engine.import_prefix_pages(a.get("entries") or [])
        return {"imported": int(n)}

    def _rpc_reload_weights(self, a):
        version = a.get("version")
        model = None
        if self.model_factory is not None:
            model = self.model_factory(version=version)
        self.engine.reload_weights(model)
        if version is not None:
            self.weights_version = version
        return {"weights_version": self.weights_version}

    def _rpc_warmup(self, a):
        self.engine.warmup(sample=a.get("sample", False))
        return {"build_seconds": self.engine.build_seconds}

    def _rpc_stats(self, a):
        from .soak import _engine_stats
        return _engine_stats(self.engine)

    def _rpc_stream_subscribe(self, a):
        # the serve loop attached the connection as push_sink before
        # dispatching this ack; loopback attaches the sink directly
        return {"ok": True, "epoch": self.lease_epoch}

    def _rpc_shutdown(self, a):
        self.shutting_down = True
        return {"ok": True}

    def _rpc_crash(self, a):
        raise SimulatedCrash("chaos: crash requested over RPC")


# ---------------------------------------------------------------------------
# Socket serve loop (runs in the worker process)
# ---------------------------------------------------------------------------
class SocketServerLoop:
    """Accept parent connections and pump frames through a
    :class:`ReplicaServer` until it flags shutdown.  The RPC connection
    is pumped on the accept thread (one request/reply at a time, as
    before); a connection whose first frame is ``stream_subscribe``
    becomes the persistent PUSH channel and is pumped on its own
    daemon thread, so token frames flow while an RPC is in flight.  A
    fresh connection after a drop (parent restarted its transport) is
    business as usual."""

    def __init__(self, server, *, host="127.0.0.1", port=0):
        self.server = server
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(4)
        self.host, self.port = self._listener.getsockname()[:2]
        # one dispatch at a time: the push-channel pump thread and the
        # RPC pump share the (not thread-safe) ReplicaServer
        self._dispatch_lock = threading.Lock()

    def _handle(self, frame):
        with self._dispatch_lock:
            return self.server.handle_frame(frame)

    def serve_forever(self, accept_timeout=1.0):
        self._listener.settimeout(accept_timeout)
        while not self.server.shutting_down:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            first = self._read_frame(conn)
            if first is None:
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            if self._is_subscribe(first):
                # push channel: attach the sink, ack, pump on a thread
                self.server.push_sink = conn.sendall
                reply = self._handle(first)
                try:
                    conn.sendall(reply)
                except OSError:
                    continue
                threading.Thread(
                    target=self._pump, args=(conn,), daemon=True,
                    name="ptpu-push-conn").start()
                continue
            reply = self._handle(first)
            if reply is not None:
                try:
                    conn.sendall(reply)
                except OSError:
                    pass
            try:
                self._pump(conn)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
        self._listener.close()

    def _is_subscribe(self, frame):
        try:
            msg = wire.decode_frame(frame)
        except wire.FrameError:
            return False
        return isinstance(msg, dict) and msg.get("m") == "stream_subscribe"

    def _read_frame(self, conn, first_timeout=5.0):
        """Read one complete frame (or None on drop/corruption)."""
        conn.settimeout(first_timeout)
        try:
            header = _recv_exact(conn, wire.HEADER_SIZE)
            _, length, _ = wire.parse_header(header)
            payload = _recv_exact(conn, length)
        except (socket.timeout, TransportSevered, wire.FrameError,
                OSError):
            return None
        return header + payload

    def _pump(self, conn):
        conn.settimeout(0.5)
        while not self.server.shutting_down:
            try:
                header = _recv_exact(conn, wire.HEADER_SIZE)
            except socket.timeout:
                continue
            except (TransportSevered, OSError):
                return                     # parent dropped; re-accept
            try:
                _, length, _ = wire.parse_header(header)
                conn.settimeout(10.0)
                payload = _recv_exact(conn, length)
            except wire.FrameError:
                return                     # unsynced stream; re-accept
            except (socket.timeout, TransportSevered, OSError):
                return
            finally:
                try:
                    conn.settimeout(0.5)
                except OSError:
                    return
            reply = self._handle(header + payload)
            if reply is not None:
                try:
                    conn.sendall(reply)
                except OSError:
                    return


# ---------------------------------------------------------------------------
# Client proxy
# ---------------------------------------------------------------------------
class RemoteEngine:
    """Duck-types the engine surface the fleet consumes (submit / step /
    cancel / load / prefix_match_pages / cancelled / extract / inject /
    reload_weights / warmup), so it drops into a ``ReplicaHandle``
    unchanged.  Token events from step replies are replayed into
    client-side callbacks; ``load()`` is served from the snapshot the
    server attaches to every reply (zero extra round trips on the
    routing hot path)."""

    def __init__(self, transport, *, hello=True):
        self.transport = transport
        self.cancelled = {}           # client-side mirror, router drains
        self._cbs = {}                # rid -> client on_token callback
        self._load = None
        self._pending_step = None
        self.pid = None
        self.scrape_port = None
        self.replica_id = None
        self.weights_version = 0
        # exactly-once stream delivery by sequence number
        self._seq = {}                # rid -> last delivered seq
        self._ahead = {}              # rid -> {seq: tok} out-of-order hold
        self._need_resync = set()     # rids with a detected gap
        self._push_q = deque()        # pushed frames awaiting pump
        self._push_handle = None
        self.stream_dups = 0          # dropped by seq (benign overlap)
        self.stream_gaps = 0
        self.stream_resyncs = 0
        self.push_delivered = 0       # tokens delivered off push frames
        self.fenced_replies = 0       # old-epoch replies dropped whole
        if hello:
            info = transport.call("hello")
            self.max_slots = info["max_slots"]
            self.max_new_tokens = info["max_new_tokens"]
            self.page = info["page"]
            self.pages_per_seq = info["pages_per_seq"]
            self.int8_kv = info["int8_kv"]
            self.pid = info["pid"]
            self.scrape_port = info.get("scrape_port")
            self.replica_id = info.get("replica_id")
            self.weights_version = info.get("weights_version", 0)
            self._refresh_load()

    # -- bookkeeping --------------------------------------------------------
    def _refresh_load(self):
        if self.transport.last_load is not None:
            self._load = self.transport.last_load

    def _drop_stream_state(self, rid):
        rid = int(rid)
        self._cbs.pop(rid, None)
        self._seq.pop(rid, None)
        self._ahead.pop(rid, None)
        self._need_resync.discard(rid)

    def _deliver(self, rid, seq, tok, *, pushed=False):
        """Exactly-once, in-order delivery: seq must be last+1.  Lower
        is a duplicate (both channels / reconnect replay) and dropped;
        higher is held and flagged for a pull-path resync."""
        rid, seq = int(rid), int(seq)
        last = self._seq.get(rid)
        if last is None:
            return                    # no live stream for this rid here
        if seq <= last:
            self.stream_dups += 1
            _STREAM_DUP.inc()
            return
        if seq > last + 1:
            self._ahead.setdefault(rid, {})[seq] = tok
            if rid not in self._need_resync:
                self._need_resync.add(rid)
                self.stream_gaps += 1
            return
        cb = self._cbs.get(rid)
        if cb is not None:
            cb(rid, tok)
        if pushed:
            self.push_delivered += 1
        self._seq[rid] = seq
        ahead = self._ahead.get(rid)
        while ahead:
            nxt = self._seq[rid] + 1
            if nxt not in ahead:
                break
            t = ahead.pop(nxt)
            if cb is not None:
                cb(rid, t)
            if pushed:
                self.push_delivered += 1
            self._seq[rid] = nxt
        if not ahead:
            self._ahead.pop(rid, None)
            self._need_resync.discard(rid)

    def _link_fenced(self):
        """True when the LAST reply on this link was generated under an
        older lease epoch than the link now holds — a late arrival from
        before a partition; its contents must not surface."""
        ep = self.transport.last_ep
        if ep is not None and ep < self.transport.epoch:
            self.fenced_replies += 1
            return True
        return False

    def _absorb(self, reply):
        """Fold a step/stream/cancel reply's events + cancels into the
        client-side stream state, exactly once per reply."""
        if self._link_fenced():
            return
        for rid, seq, tok in reply.get("events") or []:
            self._deliver(rid, seq, tok)
        for rid, reason in (reply.get("cancelled") or {}).items():
            rid = int(rid)
            self.cancelled[rid] = reason
            self._drop_stream_state(rid)
        self._refresh_load()

    # -- push channel -------------------------------------------------------
    def enable_push(self):
        """Open the persistent push channel (second connection over a
        socket transport, a synchronous buffer over loopback).  Pushed
        frames queue until :meth:`pump_push` drains them on the caller's
        thread, so callbacks never fire concurrently."""
        if self._push_handle is None:
            self._push_handle = self.transport.open_push(
                self._push_q.append)
        return self._push_handle is not None

    def pump_push(self):
        """Deliver queued push frames into client callbacks.  Safe to
        call at any cadence — a front-end polling between supervisor
        ticks gets tokens the moment the server emits them instead of
        quantized to the tick.  Returns frames drained."""
        n = 0
        while self._push_q:
            msg = self._push_q.popleft()
            n += 1
            ep = msg.get("ep")
            if ep is not None and int(ep) < self.transport.epoch:
                self.fenced_replies += 1
                continue
            for rid, seq, tok in msg.get("push") or []:
                self._deliver(rid, seq, tok, pushed=True)
        return n

    def _resync_args(self):
        if not self._need_resync:
            return {}
        self.stream_resyncs += len(self._need_resync)
        _STREAM_RESYNC.inc(len(self._need_resync))
        return {"resync": {int(r): int(self._seq.get(r, 0))
                           for r in self._need_resync}}

    # -- engine surface -----------------------------------------------------
    def submit(self, prompt_ids, temperature=0.0, top_k=0, top_p=1.0,
               on_token=None, deadline_seconds=None, rid=None):
        out = self.transport.call("submit", {
            "prompt": [int(t) for t in prompt_ids],
            "temperature": float(temperature),
            "top_k": int(top_k), "top_p": float(top_p),
            "deadline_seconds": deadline_seconds,
            "rid": rid,
        })
        out = int(out)
        if on_token is not None:
            self._cbs[out] = on_token
        # fresh stream: server restarts this rid's seq from 1
        self._seq[out] = 0
        self._ahead.pop(out, None)
        self._need_resync.discard(out)
        self._refresh_load()
        return out

    def prestep(self):
        """Issue the step RPC without collecting it — the supervisor
        calls this for every routable replica before the router's
        sequential collection pass, so child processes decode
        CONCURRENTLY on real wall clock."""
        if self._pending_step is None:
            self._pending_step = self.transport.begin(
                "step", self._resync_args())

    def step(self):
        call, self._pending_step = self._pending_step, None
        try:
            if call is not None:
                reply = self.transport.finish(call)
            else:
                reply = self.transport.call("step", self._resync_args())
        except BaseException:
            self._pending_step = None
            raise
        self.pump_push()
        if self._link_fenced():
            # late reply from before the lease was re-issued: fenced
            return {}
        self._absorb(reply)
        done = {int(r): list(ids)
                for r, ids in (reply.get("done") or {}).items()}
        for rid in done:
            self._drop_stream_state(rid)
        return done

    def run_until_complete(self, max_ticks=10000):
        """Drive the remote engine until it drains (parity with the
        in-process engine surface; tests and small tools use it)."""
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            load = self.load()
            if not load.get("queue_depth") and \
                    not load.get("occupied_slots"):
                return done
        raise TimeoutError("remote serving loop did not drain")

    def cancel(self, rid, reason="client"):
        reply = self.transport.call("cancel", {"rid": int(rid),
                                               "reason": reason})
        self._absorb(reply)
        self._drop_stream_state(rid)
        return bool(reply["ok"])

    def load(self):
        if self._load is None:
            self._load = self.transport.call("load", {})
        return self._load

    def prefix_match_pages(self, tokens):
        return self.transport.call("prefix_match_pages",
                                   {"tokens": [int(t) for t in tokens]})

    def stream(self):
        self.pump_push()
        self._absorb(self.transport.call("stream", self._resync_args()))

    def lease(self, epoch=None, timeout=None):
        """Grant/renew the lease at ``epoch`` (bumps the link's fencing
        token) and return the server's view — quarantine counters and
        the rids it cancelled when an older lease was revoked."""
        if epoch is not None:
            self.transport.epoch = int(epoch)
        return self.transport.call("lease", {}, timeout=timeout)

    # -- migration / upgrade seam -------------------------------------------
    def extract_wire(self, slot):
        return self.transport.call("extract", {"slot": int(slot)})

    def inject_wire(self, req_wire):
        return int(self.transport.call("inject", {"req": req_wire}))

    def drain_requests(self):
        return self.transport.call("drain", {})

    def steal_requests(self, n):
        """Pop up to ``n`` waiting requests (KV snapshots ride along)
        off the replica's queue for live migration to a peer."""
        return self.transport.call("steal", {"n": int(n)})["stolen"]

    def export_prefix(self, max_pages=None):
        return self.transport.call(
            "export_prefix", {"max_pages": max_pages})["entries"]

    def import_prefix(self, entries):
        return int(self.transport.call(
            "import_prefix", {"entries": entries})["imported"])

    def release_stream(self, rid):
        """Detach and return the client callback for ``rid`` (the
        stream is moving to a peer replica)."""
        self._seq.pop(int(rid), None)
        self._ahead.pop(int(rid), None)
        self._need_resync.discard(int(rid))
        return self._cbs.pop(int(rid), None)

    def adopt_stream(self, rid, cb):
        if cb is not None:
            self._cbs[int(rid)] = cb
            # the migrated stream restarts at seq 1 on this replica
            self._seq[int(rid)] = 0
            self._ahead.pop(int(rid), None)

    def reload_weights(self, model=None, version=None):
        if model is not None:
            raise ValueError(
                "RemoteEngine.reload_weights ships a version tag, not a "
                "live model — the worker rebuilds from its model spec")
        out = self.transport.call("reload_weights", {"version": version})
        self.weights_version = out["weights_version"]
        self._load = None
        return out

    def warmup(self, sample=False):
        out = self.transport.call("warmup", {"sample": sample})
        # match the engine surface: warmup() returns build_seconds
        self.build_seconds = out["build_seconds"]
        return self.build_seconds

    def engine_stats(self):
        try:
            return self.transport.call("stats", {})
        except (TransportError, wire.FrameError, OSError):
            # a dead replica's counters died with it; report the link
            # state instead of failing the whole soak's accounting
            return {"disaggregated": False, "unreachable": True,
                    "preemptions": 0, "prefix_hit_pages": 0,
                    "cancellations": 0, "handoffs": 0,
                    "handoff_bytes": 0, "int8_kv": False,
                    "int8_weights": False, "weight_bytes": {},
                    "spec": None}

    def ping(self, timeout=None):
        return self.transport.call("ping", {}, timeout=timeout)

    def shutdown(self):
        try:
            return self.transport.call("shutdown", {})
        except (TransportError, wire.FrameError, OSError):
            return None

    def close(self):
        h, self._push_handle = self._push_handle, None
        if h is not None and hasattr(h, "close"):
            try:
                h.close()
            except OSError:
                pass
        self.transport.close()
