"""Replica worker: one serving engine in its own OS process.

Launched by :class:`~paddle_tpu.inference.fleet.cluster.FleetSupervisor`
as ``python -m paddle_tpu.inference.fleet.worker --spec '<json>'``.
The spec is plain JSON (model config + engine kwargs + seed), so the
child rebuilds its own weights deterministically — nothing crosses the
process boundary at spawn except the spec and, later, frames on the
RPC socket.

Startup handshake: one line on stdout ::

    PTPU_WORKER_READY {"port": ..., "pid": ..., "replica_id": ...,
                       "scrape_port": ...}

then the socket serve loop runs until a ``shutdown`` RPC (or a signal).

Crash forensics (docs/TELEMETRY.md "Flight recorder"): when the spec
carries ``flight_dir``, a FlightRecorder is installed at boot and

- an UNHANDLED exception dumps a ``replica_crash`` bundle (exception,
  traceback, replica id) before the process exits non-zero;
- SIGTERM dumps a ``replica_sigterm`` bundle before exiting —

both are ordinary ``ptpu-flight-1`` bundles that
``tools/flight_report.py`` loads and validates.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import traceback


def _install_crash_paths(replica_id):
    from ...telemetry import flight as _flight

    def _excepthook(exc_type, exc, tb):
        _flight.maybe_dump("replica_crash", {
            "replica_id": replica_id,
            "pid": os.getpid(),
            "exc": repr(exc),
            "traceback": "".join(
                traceback.format_exception(exc_type, exc, tb))[-4000:],
        })
        sys.__excepthook__(exc_type, exc, tb)
        # the frame-pump thread state is unrecoverable; exit loudly
        os._exit(1)

    def _on_sigterm(signum, frame):
        _flight.maybe_dump("replica_sigterm", {
            "replica_id": replica_id,
            "pid": os.getpid(),
            "signal": int(signum),
        })
        os._exit(0)

    sys.excepthook = _excepthook
    signal.signal(signal.SIGTERM, _on_sigterm)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="paddle_tpu.inference.fleet.worker")
    ap.add_argument("--spec", help="JSON replica spec")
    ap.add_argument("--spec-file", help="path to a JSON replica spec")
    args = ap.parse_args(argv)
    if args.spec_file:
        with open(args.spec_file) as f:
            spec = json.load(f)
    elif args.spec:
        spec = json.loads(args.spec)
    else:
        ap.error("one of --spec / --spec-file is required")

    replica_id = spec.get("replica_id", 0)
    flight_dir = spec.get("flight_dir")
    if flight_dir:
        from ...telemetry import flight as _flight
        _flight.install(flight_dir)
    _install_crash_paths(replica_id)

    from ... import telemetry as _telemetry
    from ...telemetry.scrape import ScrapeServer
    from ..serving import ContinuousBatchingEngine
    from .cluster import build_model_from_spec
    from .transport import ReplicaServer, SocketServerLoop

    scrape_port = None
    if spec.get("metrics"):
        _telemetry.enable()
        scrape = ScrapeServer(_telemetry.get_registry(),
                              replica_id=replica_id).start()
        scrape_port = scrape.port

    from .router import RID_STRIDE

    model = build_model_from_spec(spec)
    engine = ContinuousBatchingEngine(
        model, rid_base=replica_id * RID_STRIDE,
        **spec.get("engine_kw", {}))

    def model_factory(version=None):
        return build_model_from_spec(spec, version=version)

    server = ReplicaServer(engine, replica_id=replica_id,
                           model_factory=model_factory,
                           scrape_port=scrape_port)
    loop = SocketServerLoop(server, port=spec.get("port", 0))
    print("PTPU_WORKER_READY " + json.dumps({
        "port": loop.port, "pid": os.getpid(),
        "replica_id": replica_id, "scrape_port": scrape_port}),
        flush=True)
    loop.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
