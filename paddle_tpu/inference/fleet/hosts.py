"""Cross-host fleet layer: TCPStore rendezvous, per-host agents, fenced
placement (docs/SERVING.md "Cross-host topology").

PR 18 made replicas real OS processes, but the supervisor still
fork/exec'd them locally — one host, no notion of a machine dying or a
network partitioning.  This module takes the fleet off the host:

- **Rendezvous.** Every host runs a :class:`HostAgent` that registers
  itself — address, RPC port, worker slots, chip inventory, pid — in
  the existing :class:`~paddle_tpu.distributed.store.TCPStore` under
  ``fleet/host/<ordinal>`` (ordinals allocated with the store's atomic
  ``add``), then bumps a per-host heartbeat counter ``fleet/hb/<n>``.
  The supervisor discovers hosts by READING the store, never by being
  configured with addresses.
- **Placement via agents.** The supervisor spawns and respawns workers
  by calling the host's agent (``spawn_worker`` / ``kill_worker`` RPCs
  over the same PTF1 framed wire the replicas speak), spreading
  replicas across hosts — the failure domains — and the router's
  least-loaded scoring gains a host-pressure term so traffic spreads
  the same way.
- **Host leases.** A host whose heartbeat counter stalls AND whose
  agent stops answering pings is declared severed: every replica on it
  is fenced to a higher lease epoch and its requests replay elsewhere
  through the existing exactly-once machinery.  When the host heals,
  its surviving workers self-quarantine on the first higher-epoch frame
  (transport.py) before the supervisor re-adopts or retires them — a
  partitioned-then-healed host can never double-serve a rid, by
  construction rather than by timing.

The agent is transport-agnostic like ReplicaServer: in-process
(:func:`spawn_local_agent`, the tier-1 test path and the
``PTPU_FLEET_HOSTS=0``-adjacent local topology) or a real process tree
(:class:`AgentProc` -> ``python -m paddle_tpu.inference.fleet.hosts``)
whose workers are themselves subprocesses — two of those trees on one
machine are the two-host chaos scenario tools/serve_bench.py drives.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import OrderedDict

from ... import telemetry as _telemetry
from ...distributed.store import TCPStore
from . import wire
from .overload import _OFF_SPELLINGS, outcome_to_wire
from .transport import (LoopbackTransport, SocketServerLoop,
                        SocketTransport, TransportError)

__all__ = [
    "AgentClient", "AgentProc", "HostAgent", "HostDirectory",
    "HostHandle", "HostLost", "HostedChild", "fleet_hosts_enabled",
    "spawn_local_agent", "spawn_proc_agent", "spawn_on_host",
]

_ENV_HOSTS = "PTPU_FLEET_HOSTS"

_HOSTS = _telemetry.gauge(
    "fleet_hosts", "registered fleet hosts by liveness state",
    labelnames=("state",))
_SEVERED = _telemetry.counter(
    "fleet_host_severed_total",
    "hosts declared severed (heartbeat stalled and agent unreachable)")
_HEALED = _telemetry.counter(
    "fleet_host_healed_total", "severed hosts that healed")
_ADOPTED = _telemetry.counter(
    "fleet_workers_adopted_total",
    "surviving workers re-leased from a healed host")


def fleet_hosts_enabled():
    """``PTPU_FLEET_HOSTS=0`` is the single-host escape hatch: any
    ``hosts=`` topology collapses to the PR 18 local spawn path,
    bitwise-identical, no code change needed."""
    return os.environ.get(_ENV_HOSTS, "").strip().lower() \
        not in _OFF_SPELLINGS


class HostLost(ConnectionError):
    """A replica's host was declared severed (=> transient taxonomy:
    the work replays, the fleet survives)."""


def _chip_inventory():
    """Best-effort accelerator inventory for the rendezvous record —
    advisory placement metadata, never load-bearing."""
    try:
        import jax

        devs = jax.devices()
        return {"count": len(devs),
                "platform": devs[0].platform if devs else "none"}
    except Exception:
        return {"count": 0, "platform": "unknown"}


# ---------------------------------------------------------------------------
# Rendezvous directory (over the TCPStore)
# ---------------------------------------------------------------------------
class HostDirectory:
    """The rendezvous contract, on plain store primitives:

    - ``fleet/nhosts`` — atomic ordinal allocator (``add(1) - 1``);
    - ``fleet/host/<n>`` — one JSON record per host (address, port,
      slots, chips, pid), written by the host's own agent;
    - ``fleet/hb/<n>`` — a monotone heartbeat counter the agent bumps;
      liveness is "the counter advanced", never a wall-clock timestamp
      (an NTP step on either side must not kill a host).
    """

    PREFIX = "fleet"

    def __init__(self, store):
        self.store = store

    def _key(self, *parts):
        return "/".join((self.PREFIX,) + tuple(str(p) for p in parts))

    def register(self, info):
        """Allocate an ordinal and publish this host's record; returns
        the ordinal."""
        ordinal = int(self.store.add(self._key("nhosts"), 1)) - 1
        self.store.set(self._key("host", ordinal),
                       json.dumps(dict(info, ordinal=ordinal)))
        return ordinal

    def update(self, ordinal, info):
        self.store.set(self._key("host", ordinal),
                       json.dumps(dict(info, ordinal=ordinal)))

    def get(self, ordinal):
        raw = self.store.get(self._key("host", ordinal))
        return json.loads(raw.decode()) if raw else None

    def count(self):
        return int(self.store.add(self._key("nhosts"), 0))

    def list_hosts(self):
        """Every registered host record — THE discovery path."""
        return [rec for rec in (self.get(i) for i in range(self.count()))
                if rec is not None]

    def wait_hosts(self, n, timeout=60.0):
        """Block until ``n`` hosts have registered (rendezvous)."""
        for i in range(int(n)):
            self.store.wait(self._key("host", i), timeout=timeout)
        return self.list_hosts()

    def beat(self, ordinal):
        return int(self.store.add(self._key("hb", ordinal), 1))

    def beats(self, ordinal):
        """Read the heartbeat counter without advancing it."""
        return int(self.store.add(self._key("hb", ordinal), 0))


# ---------------------------------------------------------------------------
# The per-host agent (server half)
# ---------------------------------------------------------------------------
class HostAgent:
    """Per-host launcher + registrar.  ``handle_frame(bytes) -> bytes``
    speaks the same PTF1 call frames as ReplicaServer (with the same
    idempotency-cache replay for re-sent frames — ``spawn_worker`` must
    be exactly-once under retries), so it sits behind a
    LoopbackTransport in-process or a SocketServerLoop in its own
    process with zero extra plumbing.  Agent RPCs are not lease-fenced:
    the supervisor is the agent's only caller, and worker placement is
    re-validated against the store on every host tick."""

    IDEMPOTENCY_WINDOW = 64

    def __init__(self, spec, *, host_id="host0", proc=False, slots=8,
                 workdir=None, directory=None, heartbeat_every=0.05,
                 codec=None):
        self.spec = dict(spec)
        self.host_id = str(host_id)
        self.proc = bool(proc)
        self.slots = int(slots)
        self.workdir = workdir
        self.directory = directory
        self.heartbeat_every = float(heartbeat_every)
        self.codec = codec
        self.ordinal = None
        self.port = None              # set when served over a socket
        self.workers = {}             # worker ordinal -> child
        self.spawned = 0
        self.killed = 0
        self.handled = 0
        self.duplicates = 0
        self._done = OrderedDict()    # call id -> encoded reply
        # transport compatibility (LoopbackTransport / SocketServerLoop)
        self.dead = False
        self.shutting_down = False
        self.push_sink = None
        # local-mode partition seam: while severed, the heartbeat thread
        # stops reaching the store (the "network" includes the store)
        self.severed = False
        self._hb_thread = None

    # -- rendezvous ---------------------------------------------------------
    def register(self, *, address="127.0.0.1", port=None):
        if self.directory is None:
            raise RuntimeError("HostAgent has no directory to register in")
        self.port = port
        self.ordinal = self.directory.register({
            "host_id": self.host_id,
            "address": address,
            "port": port,
            "pid": os.getpid(),
            "slots": self.slots,
            "mode": "proc" if self.proc else "local",
            "chips": _chip_inventory(),
        })
        self.directory.beat(self.ordinal)
        return self.ordinal

    def beat(self):
        if self.directory is not None and self.ordinal is not None \
                and not self.severed:
            self.directory.beat(self.ordinal)

    def start_heartbeat(self):
        def loop():
            while not self.shutting_down:
                try:
                    self.beat()
                except Exception:
                    pass              # store unreachable: a partition
                time.sleep(self.heartbeat_every)

        self._hb_thread = threading.Thread(
            target=loop, daemon=True, name="ptpu-host-heartbeat")
        self._hb_thread.start()
        return self._hb_thread

    # -- frame dispatch (mirrors ReplicaServer's shape) ---------------------
    def handle_frame(self, data):
        try:
            msg = wire.decode_frame(data)
        except wire.FrameError as exc:
            return wire.encode_frame(
                {"id": None, "err": outcome_to_wire(exc)}, self.codec)
        call_id = msg.get("id")
        cached = self._done.get(call_id)
        if cached is not None:
            self.duplicates += 1
            self._done.move_to_end(call_id)
            return cached
        self.handled += 1
        try:
            handler = getattr(self, "_rpc_" + str(msg.get("m")), None)
            if handler is None:
                raise ValueError(f"agent rpc: unknown {msg.get('m')!r}")
            reply = {"id": call_id, "ok": handler(msg.get("a") or {})}
        except Exception as exc:      # noqa: BLE001
            reply = {"id": call_id, "err": outcome_to_wire(exc)}
        out = wire.encode_frame(reply, self.codec)
        if call_id is not None:
            self._done[call_id] = out
            while len(self._done) > self.IDEMPOTENCY_WINDOW:
                self._done.popitem(last=False)
        return out

    # -- RPCs ---------------------------------------------------------------
    def _rpc_hello(self, a):
        return {"host_id": self.host_id, "ordinal": self.ordinal,
                "pid": os.getpid(), "slots": self.slots,
                "mode": "proc" if self.proc else "local",
                "n_workers": len(self.workers),
                "chips": _chip_inventory()}

    def _rpc_ping(self, a):
        return True

    def _rpc_spawn_worker(self, a):
        from .cluster import LocalChild, ProcChild

        wid = int(a["replica_id"])
        spec = a.get("spec") or self.spec
        if wid in self.workers:
            raise ValueError(f"worker {wid} already running on "
                             f"{self.host_id}")
        if len(self.workers) >= self.slots:
            raise RuntimeError(
                f"host {self.host_id}: all {self.slots} slots in use")
        if self.proc:
            child = ProcChild(spec, wid, workdir=self.workdir)
            info = {"mode": "proc", "port": child.port, "pid": child.pid,
                    "scrape_port": child.scrape_port}
        else:
            child = LocalChild(spec, wid)
            info = {"mode": "local", "pid": child.pid}
        self.workers[wid] = child
        self.spawned += 1
        return dict(info, host=self.host_id, replica_id=wid)

    def _rpc_kill_worker(self, a):
        wid = int(a["replica_id"])
        child = self.workers.pop(wid, None)
        if child is None:
            return {"killed": False}
        child.kill()
        child.wait(timeout=10.0)
        child.close_logs()
        self.killed += 1
        return {"killed": True}

    def _rpc_list_workers(self, a):
        out = {}
        for wid, child in self.workers.items():
            out[str(wid)] = {
                "pid": child.pid,
                "port": getattr(child, "port", None),
                "alive": child.poll() is None,
            }
        return {"workers": out, "host": self.host_id}

    def _rpc_shutdown(self, a):
        self.close()
        return {"workers_killed": self.killed}

    # -- local-mode helpers -------------------------------------------------
    def worker_transport(self, wid, **kw):
        """A fresh loopback link to a local worker's server (heal
        re-adoption opens a NEW link; the old one died with its lease)."""
        return LoopbackTransport(self.workers[int(wid)].server, **kw)

    def close(self):
        self.shutting_down = True
        self.dead = True
        for wid in list(self.workers):
            child = self.workers.pop(wid)
            child.kill()
            child.wait(timeout=5.0)
            child.close_logs()
            self.killed += 1


# ---------------------------------------------------------------------------
# Supervisor-side client + handles
# ---------------------------------------------------------------------------
class AgentClient:
    """Typed client over any Transport to a HostAgent."""

    def __init__(self, transport, *, hello=True):
        self.transport = transport
        self.info = transport.call("hello") if hello else None

    def ping(self, timeout=None):
        return self.transport.call("ping", timeout=timeout)

    def spawn_worker(self, spec, replica_id, timeout=300.0):
        return self.transport.call(
            "spawn_worker", {"spec": spec, "replica_id": int(replica_id)},
            timeout=timeout)

    def kill_worker(self, replica_id, timeout=15.0):
        return self.transport.call(
            "kill_worker", {"replica_id": int(replica_id)},
            timeout=timeout)

    def list_workers(self, timeout=15.0):
        return self.transport.call("list_workers", timeout=timeout)

    def shutdown(self, timeout=15.0):
        return self.transport.call("shutdown", timeout=timeout)

    def close(self):
        self.transport.close()


class HostHandle:
    """The supervisor's view of one host: rendezvous record, agent
    client, liveness state, and every partition-gated link to it."""

    def __init__(self, host_id, ordinal, client, *, agent=None,
                 proc_agent=None, record=None):
        self.host_id = host_id
        self.ordinal = ordinal
        self.client = client
        self.agent = agent            # in-process HostAgent (local mode)
        self.proc_agent = proc_agent  # AgentProc (process-tree mode)
        self.record = record or {}
        self.state = "alive"          # alive | severed
        self.last_beats = 0
        self.last_advance = time.monotonic()
        self.links = []               # PartitionedLink per link to host
        self.replicas = set()         # router idxs currently placed here
        self.pending = 0              # spawned, not yet router-registered
        self.worker_pids = []         # every pid ever spawned (cleanup)

    # -- chaos seam ---------------------------------------------------------
    def sever(self):
        """Partition this host away: every supervisor link to it drops,
        and its heartbeats stop reaching the store (local mode flips the
        agent's severed flag; process mode SIGSTOPs the agent, freezing
        its heartbeat thread — a partitioned host is cut off from BOTH
        the supervisor and the store, which is what lets the host lease
        expire and the fencing replay fire)."""
        if self.agent is not None:
            self.agent.severed = True
        if self.proc_agent is not None:
            self.proc_agent.stop()
        for link in self.links:
            link.sever()

    def heal(self):
        if self.agent is not None:
            self.agent.severed = False
        if self.proc_agent is not None:
            self.proc_agent.cont()
        for link in self.links:
            link.heal()

    def kill_agent(self):
        """SIGKILL the host's agent process (host-loss chaos; workers
        are orphaned and only the fencing epoch protects their rids)."""
        if self.proc_agent is not None:
            self.proc_agent.kill()
        elif self.agent is not None:
            self.agent.dead = True
            self.agent.shutting_down = True


class HostedChild:
    """Supervisor-side facade for a worker living behind a host agent —
    duck-types the child surface (poll/kill/terminate/wait/close_logs)
    the supervisor already drives for local children.  A remote worker
    cannot be waitpid'd; liveness is the lease's job, and kill/terminate
    are best-effort RPCs to the agent (which may be partitioned away —
    the fencing epoch is what actually retires a stranded worker)."""

    def __init__(self, host, replica_id, info, transport):
        self.host = host
        self.host_id = host.host_id
        self.replica_id = int(replica_id)
        self.info = dict(info)
        self.pid = info.get("pid")
        self.transport = transport
        self._dead = False
        if self.pid is not None and self.pid > 0:
            host.worker_pids.append(self.pid)

    def poll(self):
        if self._dead:
            return -int(signal.SIGKILL)
        if self.host.agent is not None:
            child = self.host.agent.workers.get(self.replica_id)
            return (-int(signal.SIGKILL) if child is None
                    else child.poll())
        return None

    def _kill_rpc(self):
        try:
            self.host.client.kill_worker(self.replica_id, timeout=5.0)
        except Exception:
            pass                      # partitioned/killed agent: fenced

    def kill(self):
        if not self._dead:
            self._dead = True
            self._kill_rpc()

    def terminate(self):
        self.kill()

    def wait(self, timeout=None):
        return self.poll()

    def close_logs(self):
        pass


def spawn_on_host(host, spec, replica_id, *, transport_kw=None):
    """Spawn one worker via ``host``'s agent and return a
    :class:`HostedChild` whose transport is partition-gated (the host's
    :meth:`HostHandle.sever` drops it with everything else)."""
    from ...testing.chaos import PartitionedLink

    info = host.client.spawn_worker(spec, replica_id)
    if info.get("mode") == "proc":
        raw = SocketTransport(host.record.get("address", "127.0.0.1"),
                              info["port"], seed=replica_id,
                              **(transport_kw or {}))
    else:
        raw = host.agent.worker_transport(replica_id, seed=replica_id,
                                          **(transport_kw or {}))
    link = PartitionedLink(raw)
    if host.state != "alive":
        link.sever()
    host.links.append(link)
    return HostedChild(host, replica_id, info, link)


# ---------------------------------------------------------------------------
# Launchers
# ---------------------------------------------------------------------------
def spawn_local_agent(spec, host_id, directory, *, slots=8,
                      heartbeat_every=0.05, transport_kw=None,
                      heartbeat_thread=True):
    """In-process host: a HostAgent object whose workers are
    LocalChildren, reached over a partition-gated loopback link — the
    tier-1 multi-host topology."""
    from ...testing.chaos import PartitionedLink

    agent = HostAgent(spec, host_id=host_id, proc=False, slots=slots,
                      directory=directory,
                      heartbeat_every=heartbeat_every)
    agent.register()
    if heartbeat_thread:
        agent.start_heartbeat()
    link = PartitionedLink(
        LoopbackTransport(agent, seed=agent.ordinal + 7919,
                          **(transport_kw or {})))
    handle = HostHandle(host_id, agent.ordinal, AgentClient(link),
                        agent=agent, record=directory.get(agent.ordinal))
    handle.links.append(link)
    handle.last_beats = directory.beats(agent.ordinal)
    return handle


def spawn_proc_agent(spec, host_id, directory, *, store, workdir,
                     slots=8, transport_kw=None, spawn_timeout=180.0):
    """Process-tree host: launch ``python -m …fleet.hosts`` (which
    registers ITSELF in the store), then discover it back through the
    directory and connect — the same path a remote supervisor takes."""
    from ...testing.chaos import PartitionedLink

    proc_agent = AgentProc(spec, host_id, store_host=store.host,
                           store_port=store.port, workdir=workdir,
                           slots=slots, spawn_timeout=spawn_timeout)
    record = directory.get(proc_agent.ordinal)
    if record is None:
        raise TransportError(
            f"host {host_id}: agent handshook but never registered")
    link = PartitionedLink(SocketTransport(
        record.get("address", "127.0.0.1"), record["port"],
        seed=proc_agent.ordinal + 7919, **(transport_kw or {})))
    handle = HostHandle(host_id, proc_agent.ordinal, AgentClient(link),
                        proc_agent=proc_agent, record=record)
    handle.links.append(link)
    handle.last_beats = directory.beats(proc_agent.ordinal)
    return handle


class AgentProc:
    """A real host-agent subprocess (its workers are grandchildren).
    Mirrors cluster.ProcChild: spec file + log file + one-line stdout
    handshake, SIGKILL-able for host-loss chaos."""

    HANDSHAKE = "PTPU_AGENT_READY "

    def __init__(self, spec, host_id, *, store_host, store_port,
                 workdir, slots=8, spawn_timeout=180.0):
        from ...testing.chaos import subprocess_env

        os.makedirs(workdir, exist_ok=True)
        agent_spec = {
            "worker_spec": dict(spec),
            "host_id": str(host_id),
            "store_host": store_host,
            "store_port": int(store_port),
            "slots": int(slots),
            "workdir": os.path.join(workdir, f"host_{host_id}"),
            "flight_dir": spec.get("flight_dir"),
        }
        self.log_path = os.path.join(workdir, f"agent_{host_id}.log")
        self._log = open(self.log_path, "ab", buffering=0)
        spec_path = os.path.join(workdir, f"agent_{host_id}.spec.json")
        with open(spec_path, "w") as f:
            json.dump(agent_spec, f)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.inference.fleet.hosts",
             "--spec-file", spec_path],
            stdout=subprocess.PIPE, stderr=self._log,
            env=subprocess_env(), cwd=os.getcwd())
        self.pid = self.proc.pid
        info = self._handshake(spawn_timeout)
        self.port = info["port"]
        self.ordinal = info["ordinal"]
        self.proc.stdout.close()

    def _handshake(self, timeout):
        import select

        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not select.select(
                    [self.proc.stdout], [], [], max(remaining, 0.0))[0]:
                self.proc.kill()
                raise TransportError(
                    f"host agent pid {self.pid}: no handshake in "
                    f"{timeout}s (log: {self.log_path})")
            line = self.proc.stdout.readline()
            if not line:
                rc = self.proc.wait()
                raise TransportError(
                    f"host agent pid {self.pid} exited {rc} before "
                    f"handshake (log: {self.log_path})")
            self._log.write(line)
            text = line.decode("utf-8", "replace")
            if text.startswith(self.HANDSHAKE):
                return json.loads(text[len(self.HANDSHAKE):])

    def poll(self):
        return self.proc.poll()

    def kill(self):
        try:
            self.proc.kill()
        except OSError:
            pass

    def terminate(self):
        try:
            self.proc.terminate()
        except OSError:
            pass

    def stop(self):
        """SIGSTOP: freeze the agent (heartbeat thread included) —
        the process-tree half of a host partition."""
        try:
            os.kill(self.pid, signal.SIGSTOP)
        except OSError:
            pass

    def cont(self):
        try:
            os.kill(self.pid, signal.SIGCONT)
        except OSError:
            pass

    def wait(self, timeout=None):
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def close_logs(self):
        try:
            self._log.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Agent process entry point
# ---------------------------------------------------------------------------
def main(argv=None):
    ap = argparse.ArgumentParser(prog="paddle_tpu.inference.fleet.hosts")
    ap.add_argument("--spec-file", required=True,
                    help="path to a JSON host-agent spec")
    args = ap.parse_args(argv)
    with open(args.spec_file) as f:
        spec = json.load(f)

    host_id = spec.get("host_id", "host0")
    flight_dir = spec.get("flight_dir")
    if flight_dir:
        from ...telemetry import flight as _flight

        _flight.install(flight_dir)
    from .worker import _install_crash_paths

    _install_crash_paths(f"agent:{host_id}")

    store = TCPStore(host=spec.get("store_host", "127.0.0.1"),
                     port=int(spec["store_port"]), is_master=False)
    directory = HostDirectory(store)
    agent = HostAgent(spec.get("worker_spec") or {}, host_id=host_id,
                      proc=True, slots=spec.get("slots", 8),
                      workdir=spec.get("workdir"), directory=directory,
                      heartbeat_every=spec.get("heartbeat_every", 0.2))
    loop = SocketServerLoop(agent, port=spec.get("port", 0))
    agent.register(address="127.0.0.1", port=loop.port)
    print(AgentProc.HANDSHAKE + json.dumps({
        "port": loop.port, "pid": os.getpid(),
        "ordinal": agent.ordinal, "host_id": host_id}), flush=True)
    agent.start_heartbeat()
    loop.serve_forever()
    agent.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
