"""Admission router: N engine replicas behind one submit() surface.

Production traffic needs more than one continuous-batching engine; this
router owns the fleet topology (docs/SERVING.md):

- **Pluggable dispatch policies.** ``round_robin`` (the baseline),
  ``least_loaded`` (scores replicas on the live queue-depth/slot/KV
  telemetry ``ContinuousBatchingEngine.load()`` exposes — the PR 11
  signals, read synchronously), and ``prefix_affinity`` (routes a
  request to the replica whose prefix cache already holds the longest
  prefix of its prompt — ``prefix_match_pages()`` — falling back to
  least-loaded on a miss). Ties break deterministically on the lowest
  replica index, so routing is reproducible.
- **Backpressure.** Each replica accepts at most ``max_queue_depth``
  waiting requests; overflow stays in the router's own pending queue
  and is re-scored every tick (late binding: a request dispatches to
  whichever replica is best when capacity appears, not when it arrived).
- **Health + requeue-on-death.** A replica whose ``step()`` raises is
  marked dead; every request it held (queued, running, or swapped) is
  resubmitted through the policy to the survivors with the SAME request
  id — at-least-once semantics, and greedy outputs are deterministic so
  the replay is invisible to the caller. Generated-so-far tokens are
  recomputed from the original prompt (the dead replica's KV is gone).

Request ids are globally unique across the fleet (each replica gets a
disjoint ``rid_base`` space and the router passes explicit rids), so
the per-request trace trees (docs/TELEMETRY.md Tracing) — including the
router's ``route`` span — reassemble per request, never colliding
across replicas.
"""
from __future__ import annotations

import time
from collections import deque

from ... import telemetry as _telemetry
from ...telemetry import trace as _trace

__all__ = ["FleetRouter", "ReplicaHandle", "POLICIES"]

_DISPATCH = _telemetry.counter(
    "fleet_dispatch_total", "requests dispatched to a replica",
    labelnames=("policy", "replica"))
_REQUEUES = _telemetry.counter(
    "fleet_requeues_total",
    "requests recovered from a dead replica and resubmitted")
_DEATHS = _telemetry.counter(
    "fleet_replica_deaths_total", "replicas marked unhealthy")
_PENDING = _telemetry.gauge(
    "fleet_pending_depth", "requests held in the router (backpressure)")
_HEALTHY = _telemetry.gauge(
    "fleet_replicas_healthy", "replicas currently serving")

#: rid spacing between replicas — disjoint id spaces for trace trees
RID_STRIDE = 1_000_000


def _load_score(handle):
    """Lower is better: waiting requests weigh full, occupied slots
    partial (they drain one token per tick), low KV headroom penalizes."""
    load = handle.engine.load()
    return (load["queue_depth"] + 0.5 * load["occupied_slots"]
            + (1.0 - load["kv_free_fraction"]))


def _policy_round_robin(router, prompt, candidates):
    idx = candidates[router._rr_cursor % len(candidates)]
    router._rr_cursor += 1
    return idx


def _policy_least_loaded(router, prompt, candidates):
    return min(candidates,
               key=lambda i: (_load_score(router.replicas[i]), i))


def _policy_prefix_affinity(router, prompt, candidates):
    """Most cached prefix pages wins; zero-hit prompts fall back to
    least-loaded (which also breaks exact ties)."""
    hits = {i: router.replicas[i].engine.prefix_match_pages(prompt)
            for i in candidates}
    best = max(hits.values())
    if best <= 0:
        return _policy_least_loaded(router, prompt, candidates)
    front = [i for i in candidates if hits[i] == best]
    return min(front, key=lambda i: (_load_score(router.replicas[i]), i))


POLICIES = {
    "round_robin": _policy_round_robin,
    "least_loaded": _policy_least_loaded,
    "prefix_affinity": _policy_prefix_affinity,
}


class ReplicaHandle:
    """One replica's router-side state: health, dispatch bookkeeping,
    and the accumulated busy-time the soak's simulated-parallel clock
    uses (replicas run concurrently in deployment; in-process they tick
    sequentially, so wall time is NOT the fleet critical path)."""

    __slots__ = ("idx", "engine", "healthy", "dispatched", "steps",
                 "busy_seconds", "death_reason")

    def __init__(self, idx, engine):
        self.idx = idx
        self.engine = engine
        self.healthy = True
        self.dispatched = 0
        self.steps = 0
        self.busy_seconds = 0.0
        self.death_reason = None


class FleetRouter:
    """Dispatch requests across replicas; tick the whole fleet per
    ``step()``. ``engines`` is a list of ContinuousBatchingEngine (or
    anything matching its fleet surface: submit/step/cancel/load/
    prefix_match_pages/cancelled, e.g. fleet.DisaggregatedEngine)."""

    def __init__(self, engines, policy="least_loaded",
                 max_queue_depth=None):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        if callable(policy):
            self._policy_name = getattr(policy, "__name__", "custom")
            self._policy = policy
        else:
            if policy not in POLICIES:
                raise ValueError(
                    f"unknown policy {policy!r}; one of {sorted(POLICIES)}")
            self._policy_name = policy
            self._policy = POLICIES[policy]
        self.replicas = [ReplicaHandle(i, e) for i, e in enumerate(engines)]
        # backpressure cap per replica: its slots plus one refill wave
        self.max_queue_depth = (max_queue_depth
                                if max_queue_depth is not None
                                else 2 * max(e.max_slots for e in engines))
        self._pending = deque()      # (rid, prompt, kwargs) awaiting dispatch
        self._inflight = {}          # rid -> (replica idx, prompt, kwargs)
        self._next_rid = 0
        self._rr_cursor = 0
        self._delivered = {}         # rid -> tokens streamed to the client
        self.cancelled = {}          # rid -> reason (merged fleet view)
        self.requeues = 0

    # -- submit / cancel ----------------------------------------------------
    def submit(self, prompt_ids, **kwargs) -> int:
        """Mint a fleet-wide rid, open its ``route`` span, and dispatch
        (or hold under backpressure — dispatch retries every step). A
        ``deadline_seconds`` is stamped to an absolute point NOW, at
        router submit: time spent queued under backpressure counts
        against the deadline (the engine otherwise restarts the clock
        at dispatch, silently extending it)."""
        rid = self._next_rid
        self._next_rid += 1
        prompt = [int(t) for t in prompt_ids]
        kwargs = dict(kwargs)
        if kwargs.get("deadline_seconds") is not None:
            kwargs["_deadline_at"] = (time.perf_counter()
                                      + float(kwargs.pop("deadline_seconds")))
        if kwargs.get("on_token") is not None:
            # count delivered tokens so a dead-replica replay can skip
            # the already-streamed prefix: the streaming contract stays
            # exactly-once for greedy requests (the replayed prefix is
            # bitwise the delivered one; sampled replays may diverge
            # and are documented at-least-once)
            self._delivered[rid] = 0
            kwargs["_on_token"] = kwargs.pop("on_token")
        _trace.async_begin("route", rid, {"policy": self._policy_name})
        self._pending.append((rid, prompt, kwargs))
        self._dispatch_pending()
        return rid

    def cancel(self, rid, reason="user") -> bool:
        for i, (prid, _p, _kw) in enumerate(self._pending):
            if prid == rid:
                del self._pending[i]
                self.cancelled[rid] = reason
                # no engine ever saw this rid: only the route span is
                # open (no "request" span to close)
                _trace.async_end("route", rid, {"cancelled": reason})
                return True
        entry = self._inflight.get(rid)
        if entry is None:
            return False
        handle = self.replicas[entry[0]]
        if handle.engine.cancel(rid, reason=reason):
            self._inflight.pop(rid, None)
            self.cancelled[rid] = reason
            return True
        return False

    # -- dispatch -----------------------------------------------------------
    def _candidates(self):
        return [h.idx for h in self.replicas
                if h.healthy
                and h.engine.load()["queue_depth"] < self.max_queue_depth]

    def _dispatch_pending(self):
        while self._pending:
            cands = self._candidates()
            if not cands:
                return               # backpressure: hold in the router
            rid, prompt, kwargs = self._pending[0]
            idx = self._policy(self, prompt, cands)
            handle = self.replicas[idx]
            self._pending.popleft()
            kw = dict(kwargs)
            at = kw.pop("_deadline_at", None)
            if at is not None:
                # remaining budget at dispatch; <= 0 cancels on the
                # replica's first tick (the request is already late)
                kw["deadline_seconds"] = at - time.perf_counter()
            cb = kw.pop("_on_token", None)
            if cb is not None:
                # suppress the first `skip` tokens of THIS dispatch's
                # stream: a dead-replica replay regenerates from
                # scratch, and the client already received that prefix
                skip = self._delivered.get(rid, 0)
                state = {"seen": 0}

                def on_token(r, t, _cb=cb, _skip=skip, _state=state):
                    _state["seen"] += 1
                    if _state["seen"] > _skip:
                        self._delivered[r] = self._delivered.get(r, 0) + 1
                        _cb(r, t)

                kw["on_token"] = on_token
            handle.engine.submit(prompt, rid=rid, **kw)
            handle.dispatched += 1
            self._inflight[rid] = (idx, prompt, kwargs)
            _DISPATCH.inc(labels=(self._policy_name, str(idx)))
            _trace.async_end("route", rid, {"replica": idx})

    # -- fleet tick ---------------------------------------------------------
    def _on_death(self, handle, exc):
        """Mark a replica dead and requeue everything it held. The
        engine's internal state is untrusted after an arbitrary failure;
        requests replay from their original prompts."""
        handle.healthy = False
        handle.death_reason = repr(exc)
        _DEATHS.inc()
        lost = [rid for rid, (idx, _p, _kw) in self._inflight.items()
                if idx == handle.idx]
        for rid in lost:
            _idx, prompt, kwargs = self._inflight.pop(rid)
            self.requeues += 1
            _REQUEUES.inc()
            _trace.async_instant("requeue", rid,
                                 {"dead_replica": handle.idx})
            _trace.async_begin("route", rid,
                               {"policy": self._policy_name,
                                "requeue": True})
            self._pending.append((rid, prompt, kwargs))
        if not any(h.healthy for h in self.replicas):
            raise RuntimeError(
                "FleetRouter: every replica is dead "
                f"(last failure: {handle.death_reason})") from exc

    def step(self):
        """Dispatch pending work, tick every healthy replica, collect
        completions/cancellations, recover from replica deaths.
        Returns {rid: full token ids} finishing this fleet tick."""
        self._dispatch_pending()
        done = {}
        for handle in self.replicas:
            if not handle.healthy:
                continue
            t0 = time.perf_counter()
            try:
                out = handle.engine.step()
            except Exception as exc:  # noqa: BLE001 — any failure = death
                self._on_death(handle, exc)
                continue
            handle.busy_seconds += time.perf_counter() - t0
            handle.steps += 1
            for rid, ids in out.items():
                self._inflight.pop(rid, None)
                self._delivered.pop(rid, None)
                done[rid] = ids
            eng_cancelled = getattr(handle.engine, "cancelled", None)
            if eng_cancelled:
                for rid, reason in list(eng_cancelled.items()):
                    eng_cancelled.pop(rid)
                    self._inflight.pop(rid, None)
                    self._delivered.pop(rid, None)
                    self.cancelled[rid] = reason
        self._dispatch_pending()     # freed slots admit the next wave
        if _telemetry.get_registry().enabled:
            _PENDING.set(len(self._pending))
            _HEALTHY.set(sum(1 for h in self.replicas if h.healthy))
        return done

    def drained(self):
        if self._pending or self._inflight:
            return False
        return all(not h.healthy or (
            h.engine.load()["queue_depth"] == 0
            and h.engine.load()["occupied_slots"] == 0)
            for h in self.replicas)

    def run_until_complete(self, max_ticks=100000):
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            if self.drained():
                return done
        raise TimeoutError("fleet did not drain")

    def load(self):
        """Aggregate fleet load (what a front-end LB would scrape)."""
        per = [dict(h.engine.load(), replica=h.idx, healthy=h.healthy,
                    dispatched=h.dispatched)
               for h in self.replicas]
        return {"pending": len(self._pending),
                "inflight": len(self._inflight),
                "replicas": per}


def make_replicas(model_factory, n, rid_stride=RID_STRIDE, **engine_kw):
    """Build n engines with disjoint rid spaces. ``model_factory`` is
    called once per replica (each replica owns its weights in a real
    deployment; passing a shared model is fine for in-process tests)."""
    from ..serving import ContinuousBatchingEngine

    return [ContinuousBatchingEngine(model_factory(i),
                                     rid_base=i * rid_stride, **engine_kw)
            for i in range(n)]
