"""Admission router: N engine replicas behind one submit() surface.

Production traffic needs more than one continuous-batching engine; this
router owns the fleet topology (docs/SERVING.md):

- **Pluggable dispatch policies.** ``round_robin`` (the baseline),
  ``least_loaded`` (scores replicas on the live queue-depth/slot/KV
  telemetry ``ContinuousBatchingEngine.load()`` exposes — the PR 11
  signals, read synchronously), and ``prefix_affinity`` (routes a
  request to the replica whose prefix cache already holds the longest
  prefix of its prompt — ``prefix_match_pages()`` — falling back to
  least-loaded on a miss). Ties break deterministically on the lowest
  replica index, so routing is reproducible.
- **Backpressure.** Each replica accepts at most ``max_queue_depth``
  waiting requests; overflow stays in the router's own pending queue
  and is re-scored every tick (late binding: a request dispatches to
  whichever replica is best when capacity appears, not when it arrived).
- **Health + circuit breakers + requeue (docs/SERVING.md "Overload &
  degradation").** A replica whose ``step()`` raises a *fatal* fault is
  marked dead after ``max_consecutive_fatal`` in a row (default 1 — the
  pre-overload behavior); every request it held (queued, running, or
  swapped) is resubmitted through the policy to the survivors with the
  SAME request id — at-least-once semantics, and greedy outputs are
  deterministic so the replay is invisible to the caller (the streamed
  prefix is suppressed, so the client stream stays exactly-once).
  *Transient* faults (``overload.classify_step_exception``) instead
  tick a per-replica circuit breaker: past the error-rate threshold the
  breaker OPENS (the replica's work requeues through the same replay
  machinery, dispatch routes around it), backs off exponentially with
  deterministic jitter, half-opens for a single probe request, and
  closes after consecutive clean steps — a flaky replica loses traffic
  for a backoff, not forever.
- **Admission control / shedding / brownout.** With an
  ``overload.OverloadConfig`` carrying an SLO or watermarks, ``submit``
  rejects with a structured ``Overloaded(retry_after)`` terminal
  outcome when the predicted TTFT breaks the SLO (or the queue-depth /
  rate-limit watermark trips), each ``step()`` sheds queued
  deadline-infeasible / lowest-priority requests past the shed
  watermark (``router.shed`` maps rid -> reason), and the brownout
  ladder reversibly degrades the engines under sustained pressure.
  ``PTPU_OVERLOAD=0`` keeps every pre-overload code path bitwise.

Request ids are globally unique across the fleet (each replica gets a
disjoint ``rid_base`` space and the router passes explicit rids), so
the per-request trace trees (docs/TELEMETRY.md Tracing) — including the
router's ``route`` span — reassemble per request, never colliding
across replicas.
"""
from __future__ import annotations

import time
from collections import deque

from ... import telemetry as _telemetry
from ...telemetry import flight as _flight
from ...telemetry import trace as _trace
from . import overload as _overload

__all__ = ["FleetRouter", "ReplicaHandle", "POLICIES"]

_DISPATCH = _telemetry.counter(
    "fleet_dispatch_total", "requests dispatched to a replica",
    labelnames=("policy", "replica"))
_REQUEUES = _telemetry.counter(
    "fleet_requeues_total",
    "requests recovered from a dead replica and resubmitted")
_DEATHS = _telemetry.counter(
    "fleet_replica_deaths_total", "replicas marked unhealthy")
_PENDING = _telemetry.gauge(
    "fleet_pending_depth", "requests held in the router (backpressure)")
_HEALTHY = _telemetry.gauge(
    "fleet_replicas_healthy", "replicas currently serving")

#: rid spacing between replicas — disjoint id spaces for trace trees
RID_STRIDE = 1_000_000


def _load_score(handle):
    """Lower is better: waiting requests weigh full, occupied slots
    partial (they drain one token per tick), low KV headroom penalizes."""
    load = handle.engine.load()
    return (load["queue_depth"] + 0.5 * load["occupied_slots"]
            + (1.0 - load["kv_free_fraction"]))


def _policy_round_robin(router, prompt, candidates):
    idx = candidates[router._rr_cursor % len(candidates)]
    router._rr_cursor += 1
    return idx


def _policy_least_loaded(router, prompt, candidates):
    return min(candidates, key=lambda i: (router._score(i), i))


def _policy_prefix_affinity(router, prompt, candidates):
    """Most cached prefix pages wins; zero-hit prompts fall back to
    least-loaded (which also breaks exact ties)."""
    hits = {i: router.replicas[i].engine.prefix_match_pages(prompt)
            for i in candidates}
    best = max(hits.values())
    if best <= 0:
        return _policy_least_loaded(router, prompt, candidates)
    front = [i for i in candidates if hits[i] == best]
    return min(front, key=lambda i: (router._score(i), i))


POLICIES = {
    "round_robin": _policy_round_robin,
    "least_loaded": _policy_least_loaded,
    "prefix_affinity": _policy_prefix_affinity,
}


class ReplicaHandle:
    """One replica's router-side state: health, dispatch bookkeeping,
    and the accumulated busy-time the soak's simulated-parallel clock
    uses (replicas run concurrently in deployment; in-process they tick
    sequentially, so wall time is NOT the fleet critical path)."""

    __slots__ = ("idx", "engine", "healthy", "dispatched", "steps",
                 "busy_seconds", "death_reason", "draining", "retired",
                 "host")

    def __init__(self, idx, engine):
        self.idx = idx
        self.engine = engine
        self.healthy = True
        # failure-domain id (fleet.hosts host_id); None on a single-host
        # fleet, which keeps every scoring path bitwise pre-hosts
        self.host = None
        self.dispatched = 0
        self.steps = 0
        self.busy_seconds = 0.0
        self.death_reason = None
        # draining: no NEW dispatches (rolling upgrade / scale-down);
        # inflight work still ticks.  retired: out of the fleet for good
        # (scale-down completed) — distinct from dead so it doesn't
        # count as a failure in stats or trip the all-dead check.
        self.draining = False
        self.retired = False


class FleetRouter:
    """Dispatch requests across replicas; tick the whole fleet per
    ``step()``. ``engines`` is a list of ContinuousBatchingEngine (or
    anything matching its fleet surface: submit/step/cancel/load/
    prefix_match_pages/cancelled, e.g. fleet.DisaggregatedEngine)."""

    def __init__(self, engines, policy="least_loaded",
                 max_queue_depth=None, overload=None):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        if callable(policy):
            self._policy_name = getattr(policy, "__name__", "custom")
            self._policy = policy
        else:
            if policy not in POLICIES:
                raise ValueError(
                    f"unknown policy {policy!r}; one of {sorted(POLICIES)}")
            self._policy_name = policy
            self._policy = POLICIES[policy]
        self.replicas = [ReplicaHandle(i, e) for i, e in enumerate(engines)]
        # backpressure cap per replica: its slots plus one refill wave
        self.max_queue_depth = (max_queue_depth
                                if max_queue_depth is not None
                                else 2 * max(e.max_slots for e in engines))
        # pending/inflight entries: (rid, prompt, kwargs, priority)
        self._pending = deque()      # awaiting dispatch (backpressure)
        self._inflight = {}          # rid -> (replica idx, prompt, kw, pri)
        self._next_rid = 0
        self._rr_cursor = 0
        self._delivered = {}         # rid -> tokens streamed to the client
        self.cancelled = {}          # rid -> reason (merged fleet view)
        self.shed = {}               # rid -> reason (overload shedding)
        self.requeues = 0
        self.served = 0              # completions returned by step()
        # cross-host placement (fleet.hosts): host-pressure weight in
        # the policy score, and the shedding-becomes-migration hook the
        # supervisor installs — both inert on a single-host fleet
        self.host_spread = 0.25
        self.shed_rescue = None      # (entry, reason) -> bool (rescued)
        self.rescued = 0
        # overload machinery (fleet.overload, docs/SERVING.md "Overload
        # & degradation"): None (PTPU_OVERLOAD=0 or overload=False)
        # keeps every pre-overload code path — any step() exception is
        # permanent death, no admission control, no shedding/brownout
        cfg = _overload.resolve_config(overload)
        self._ov = (_overload.OverloadController(cfg, len(engines))
                    if cfg is not None else None)

    # -- submit / cancel ----------------------------------------------------
    def submit(self, prompt_ids, priority="interactive", **kwargs) -> int:
        """Mint a fleet-wide rid, open its ``route`` span, and dispatch
        (or hold under backpressure — dispatch retries every step). A
        ``deadline_seconds`` is stamped to an absolute point NOW, at
        router submit: time spent queued under backpressure counts
        against the deadline (the engine otherwise restarts the clock
        at dispatch, silently extending it).

        With overload control active, admission runs FIRST: the request
        may be rejected with a structured :class:`.overload.Overloaded`
        (carrying ``retry_after``) instead of queueing — nothing is
        minted for a rejected request. ``priority`` ("interactive" |
        "batch") orders dispatch and shedding; without overload control
        it is accepted and ignored (plain FIFO)."""
        if self._ov is not None:
            self._ov.admit(self, priority)     # may raise Overloaded
        rid = self._next_rid
        self._next_rid += 1
        prompt = [int(t) for t in prompt_ids]
        kwargs = dict(kwargs)
        clock = (self._ov.clock if self._ov is not None
                 else time.perf_counter)
        if kwargs.get("deadline_seconds") is not None:
            kwargs["_deadline_at"] = (clock()
                                      + float(kwargs.pop("deadline_seconds")))
        if kwargs.get("on_token") is not None or self._ov is not None:
            # count delivered tokens so a dead-replica (or breaker)
            # replay can skip the already-streamed prefix: the streaming
            # contract stays exactly-once for greedy requests (the
            # replayed prefix is bitwise the delivered one; sampled
            # replays may diverge and are documented at-least-once).
            # Overload control always installs the wrapper — the first
            # delivered token is the TTFT observation the admission
            # predictor learns from.
            self._delivered[rid] = 0
            kwargs["_on_token"] = kwargs.pop("on_token", None)
        if self._ov is not None:
            self._ov.predictor.note_submit(rid)
        _trace.async_begin("route", rid, {"policy": self._policy_name})
        self._pending.append((rid, prompt, kwargs, priority))
        self._dispatch_pending()
        return rid

    def cancel(self, rid, reason="user") -> bool:
        for i, entry in enumerate(self._pending):
            if entry[0] == rid:
                del self._pending[i]
                self.cancelled[rid] = reason
                self._delivered.pop(rid, None)
                if self._ov is not None:
                    self._ov.predictor.forget(rid)
                # no engine ever saw this rid: only the route span is
                # open (no "request" span to close)
                _trace.async_end("route", rid, {"cancelled": reason})
                return True
        entry = self._inflight.get(rid)
        if entry is None:
            return False
        handle = self.replicas[entry[0]]
        if handle.engine.cancel(rid, reason=reason):
            self._inflight.pop(rid, None)
            self.cancelled[rid] = reason
            self._delivered.pop(rid, None)
            if self._ov is not None:
                self._ov.predictor.forget(rid)
            return True
        return False

    # -- dispatch -----------------------------------------------------------
    def _score(self, i):
        """Policy score for replica ``i``: the load score plus — only
        when the fleet spans hosts — a host-pressure term that spreads
        traffic across failure domains (two equally-loaded replicas
        tie-break to the quieter host).  With no host mapping the term
        vanishes and scoring is bitwise the pre-hosts behavior."""
        h = self.replicas[i]
        score = _load_score(h)
        if h.host is not None:
            score += self.host_spread * self._host_pressure(h.host)
        return score

    def _host_pressure(self, host):
        """Mean in-flight load (waiting + running) per replica on
        ``host``, normalized by its replica count so a big host is not
        penalized for being big."""
        total, n = 0.0, 0
        for h in self.replicas:
            if h.host != host or not h.healthy or h.retired:
                continue
            load = h.engine.load()
            total += load["queue_depth"] + load["occupied_slots"]
            n += 1
        return total / n if n else 0.0

    def _replica_inflight(self, idx):
        return sum(1 for entry in self._inflight.values()
                   if entry[0] == idx)

    def _candidates(self):
        # per-replica inflight counts matter only to half-open probe
        # gating; one O(inflight) pass, and only when a breaker is
        # actually out of the closed state
        counts = None
        if self._ov is not None and any(
                br.state != "closed" for br in self._ov.breakers):
            counts = {}
            for entry in self._inflight.values():
                counts[entry[0]] = counts.get(entry[0], 0) + 1
        cands = []
        for h in self.replicas:
            if not h.healthy or h.draining or h.retired:
                continue
            if h.engine.load()["queue_depth"] >= self.max_queue_depth:
                continue
            if self._ov is not None:
                # route around open breakers; a half-open replica takes
                # exactly one probe request at a time
                br = self._ov.breakers[h.idx]
                if not br.routable(0 if counts is None
                                   else counts.get(h.idx, 0)):
                    continue
            cands.append(h.idx)
        return cands

    def _next_pending(self):
        """Index of the next entry to dispatch: plain FIFO without
        overload control; priority-aware FIFO (interactive before
        batch, arrival order within a class) with it."""
        if self._ov is None or len(self._pending) <= 1:
            return 0
        for i, entry in enumerate(self._pending):
            if (entry[3] if len(entry) > 3 else "interactive") \
                    == "interactive":
                return i
        return 0

    def _prepared_kwargs(self, rid, kwargs):
        """Turn a pending entry's stored kwargs into submit kwargs:
        stamp the remaining deadline budget and install the
        delivered-token suppression wrapper (shared by the policy
        dispatch path and the shed-rescue targeted dispatch)."""
        kw = dict(kwargs)
        at = kw.pop("_deadline_at", None)
        if at is not None:
            # remaining budget at dispatch; <= 0 cancels on the
            # replica's first tick (the request is already late)
            now = (self._ov.clock() if self._ov is not None
                   else time.perf_counter())
            kw["deadline_seconds"] = at - now
        cb = kw.pop("_on_token", None)
        if cb is not None or rid in self._delivered:
            # suppress the first `skip` tokens of THIS dispatch's
            # stream: a dead-replica (or breaker-open) replay
            # regenerates from scratch, and the client already
            # received that prefix. The wrapper also feeds the
            # admission predictor its TTFT observations.
            skip = self._delivered.get(rid, 0)
            state = {"seen": 0}

            def on_token(r, t, _cb=cb, _skip=skip, _state=state):
                _state["seen"] += 1
                if _state["seen"] > _skip:
                    n = self._delivered.get(r, 0) + 1
                    self._delivered[r] = n
                    if n == 1 and self._ov is not None:
                        self._ov.predictor.note_first_token(r)
                    if _cb is not None:
                        _cb(r, t)

            kw["on_token"] = on_token
        return kw

    def dispatch_to(self, entry, idx):
        """Dispatch one specific pending entry to one specific replica —
        the shedding-becomes-migration path (a supervisor found real
        headroom on another host for a would-be shed victim).  Returns
        True if the entry left the pending queue for ``idx``; False
        leaves it exactly where it was (the shed proceeds)."""
        try:
            pos = self._pending.index(entry)
        except ValueError:
            return False
        rid, prompt, kwargs, priority = entry
        handle = self.replicas[idx]
        try:
            handle.engine.submit(prompt, rid=rid,
                                 **self._prepared_kwargs(rid, kwargs))
        except Exception:              # noqa: BLE001
            return False               # best-effort; victim sheds
        del self._pending[pos]
        handle.dispatched += 1
        self._inflight[rid] = (idx, prompt, kwargs, priority)
        _DISPATCH.inc(labels=("shed_rescue", str(idx)))
        _trace.async_end("route", rid, {"replica": idx, "rescued": True})
        return True

    def _dispatch_pending(self):
        while self._pending:
            cands = self._candidates()
            if not cands:
                return               # backpressure: hold in the router
            pick = self._next_pending()
            rid, prompt, kwargs, priority = self._pending[pick]
            idx = self._policy(self, prompt, cands)
            handle = self.replicas[idx]
            del self._pending[pick]
            kw = self._prepared_kwargs(rid, kwargs)
            try:
                handle.engine.submit(prompt, rid=rid, **kw)
            except Exception as exc:   # noqa: BLE001
                if _overload.classify_step_exception(exc) != "transient":
                    raise
                # the replica died between health checks (a SIGKILLed
                # child whose lease hasn't expired yet): declare it dead
                # now — its inflight work requeues — and put THIS
                # request back at the head for a surviving candidate
                self._pending.appendleft((rid, prompt, kwargs, priority))
                self.kill_replica(handle.idx, exc, raise_if_empty=False,
                                  context={"during": "dispatch"})
                continue
            handle.dispatched += 1
            self._inflight[rid] = (idx, prompt, kwargs, priority)
            _DISPATCH.inc(labels=(self._policy_name, str(idx)))
            _trace.async_end("route", rid, {"replica": idx})

    # -- fleet tick ---------------------------------------------------------
    def _requeue_all(self, handle, instant, attrs):
        """Pull every inflight request off ``handle`` and hold it in the
        router for re-dispatch with the SAME rid — the exactly-once
        replay machinery (the streamed prefix is suppressed at the next
        dispatch). Shared by permanent death and breaker-open."""
        lost = [rid for rid, entry in self._inflight.items()
                if entry[0] == handle.idx]
        for rid in lost:
            _idx, prompt, kwargs, priority = self._inflight.pop(rid)
            self.requeues += 1
            _REQUEUES.inc()
            _trace.async_instant(instant, rid, attrs)
            _trace.async_begin("route", rid,
                               {"policy": self._policy_name,
                                "requeue": True})
            self._pending.append((rid, prompt, kwargs, priority))

    def _on_death(self, handle, exc):
        """Mark a replica dead and requeue everything it held. The
        engine's internal state is untrusted after an arbitrary failure;
        requests replay from their original prompts."""
        self.kill_replica(handle.idx, exc)

    def kill_replica(self, idx, exc, *, raise_if_empty=True, context=None):
        """Declare replica ``idx`` dead — from inside (a step() fault)
        or from outside (a supervisor's heartbeat-lease expiry or child
        exit detection, which passes ``context`` with the exit code and
        heartbeat age for the ``replica_death`` flight bundle).  Every
        request the replica held requeues with its original rid through
        the exactly-once replay machinery.  ``raise_if_empty=False``
        hands the no-survivors case to the caller: a supervisor
        RESPAWNS instead of dying."""
        handle = self.replicas[idx]
        if not handle.healthy:
            return
        handle.healthy = False
        handle.death_reason = repr(exc)
        _DEATHS.inc()
        ctx = {"replica": handle.idx, "exc": repr(exc),
               "healthy_replicas": sum(h.healthy for h in self.replicas)}
        if context:
            ctx.update(context)
        _flight.maybe_dump("replica_death", ctx)
        self._requeue_all(handle, "requeue", {"dead_replica": handle.idx})
        if raise_if_empty and not any(
                h.healthy and not h.retired for h in self.replicas):
            raise RuntimeError(
                "FleetRouter: every replica is dead "
                f"(last failure: {handle.death_reason})") from exc

    def add_replica(self, engine):
        """Grow the fleet live (supervisor respawn / autoscale-up): a
        fresh handle — and a fresh breaker when overload control is on —
        routable from the next dispatch.  Returns the new index."""
        idx = len(self.replicas)
        self.replicas.append(ReplicaHandle(idx, engine))
        if self._ov is not None:
            self._ov.add_breaker()
        return idx

    def reassign(self, rid, new_idx):
        """Point an inflight rid at a new replica (KV migration moved
        the live request).  The delivered-token suppression state stays:
        the stream continues on the peer, exactly once."""
        entry = self._inflight.get(rid)
        if entry is not None:
            self._inflight[rid] = (new_idx,) + entry[1:]

    def _on_breaker_open(self, handle):
        """The breaker opened: tear the replica's requests out of the
        (still-alive) engine — a later half-open tick must never
        double-serve a rid the survivors already replayed — and requeue
        them through the exactly-once replay machinery. A request the
        engine had ALREADY terminally cancelled inside the failing tick
        (e.g. its deadline expired before the fault) keeps that outcome
        instead of replaying: honoring it here also clears the
        engine-side record, so a later half-open drain can never
        double-terminate a rid the survivors are serving."""
        _flight.maybe_dump("breaker_open", {"replica": handle.idx})
        eng_cancelled = getattr(handle.engine, "cancelled", None)
        if eng_cancelled is None:     # NOT `or {}`: an EMPTY dict is
            eng_cancelled = {}        # falsy, and pops must reach the
                                      # engine's real dict
        wedged = None
        for rid, entry in list(self._inflight.items()):
            if entry[0] != handle.idx:
                continue
            try:
                cancelled_now = handle.engine.cancel(
                    rid, reason="breaker_requeue")
            except Exception as exc:  # noqa: BLE001
                # cancel() itself failing means the engine's HOST state
                # is untrusted: the rid still requeues, but the replica
                # must die (below) — a half-open probe on an engine
                # still holding this rid could double-serve it
                wedged = exc
                cancelled_now = False
            prior = eng_cancelled.pop(rid, None)
            _idx, prompt, kwargs, priority = self._inflight.pop(rid)
            if not cancelled_now and prior is not None:
                # the engine already reached a terminal cancel for this
                # rid in the failing tick — that outcome stands
                self.cancelled[rid] = prior
                self._delivered.pop(rid, None)
                self._ov.predictor.forget(rid)
                _trace.async_end("route", rid, {"cancelled": prior})
                continue
            self.requeues += 1
            _REQUEUES.inc()
            _trace.async_instant("breaker_requeue", rid,
                                 {"replica": handle.idx})
            _trace.async_begin("route", rid,
                               {"policy": self._policy_name,
                                "requeue": True})
            self._pending.append((rid, prompt, kwargs, priority))
        if wedged is not None:
            # every request is already safely requeued; the engine that
            # cannot even cancel is out of the fleet for good
            handle.healthy = False
            handle.death_reason = repr(wedged)
            _DEATHS.inc()
            _flight.maybe_dump("replica_death", {
                "replica": handle.idx, "exc": repr(wedged),
                "why": "cancel() failed during breaker requeue",
                "healthy_replicas": sum(h.healthy
                                        for h in self.replicas)})
            if not any(h.healthy for h in self.replicas):
                raise RuntimeError(
                    "FleetRouter: every replica is dead "
                    f"(last failure: {handle.death_reason})") from wedged

    def _on_step_error(self, handle, exc):
        """Classify a step() fault through the replica's breaker:
        transient faults tolerate/open (requeue + backoff), fatal faults
        keep the permanent-death path after ``max_consecutive_fatal``
        in a row."""
        kind = _overload.classify_step_exception(exc)
        action = self._ov.breakers[handle.idx].record_failure(kind)
        if action == "die":
            self._on_death(handle, exc)
        elif action == "open":
            self._on_breaker_open(handle)
        # "tolerate": the requests stay on the replica; next tick retries

    def _overload_tick(self):
        """Once per fleet tick: advance breakers, shed past the
        watermarks, and update the brownout ladder."""
        ov = self._ov
        for br in ov.breakers:
            br.poll()
        for entry, reason in ov.shed_targets(self):
            rid = entry[0]
            if self.shed_rescue is not None:
                try:
                    rescued = self.shed_rescue(entry, reason)
                except Exception:     # noqa: BLE001
                    rescued = False   # rescue is best-effort: shed
                if rescued:
                    self.rescued += 1
                    continue          # migrated to headroom, not shed
            try:
                self._pending.remove(entry)
            except ValueError:
                continue             # already gone (raced a cancel)
            self.shed[rid] = reason
            _overload.note_shed(reason)
            ov.predictor.forget(rid)
            self._delivered.pop(rid, None)
            _trace.async_end("route", rid, {"shed": reason})
        engines = [h.engine for h in self.replicas if h.healthy]
        ov.brownout.update(ov.pressure(self), engines)

    def step(self):
        """Dispatch pending work, tick every healthy replica, collect
        completions/cancellations, recover from replica faults (breaker
        or death). Returns {rid: full token ids} finishing this tick."""
        if self._ov is not None:
            self._overload_tick()
        self._dispatch_pending()
        done = {}
        for handle in self.replicas:
            if not handle.healthy or handle.retired:
                continue
            had_work = False
            if self._ov is not None:
                # open breaker: in backoff — the replica neither ticks
                # nor receives traffic until its half-open probe window
                br = self._ov.breakers[handle.idx]
                if br.poll() == "open":
                    continue
                if br.state == "half_open":
                    # a close needs REAL probe ticks (requests inflight),
                    # not idle no-op steps
                    had_work = self._replica_inflight(handle.idx) > 0
            t0 = time.perf_counter()
            try:
                out = handle.engine.step()
            except Exception as exc:  # noqa: BLE001
                if self._ov is None:   # pre-overload: any failure = death
                    self._on_death(handle, exc)
                else:
                    self._on_step_error(handle, exc)
                continue
            if self._ov is not None:
                self._ov.breakers[handle.idx].record_success(
                    probe_work=had_work)
            handle.busy_seconds += time.perf_counter() - t0
            handle.steps += 1
            for rid, ids in out.items():
                self._inflight.pop(rid, None)
                self._delivered.pop(rid, None)
                done[rid] = ids
            eng_cancelled = getattr(handle.engine, "cancelled", None)
            if eng_cancelled:
                for rid, reason in list(eng_cancelled.items()):
                    eng_cancelled.pop(rid)
                    self._inflight.pop(rid, None)
                    self._delivered.pop(rid, None)
                    self.cancelled[rid] = reason
                    if self._ov is not None:
                        self._ov.predictor.forget(rid)
        self.served += len(done)
        self._dispatch_pending()     # freed slots admit the next wave
        if _telemetry.get_registry().enabled:
            _PENDING.set(len(self._pending))
            _HEALTHY.set(sum(1 for h in self.replicas if h.healthy))
        return done

    def drained(self):
        if self._pending or self._inflight:
            return False
        return all(not h.healthy or h.retired or (
            h.engine.load()["queue_depth"] == 0
            and h.engine.load()["occupied_slots"] == 0)
            for h in self.replicas)

    def run_until_complete(self, max_ticks=100000):
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            if self.drained():
                return done
        raise TimeoutError("fleet did not drain")

    def load(self):
        """Aggregate fleet load (what a front-end LB would scrape)."""
        per = [dict(h.engine.load(), replica=h.idx, healthy=h.healthy,
                    dispatched=h.dispatched)
               for h in self.replicas]
        out = {"pending": len(self._pending),
               "inflight": len(self._inflight),
               "replicas": per}
        if self._ov is not None:
            out["overload"] = self._ov.summary()
        return out

    @property
    def overload(self):
        """The live OverloadController (None when PTPU_OVERLOAD=0 /
        overload=False keeps the pre-overload router)."""
        return self._ov

    def outcomes(self):
        """Terminal-outcome accounting over this router's lifetime:
        every submitted-and-admitted request ends in exactly one of
        served / cancelled / shed (rejected requests never minted a
        rid; the admission controller counts them separately)."""
        out = {"served": self.served,
               "cancelled": len(self.cancelled),
               "shed": len(self.shed),
               "pending": len(self._pending),
               "inflight": len(self._inflight)}
        if self._ov is not None:
            out["rejected"] = sum(self._ov.rejects.values())
        return out


def make_replicas(model_factory, n, rid_stride=RID_STRIDE, **engine_kw):
    """Build n engines with disjoint rid spaces. ``model_factory`` is
    called once per replica (each replica owns its weights in a real
    deployment; passing a shared model is fine for in-process tests)."""
    from ..serving import ContinuousBatchingEngine

    return [ContinuousBatchingEngine(model_factory(i),
                                     rid_base=i * rid_stride, **engine_kw)
            for i in range(n)]
