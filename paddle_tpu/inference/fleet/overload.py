"""Overload-safe fleet serving (docs/SERVING.md "Overload & degradation").

The PR 12 fleet is only safe under *polite* load: the router's sole
failure response is requeue-on-replica-death, any ``step()`` exception
permanently removes a replica, and an overloaded fleet grows unbounded
router queues until every request blows its deadline deep in the queue.
This module gives serving the inverse discipline the training side
already has (crash-safe checkpoints, the anomaly guard's
skip→rollback→abort ladder):

- **SLO-aware admission control** (:class:`AdmissionController` inside
  :class:`OverloadController`): predict TTFT for a would-be-admitted
  request from the live fleet load and the recently OBSERVED TTFTs, and
  reject with a structured :class:`Overloaded` terminal outcome (carrying
  ``retry_after``) instead of queueing it to certain death. Optional
  token-bucket rate limiting and priority classes (``interactive`` vs
  ``batch`` — batch hits every watermark first).
- **Load shedding** (:meth:`OverloadController.shed`): when router queue
  depth or predicted TTFT crosses a watermark, queued requests are shed
  — deadline-infeasible ones first (their SLO is already lost), then
  lowest-priority from the back of the queue — each with a counted,
  traced reason (``serving_shed_total{reason}``).
- **Per-replica circuit breakers** (:class:`CircuitBreaker`):
  ``step()`` exceptions are classified *transient* vs *fatal*
  (:func:`classify_step_exception`); transient faults tick an error-rate
  window that opens the breaker (exponential backoff + deterministic
  jitter), a half-open breaker admits one probe request and closes after
  consecutive clean steps, and requeue-on-open reuses the router's
  exactly-once replay machinery. Fatal faults keep the old
  mark-dead-forever behavior after ``max_consecutive_fatal`` in a row
  (default 1 == the pre-overload router).
- **Brownout degradation ladder** (:class:`BrownoutController`): under
  sustained pressure the fleet *reversibly* steps down — L1 caps
  ``max_new_tokens``, L2 pauses speculative drafting (output-invariant
  for greedy), L3 shrinks the per-tick prefill chunk budget
  (output-invariant) — and fully restores on recovery
  (``serving_brownout_level``).

``PTPU_OVERLOAD=0`` is the master escape hatch: the router keeps the
pre-overload code paths bitwise (any ``step()`` exception = permanent
death, no admission control, no shedding, no brownout).

All timing runs on an injectable ``clock`` so the soak harness can drive
admission, backoff, and brownout on its simulated-parallel clock
(``fleet.soak``) and tests can drive them deterministically.
"""
from __future__ import annotations

import dataclasses
import os
import time
from collections import deque

from ... import telemetry as _telemetry
from ...telemetry import flight as _flight

__all__ = [
    "Overloaded", "TransientReplicaError", "OverloadConfig",
    "OverloadController", "CircuitBreaker", "BrownoutController",
    "TtftPredictor", "TokenBucket", "classify_step_exception",
    "overload_enabled", "resolve_config", "PRIORITIES",
]

_OFF_SPELLINGS = ("0", "off", "false")


def note_shed(reason):
    """Count one shed request (the router calls this as it executes a
    shed decision — the decision and the count stay in lockstep)."""
    if _telemetry.get_registry().enabled:
        _SHED.inc(labels=(reason,))


def overload_enabled():
    """PTPU_OVERLOAD master hatch — same accepted off-spellings as the
    other escape hatches (PTPU_COMPOSED & co)."""
    return os.environ.get("PTPU_OVERLOAD", "").lower() not in _OFF_SPELLINGS


#: priority classes, best first — batch traffic hits every admission /
#: shed watermark before interactive traffic does
PRIORITIES = ("interactive", "batch")


_ADMISSION_REJECTS = _telemetry.counter(
    "serving_admission_rejects_total",
    "requests rejected at admission with a structured Overloaded outcome",
    labelnames=("reason", "priority"))
_SHED = _telemetry.counter(
    "serving_shed_total",
    "queued requests shed under overload, by reason",
    labelnames=("reason",))
_BREAKER_STATE = _telemetry.gauge(
    "serving_breaker_state",
    "per-replica circuit breaker state (0 closed, 1 half_open, 2 open)",
    labelnames=("replica",))
_BREAKER_TRANSITIONS = _telemetry.counter(
    "serving_breaker_transitions_total",
    "circuit breaker state transitions", labelnames=("replica", "to"))
_BREAKER_FAULTS = _telemetry.counter(
    "serving_breaker_faults_total",
    "replica step() faults seen by the breakers, by classification",
    labelnames=("kind",))
_BROWNOUT_LEVEL = _telemetry.gauge(
    "serving_brownout_level",
    "current brownout degradation level (0 = full service)")
_BROWNOUT_TRANSITIONS = _telemetry.counter(
    "serving_brownout_transitions_total",
    "brownout ladder transitions", labelnames=("direction",))
_PREDICTED_TTFT = _telemetry.gauge(
    "serving_predicted_ttft_seconds",
    "admission controller's newest TTFT prediction")


# ---------------------------------------------------------------------------
# Structured outcomes + fault taxonomy
# ---------------------------------------------------------------------------
class Overloaded(RuntimeError):
    """Terminal admission outcome: the request was NOT queued.

    ``retry_after`` is the controller's estimate of when capacity
    returns; ``reason`` is one of ``ttft_slo`` / ``queue_depth`` /
    ``rate_limit``; ``predicted_ttft`` carries the estimate that broke
    the SLO (None for depth/bucket rejects without data)."""

    def __init__(self, reason, retry_after, predicted_ttft=None,
                 priority="interactive"):
        self.reason = reason
        self.retry_after = float(retry_after)
        self.predicted_ttft = predicted_ttft
        self.priority = priority
        super().__init__(
            f"overloaded ({reason}): retry after {retry_after:.3f}s"
            + (f", predicted TTFT {predicted_ttft:.3f}s"
               if predicted_ttft is not None else ""))


class TransientReplicaError(RuntimeError):
    """A replica fault that is safe to retry: the step did not execute
    (or executed effect-free). The chaos harness raises these; real
    integrations should wrap runtime faults they know to be transient."""


#: exception types classified transient without message inspection.
#: OSError covers its whole subclass family (TimeoutError,
#: ConnectionError, BrokenPipeError, InterruptedError, ...) — ONE list,
#: so the taxonomy cannot silently diverge from a second check.
TRANSIENT_TYPES = (TransientReplicaError, OSError)

#: substrings marking a transient runtime fault (XLA/jax runtime errors
#: surface as RuntimeError with gRPC-style status markers; the fleet
#: transport's taxonomy — timeouts, severed links, heartbeat-lease
#: expiry — rides the same list for faults that arrive as re-hydrated
#: remote exceptions instead of live OSError subclasses)
TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED",
                     "UNAVAILABLE", "ABORTED", "preempt",
                     "severed", "heartbeat lease")


def classify_step_exception(exc):
    """``"transient"`` (retry through the breaker) or ``"fatal"``
    (the old mark-dead path after ``max_consecutive_fatal``). Unknown
    exceptions are FATAL: an arbitrary failure leaves the engine state
    untrusted, and the pre-overload semantics stay the default.

    The transport taxonomy lands here for free: TransportError and its
    subclasses (timeout, severed link) are ``ConnectionError`` /
    ``OSError`` descendants, so a dead or flapping replica process is
    transient — the breaker backs off and the requests replay
    exactly-once instead of the replica being marked dead on the first
    dropped frame."""
    if isinstance(exc, TRANSIENT_TYPES):
        return "transient"
    msg = str(exc)
    if any(m in msg for m in TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


# ---------------------------------------------------------------------------
# Structured outcomes on the wire
# ---------------------------------------------------------------------------
class RemoteReplicaError(RuntimeError):
    """An exception type the wire registry doesn't know, re-hydrated
    from a child process.  The original type name and message are
    preserved (``remote_type``), so marker-based classification still
    sees whatever the child saw."""

    def __init__(self, remote_type, message):
        self.remote_type = remote_type
        super().__init__(f"{remote_type}: {message}")


#: builtins allowed to re-hydrate by name from a child-process reply.
_WIRE_BUILTINS = {
    c.__name__: c for c in (
        ValueError, TypeError, KeyError, IndexError, RuntimeError,
        NotImplementedError, MemoryError, TimeoutError, OSError,
        ConnectionError, StopIteration,
    )
}


def outcome_to_wire(exc):
    """Serialize a structured terminal outcome (or any exception) for
    the RPC boundary.  ``Overloaded`` keeps its full structure — a
    child-process admission reject must reach the caller with
    ``retry_after`` / ``reason`` / ``predicted_ttft`` intact, not as a
    flattened string."""
    d = {"kind": type(exc).__name__, "message": str(exc)}
    if isinstance(exc, Overloaded):
        d.update(reason=exc.reason, retry_after=exc.retry_after,
                 predicted_ttft=exc.predicted_ttft, priority=exc.priority)
    elif isinstance(exc, RemoteReplicaError):
        d["kind"] = exc.remote_type          # don't double-wrap on relay
    return d


def outcome_from_wire(d):
    """Re-hydrate :func:`outcome_to_wire`.  Unknown types come back as
    :class:`RemoteReplicaError` carrying the original name + message
    (classification by marker still works; nothing is silently eaten)."""
    kind = d.get("kind", "RemoteReplicaError")
    msg = d.get("message", "")
    if kind == "Overloaded":
        return Overloaded(d.get("reason", "remote"),
                          d.get("retry_after", 0.0),
                          predicted_ttft=d.get("predicted_ttft"),
                          priority=d.get("priority", "interactive"))
    if kind == "TransientReplicaError":
        return TransientReplicaError(msg)
    cls = _WIRE_BUILTINS.get(kind)
    if cls is not None:
        try:
            return cls(msg)
        except Exception:       # exotic ctor signature -> generic wrap
            pass
    return RemoteReplicaError(kind, msg)


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class OverloadConfig:
    """Knobs for the overload machinery (docs/SERVING.md knob table).

    The defaults keep a polite fleet byte-identical in behavior: no
    admission SLO, no watermarks, no rate limit — only the breaker
    taxonomy is live, and ``max_consecutive_fatal=1`` keeps fatal
    faults on the pre-overload mark-dead path."""

    clock: object = time.perf_counter
    # -- admission ------------------------------------------------------
    ttft_slo: float | None = None     # reject when predicted TTFT > slo
    admit_depth: int | None = None    # reject when router pending >= this
    admit_depth_batch: int | None = None   # batch watermark (default /2)
    rate_limit: tuple | None = None   # (tokens_per_sec, burst)
    retry_after_min: float = 0.05
    # -- shedding -------------------------------------------------------
    shed_depth: int | None = None     # shed down to shed_low when crossed
    shed_low: int | None = None       # default shed_depth // 2
    shed_ttft_factor: float = 2.0     # shed when predicted > factor*slo
    # -- circuit breaker ------------------------------------------------
    breaker_window: int = 8           # step outcomes in the rate window
    breaker_threshold: int = 3        # failures in window -> open
    breaker_backoff: float = 0.5      # first open->half_open backoff (s)
    breaker_backoff_max: float = 30.0
    breaker_jitter: float = 0.1       # deterministic per-replica jitter
    breaker_close_after: int = 2      # clean half-open steps -> closed
    max_consecutive_fatal: int = 1    # old permanent-death behavior
    # -- brownout ladder ------------------------------------------------
    brownout_high: float = 1.0        # pressure ratio stepping DOWN
    brownout_low: float = 0.5         # pressure ratio stepping back UP
    brownout_up_ticks: int = 3        # sustained ticks before stepping
    brownout_down_ticks: int = 8      # calm ticks before restoring
    brownout_levels: int = 3
    brownout_max_new: int | None = None   # L1 cap (default max_new // 2)
    brownout_chunk: int | None = None     # L3 cap (default chunk // 2)
    # -- predictor ------------------------------------------------------
    predictor_window: int = 64


def resolve_config(overload):
    """Resolve a router's ``overload=`` argument: ``None`` builds the
    default config, ``False`` disables explicitly, a config passes
    through — and ``PTPU_OVERLOAD=0`` is the master off switch either
    way (the escape hatch must win over code-level configs so an A/B
    round never needs a code change)."""
    if not overload_enabled():
        return None
    if overload is None:
        return OverloadConfig()
    if overload is False:
        return None
    return overload


# ---------------------------------------------------------------------------
# TTFT prediction
# ---------------------------------------------------------------------------
class TtftPredictor:
    """Predict the TTFT a newly admitted request would see.

    ``base`` is the p50 of recently OBSERVED router-measured TTFTs (the
    live serving latency, including today's brownout level and breaker
    topology); the prediction scales it by the queue *waves* ahead of
    the request — every ``capacity`` waiting requests is one more
    service generation the newcomer waits through::

        predicted = base * (1 + waiting_ahead / capacity)

    With no observations yet (cold start) the predictor returns 0.0 and
    admission falls back to the depth watermark — a cold fleet must not
    reject its first requests on a guess."""

    def __init__(self, clock, window=64):
        self.clock = clock
        self._obs = deque(maxlen=int(window))
        self._submits = {}            # rid -> submit clock time

    def note_submit(self, rid):
        self._submits[rid] = self.clock()

    def note_first_token(self, rid):
        t0 = self._submits.pop(rid, None)
        if t0 is not None:
            self._obs.append(max(0.0, self.clock() - t0))

    def forget(self, rid):
        self._submits.pop(rid, None)

    def base(self):
        if not self._obs:
            return None
        vals = sorted(self._obs)
        return vals[len(vals) // 2]

    def predict(self, waiting_ahead, capacity):
        base = self.base()
        if base is None:
            return 0.0
        waves = waiting_ahead / max(1, capacity)
        return base * (1.0 + waves)


class TokenBucket:
    """Standard token bucket on the injected clock. ``take()`` returns
    0.0 on success or the wait (seconds) until a token is available."""

    def __init__(self, clock, rate, burst):
        self.clock = clock
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._t = None

    def take(self):
        now = self.clock()
        if self._t is None:
            self._t = now
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return 0.0
        return (1.0 - self._tokens) / max(self.rate, 1e-9)


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------
class CircuitBreaker:
    """Per-replica breaker: closed -> (error rate) -> open -> (backoff)
    -> half_open -> (clean steps) -> closed, with exponential backoff and
    deterministic per-replica jitter (reproducible runs; the fleet's
    half-open probes still decorrelate)."""

    def __init__(self, cfg, replica_idx, clock):
        self.cfg = cfg
        self.idx = int(replica_idx)
        self.clock = clock
        self.state = "closed"
        self._window = deque(maxlen=int(cfg.breaker_window))
        self._backoff = float(cfg.breaker_backoff)
        self.reopen_at = None
        self._probe_ok = 0
        self.consecutive_fatal = 0
        self.opens = 0                # flap count the overload gate bounds
        self.transitions = []         # (clock, to_state) for tests/report

    # -- transitions ----------------------------------------------------
    def _to(self, state):
        if state == self.state:
            return
        self.state = state
        self.transitions.append((self.clock(), state))
        if _telemetry.get_registry().enabled:
            lvl = {"closed": 0, "half_open": 1, "open": 2}[state]
            _BREAKER_STATE.set(lvl, labels=(str(self.idx),))
            _BREAKER_TRANSITIONS.inc(labels=(str(self.idx), state))

    def _open(self):
        self.opens += 1
        # deterministic jitter: a hash fraction of this replica's index
        # spreads reopen points without a live RNG (reproducible soaks)
        frac = ((self.idx * 2654435761) % 997) / 997.0
        delay = min(self._backoff * (1.0 + self.cfg.breaker_jitter * frac),
                    self.cfg.breaker_backoff_max)
        self.reopen_at = self.clock() + delay
        self._backoff = min(self._backoff * 2.0,
                            self.cfg.breaker_backoff_max)
        self._window.clear()
        self._to("open")

    def poll(self):
        """Open -> half_open once the backoff expires (one probe slot)."""
        if self.state == "open" and self.clock() >= self.reopen_at:
            self._probe_ok = 0
            self._to("half_open")
        return self.state

    # -- outcomes -------------------------------------------------------
    def record_success(self, probe_work=True):
        """``probe_work=False`` marks a clean step that processed no
        requests: while half-open, idle ticks must NOT count toward
        closing — a replica whose faults only manifest under load would
        otherwise close on an empty queue with zero real probes."""
        self.consecutive_fatal = 0
        if self.state == "half_open":
            if not probe_work:
                return
            self._probe_ok += 1
            if self._probe_ok >= self.cfg.breaker_close_after:
                self._backoff = float(self.cfg.breaker_backoff)
                self._window.clear()
                self._to("closed")
        else:
            self._window.append(1)

    def record_failure(self, kind):
        """-> action for the router: ``"die"`` (old permanent-death
        path), ``"open"`` (requeue this replica's work and back off), or
        ``"tolerate"`` (the requests stay put; retry next tick)."""
        if _telemetry.get_registry().enabled:
            _BREAKER_FAULTS.inc(labels=(kind,))
        if kind == "fatal":
            self.consecutive_fatal += 1
            if self.consecutive_fatal >= self.cfg.max_consecutive_fatal:
                return "die"
        else:
            self.consecutive_fatal = 0
        if self.state == "half_open":
            # a failed probe reopens with the (already doubled) backoff
            self._open()
            return "open"
        self._window.append(0)
        failures = sum(1 for v in self._window if not v)
        if failures >= self.cfg.breaker_threshold:
            self._open()
            return "open"
        return "tolerate"

    def routable(self, inflight):
        """May the dispatcher send a request here? Closed: yes.
        Half-open: one probe request at a time. Open: no."""
        if self.state == "closed":
            return True
        if self.state == "half_open":
            return inflight == 0
        return False

    def summary(self):
        return {"state": self.state, "opens": self.opens,
                "consecutive_fatal": self.consecutive_fatal}


# ---------------------------------------------------------------------------
# Brownout ladder
# ---------------------------------------------------------------------------
#: ladder semantics, documented order (docs/SERVING.md): each level adds
#: one reversible degradation on top of the previous ones
BROWNOUT_LADDER = (
    "L1: cap max_new_tokens",
    "L2: pause speculative drafting (greedy-output-invariant)",
    "L3: shrink the per-tick prefill chunk budget (output-invariant)",
)


class BrownoutController:
    """Reversible degradation under sustained pressure.

    ``update(pressure, engines)`` runs once per router tick with the
    fleet pressure ratio (1.0 == at the watermark). Hysteresis: the
    ladder steps DOWN one level after ``brownout_up_ticks`` consecutive
    ticks at/above ``brownout_high`` and steps back UP one level after
    ``brownout_down_ticks`` consecutive ticks at/below ``brownout_low``
    — and every knob it touched is restored exactly when its level
    disengages (greedy outputs after recovery are bitwise those of an
    unpressured run; tested)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.level = 0
        self.max_level = 0
        self.steps_down = 0
        self.steps_up = 0
        self._above = 0
        self._below = 0

    def _apply_engine(self, e, level):
        # disaggregated pairs degrade both halves
        if hasattr(e, "prefill") and hasattr(e, "decode"):
            self._apply_engine(e.prefill, level)
            self._apply_engine(e.decode, level)
            return
        if level >= 1:
            cap = self.cfg.brownout_max_new or max(
                1, getattr(e, "max_new_tokens", 2) // 2)
            e.max_new_cap = cap
        else:
            e.max_new_cap = None
        e.spec_paused = level >= 2
        if level >= 3 and getattr(e, "prefill_chunk", None):
            e.prefill_chunk_cap = (self.cfg.brownout_chunk
                                   or max(1, e.prefill_chunk // 2))
        else:
            e.prefill_chunk_cap = None

    def apply(self, engines):
        for e in engines:
            self._apply_engine(e, self.level)
        if _telemetry.get_registry().enabled:
            _BROWNOUT_LEVEL.set(self.level)

    def update(self, pressure, engines):
        changed = False
        direction = None
        if pressure >= self.cfg.brownout_high:
            self._above += 1
            self._below = 0
            if (self._above >= self.cfg.brownout_up_ticks
                    and self.level < self.cfg.brownout_levels):
                self.level += 1
                self.max_level = max(self.max_level, self.level)
                self.steps_down += 1
                self._above = 0
                changed = True
                direction = "down"
                if _telemetry.get_registry().enabled:
                    _BROWNOUT_TRANSITIONS.inc(labels=("down",))
        elif pressure <= self.cfg.brownout_low:
            self._below += 1
            self._above = 0
            if (self._below >= self.cfg.brownout_down_ticks
                    and self.level > 0):
                self.level -= 1
                self.steps_up += 1
                self._below = 0
                changed = True
                direction = "up"
                if _telemetry.get_registry().enabled:
                    _BROWNOUT_TRANSITIONS.inc(labels=("up",))
        else:
            self._above = 0
            self._below = 0
        if changed:
            self.apply(engines)
            _flight.note_event("brownout_step", {
                "direction": direction, "level": self.level,
                "pressure": round(float(pressure), 4)})
            if direction == "down":
                # stepping DOWN a level is load-shedding in anger: dump
                # a forensics bundle (flight's per-reason rate limit
                # keeps an oscillating ladder from spraying files)
                _flight.maybe_dump("brownout_step", {
                    "level": self.level,
                    "pressure": round(float(pressure), 4)})
        return self.level

    def summary(self):
        return {"level": self.level, "max_level": self.max_level,
                "steps_down": self.steps_down, "steps_up": self.steps_up,
                "restored": self.level == 0}


# ---------------------------------------------------------------------------
# The router-facing controller
# ---------------------------------------------------------------------------
class OverloadController:
    """One per FleetRouter: owns the predictor, rate bucket, per-replica
    breakers, the brownout ladder, and the admission / shedding
    decisions. The router calls in at submit (:meth:`admit`), per tick
    (:meth:`on_tick`), and per replica step outcome
    (:meth:`on_step_success` / :meth:`on_step_error`)."""

    def __init__(self, cfg, n_replicas):
        self.cfg = cfg
        self._clock_fn = cfg.clock
        clock = self.clock
        self.predictor = TtftPredictor(clock, cfg.predictor_window)
        self.bucket = (TokenBucket(clock, *cfg.rate_limit)
                       if cfg.rate_limit else None)
        self.breakers = [CircuitBreaker(cfg, i, clock)
                         for i in range(n_replicas)]
        self.brownout = BrownoutController(cfg)
        self.rejects = {}             # reason -> count
        self.last_predicted_ttft = None

    # the clock is one swappable cell so the soak harness can rebase
    # every component onto its simulated-parallel clock AFTER the
    # router (and therefore this controller) was built
    def clock(self):
        return self._clock_fn()

    def set_clock(self, fn):
        self._clock_fn = fn

    def add_breaker(self):
        """Grow the breaker list for a replica added live (supervisor
        respawn / autoscale-up).  Returns the new breaker's index."""
        idx = len(self.breakers)
        self.breakers.append(CircuitBreaker(self.cfg, idx, self.clock))
        return idx

    # -- admission ------------------------------------------------------
    def _reject(self, reason, retry_after, predicted, priority):
        self.rejects[reason] = self.rejects.get(reason, 0) + 1
        if _telemetry.get_registry().enabled:
            _ADMISSION_REJECTS.inc(labels=(reason, priority))
        raise Overloaded(reason, max(retry_after, self.cfg.retry_after_min),
                         predicted_ttft=predicted, priority=priority)

    def admit(self, router, priority):
        """Raise :class:`Overloaded` or return (admitted)."""
        cfg = self.cfg
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        if self.bucket is not None:
            wait = self.bucket.take()
            if wait > 0.0:
                self._reject("rate_limit", wait, None, priority)
        waiting, capacity = self._fleet_load(router)
        predicted = self.predictor.predict(waiting, capacity)
        self.last_predicted_ttft = predicted
        if _telemetry.get_registry().enabled:
            _PREDICTED_TTFT.set(predicted)
        if cfg.ttft_slo is not None and predicted > cfg.ttft_slo:
            self._reject("ttft_slo", predicted - cfg.ttft_slo,
                         predicted, priority)
        depth = len(router._pending)
        limit = cfg.admit_depth
        if priority == "batch":
            # an explicit batch watermark stands on its own (admit_depth
            # may be None); otherwise batch trips at half the shared one
            if cfg.admit_depth_batch is not None:
                limit = cfg.admit_depth_batch
            elif cfg.admit_depth is not None:
                limit = max(1, cfg.admit_depth // 2)
        if limit is not None and depth >= limit:
            base = self.predictor.base()
            retry = (predicted * 0.5 if base is not None
                     else cfg.retry_after_min)
            self._reject("queue_depth", retry, predicted or None, priority)

    def _fleet_load(self, router):
        """(waiting requests ahead, service capacity in slots) over the
        replicas a new request could actually land on."""
        waiting = len(router._pending)
        capacity = 0
        for h in router.replicas:
            if not h.healthy:
                continue
            br = self.breakers[h.idx]
            if br.state == "open":
                continue
            load = h.engine.load()
            waiting += load["queue_depth"] + load["occupied_slots"]
            capacity += h.engine.max_slots
        return waiting, capacity

    # -- shedding -------------------------------------------------------
    def shed_targets(self, router):
        """(entries to shed, reason by rid) from the router's pending
        queue. Deadline-infeasible entries shed first (the contract is
        already lost — shedding them is free); then, past the depth /
        predicted-TTFT watermark, lowest-priority entries from the BACK
        of the queue (least service progress lost) down to the low
        watermark."""
        cfg = self.cfg
        pending = router._pending
        if cfg.shed_depth is None and cfg.ttft_slo is None:
            return []                # shedding not configured
        if not pending:
            return []
        now = self.clock()
        base = self.predictor.base() or 0.0
        victims = []
        keep = []
        for entry in pending:
            at = entry[2].get("_deadline_at")
            if at is not None and at - now < base:
                victims.append((entry, "deadline_infeasible"))
            else:
                keep.append(entry)
        over_depth = (cfg.shed_depth is not None
                      and len(keep) > cfg.shed_depth)
        waiting, capacity = self._fleet_load(router)
        predicted = self.predictor.predict(waiting, capacity)
        over_ttft = (cfg.ttft_slo is not None and predicted
                     > cfg.shed_ttft_factor * cfg.ttft_slo)
        if over_depth or over_ttft:
            reason = "queue_depth" if over_depth else "predicted_ttft"
            low = (cfg.shed_low if cfg.shed_low is not None
                   else ((cfg.shed_depth or 0) // 2))

            def prio(entry):
                return entry[3] if len(entry) > 3 else "interactive"

            # ascending (priority rank, queue position): popping from
            # the END sheds youngest batch first, then older batch, then
            # youngest interactive — lowest priority, least progress lost
            order = sorted(range(len(keep)),
                           key=lambda i: (PRIORITIES.index(prio(keep[i])),
                                          i))
            n_alive = len(keep)
            while n_alive > max(low, 0) and order:
                i = order.pop()
                victims.append((keep[i], reason))
                n_alive -= 1
        return victims

    # -- per-tick -------------------------------------------------------
    def pressure(self, router):
        """Fleet pressure ratio for the brownout ladder: 1.0 == at the
        watermark. Uses the shed depth (or admit depth) and the TTFT
        SLO, whichever is more stressed."""
        cfg = self.cfg
        ratios = [0.0]
        depth_ref = cfg.shed_depth or cfg.admit_depth
        if depth_ref:
            ratios.append(len(router._pending) / float(depth_ref))
        if cfg.ttft_slo:
            waiting, capacity = self._fleet_load(router)
            ratios.append(self.predictor.predict(waiting, capacity)
                          / cfg.ttft_slo)
        return max(ratios)

    def summary(self):
        return {
            "rejects": dict(self.rejects),
            "breakers": [b.summary() for b in self.breakers],
            "breaker_opens": sum(b.opens for b in self.breakers),
            "brownout": self.brownout.summary(),
            "last_predicted_ttft": self.last_predicted_ttft,
        }
