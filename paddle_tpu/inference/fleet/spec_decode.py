"""Draft-model speculative decoding for the serving engine.

The engine decode tick is HBM-bound: one full weight pass produces ONE
token per sequence. Speculative decoding (docs/SERVING.md) spends a
small draft model's FLOPs to propose K tokens, then verifies all of
them in ONE target forward (`ContinuousBatchingEngine._spec_verify`) —
the target emits the longest draft prefix matching its OWN greedy
choices plus a bonus token, so each target weight pass yields 1..K+1
tokens at plain-decode quality.

Numerics contract: every emitted token is **bitwise identical** to what
plain greedy decode would have produced. The verify pass guarantees its
half by running the same per-position paged-attention kernel plain
decode runs (row-local projections batch without changing row values);
the draft only gates WHICH positions get accepted, never their values.
Temperature>0 requests fall back to the plain sampled tick.

The DraftRunner rides the TARGET's page tables: draft KV lives in its
own stacked cache `[Ld, Hkv_d, num_pages+1, page, D_d]` addressed by
the same page ids, so there is no second allocator — a page's position
means the same token index in both caches. Draft KV is (re)built at
target prefill completion and at decode-phase snapshot restores (disagg
handoffs / swap-ins); each spec tick re-primes position ``length-1``
before proposing, which both heals the one-token hole a fully-accepted
window leaves and is a bitwise no-op otherwise.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["DraftRunner"]


class DraftRunner:
    """Owns the draft model's packed weights, paged KV cache, and the
    jitted propose/prefill programs for one engine."""

    def __init__(self, engine, draft_model):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self.engine = engine
        cfg = draft_model.config
        if cfg.vocab_size != engine.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {cfg.vocab_size} != target vocab "
                f"{engine.cfg.vocab_size} — speculative decoding needs a "
                "shared tokenizer")
        self.cfg = cfg
        self.hd = cfg.hidden_size // cfg.num_heads
        self.hkv = cfg.num_kv_heads

        from ..serving import _pack_weights_stacked

        self._weights = _pack_weights_stacked(draft_model)
        dt = self._weights["embed"].dtype
        shape = (cfg.num_layers, self.hkv, engine.pool.num_pages + 1,
                 engine.page, self.hd)
        self.kc = jnp.zeros(shape, dt)
        self.vc = jnp.zeros(shape, dt)
        # one jitted program per window width C (2 for the re-prime
        # step, 1 for each subsequent draft) — both fixed-shape
        self._window_jit = jax.jit(self._window_step,
                                   donate_argnums=(4, 5))
        self.prefills = 0

    # -- compiled draft forward --------------------------------------------
    def _run_layers(self, x, layer_fn, kc, vc):
        """Layer walk over the DRAFT stack through the engine's shared
        :func:`serving._run_layer_stack` walker (one scan/unroll
        discipline for target and draft; cold start flat in draft depth
        too)."""
        from ..serving import _run_layer_stack

        return _run_layer_stack(self.engine._scan_layers,
                                self._weights["layers"], x, layer_fn,
                                kc, vc)

    def _layer_forward(self, lp, x, pos0, attend):
        """THE draft decoder-layer body: projections + rope +
        ``attend(q, k, v)`` (which owns cache writes and the attention
        math) + MLP — shared by the compiled window step and the eager
        prefill, so their numerics can never drift (drift between them
        is exactly what collapses speculative acceptance)."""
        jax, jnp = self._jax, self._jnp
        from ...models.gpt import _rms_pure

        ln1, wq, wk, wv, wo, ln2, wg, wu, wd = lp
        B, S = x.shape[:2]
        h = _rms_pure(x, ln1)
        q = (h @ wq).reshape(B, S, self.cfg.num_heads, self.hd)
        k = (h @ wk).reshape(B, S, self.hkv, self.hd)
        v = (h @ wv).reshape(B, S, self.hkv, self.hd)
        q, k = self.engine._rope(q, pos0), self.engine._rope(k, pos0)
        o = attend(q, k, v)                              # [B, S, Hq, D]
        x = x + o.reshape(B, S, -1).astype(x.dtype) @ wo
        h2 = _rms_pure(x, ln2)
        return x + (jax.nn.silu(h2 @ wg) * (h2 @ wu)) @ wd

    def _window_step(self, weights, toks, pos0, tables, kc, vc):
        """Draft forward over a C-token window at absolute positions
        pos0..pos0+C-1: writes draft KV for every window row, paged-
        attends per position, returns the greedy next token after the
        LAST position. C=1 is single-token decode; C=2 re-primes the
        previous position first (see module docstring)."""
        jnp = self._jnp
        from ...models.gpt import _rms_pure
        from ...ops.pallas.decode_attention import paged_attention

        eng = self.engine
        b, C = toks.shape
        x = weights["embed"][toks]                       # [B, C, H]
        pos = pos0[:, None] + jnp.arange(C)[None, :]
        page_idx = jnp.clip(pos // eng.page, 0, eng.pages_per_seq - 1)
        page_ids = jnp.take_along_axis(tables, page_idx, 1)
        offs = pos % eng.page

        def layer_fn(lp, x, kc_l, vc_l):
            new = {}

            def attend(q, k, v):
                kl = kc_l.at[:, page_ids, offs, :].set(
                    jnp.transpose(k, (2, 0, 1, 3)).astype(kc_l.dtype))
                vl = vc_l.at[:, page_ids, offs, :].set(
                    jnp.transpose(v, (2, 0, 1, 3)).astype(vc_l.dtype))
                new["k"], new["v"] = kl, vl
                return jnp.stack(
                    [paged_attention(q[:, i], kl, vl, tables,
                                     pos0 + i + 1) for i in range(C)],
                    1)                                   # [B, C, Hq, D]

            x = self._layer_forward(lp, x, pos0, attend)
            return x, new["k"], new["v"]

        x, kc, vc = self._run_layers(x, layer_fn, kc, vc)
        last = _rms_pure(x[:, -1], weights["fnorm"])     # [B, H]
        lg = (last @ weights["head"] if weights["head"] is not None
              else last @ weights["embed"].T)
        nxt = jnp.argmax(lg.astype(jnp.float32), -1).astype(jnp.int32)
        return nxt, kc, vc

    # -- engine-facing surface ---------------------------------------------
    def propose(self, prev, cur, lens, tables, K):
        """Greedily draft K tokens per row: one C=2 window step
        ([prev@len-1, cur@len] — the re-prime), then K-1 single-token
        steps. Returns np int32 [B, K]."""
        jnp = self._jnp
        d, self.kc, self.vc = self._window_jit(
            self._weights,
            jnp.asarray(np.stack([prev, cur], 1)), lens - 1, tables,
            self.kc, self.vc)
        drafts = [d]
        for j in range(1, K):
            d, self.kc, self.vc = self._window_jit(
                self._weights, drafts[-1][:, None], lens + j, tables,
                self.kc, self.vc)
            drafts.append(d)
        return np.stack([np.asarray(d) for d in drafts], 1)

    def prefill(self, reqs, tokens_list):
        """Write draft KV for whole token prefixes into the requests'
        pages as ONE padded batch — eager, mirroring the engine's group
        prefill op-for-op so a same-architecture draft's KV stays
        bitwise aligned with the target's (the acceptance-rate
        guarantee for self-drafting tests)."""
        jax, jnp = self._jax, self._jnp
        eng = self.engine
        w = self._weights
        B = len(reqs)
        lens = np.asarray([len(t) for t in tokens_list])
        S = int(lens.max())
        ids_np = np.zeros((B, S), np.int32)
        for i, t in enumerate(tokens_list):
            ids_np[i, : lens[i]] = t
        x = w["embed"][jnp.asarray(ids_np)]              # [B, S, H]
        pos0 = jnp.zeros((B,), jnp.int32)
        scale = 1.0 / math.sqrt(self.hd)
        rep = self.cfg.num_heads // self.hkv
        mask = jnp.tril(jnp.ones((S, S), bool))

        rows = np.concatenate([np.full(n, i) for i, n in enumerate(lens)])
        poss = np.concatenate([np.arange(n) for n in lens])
        tok_pages = np.concatenate(
            [np.asarray(r.pages, np.int64)[np.arange(n) // eng.page]
             for r, n in zip(reqs, lens)])
        offs = jnp.asarray(poss % eng.page)
        rows_j, poss_j = jnp.asarray(rows), jnp.asarray(poss)
        tok_pages = jnp.asarray(tok_pages)

        for li in range(self.cfg.num_layers):
            def attend(q, k, v, li=li):
                ck = jnp.repeat(k, rep, 2) if rep > 1 else k
                cv = jnp.repeat(v, rep, 2) if rep > 1 else v
                logits = jnp.einsum("bthd,bshd->bhts",
                                    (q * scale).astype(jnp.float32),
                                    ck.astype(jnp.float32))
                logits = jnp.where(mask[None, None], logits, -1e30)
                probs = jax.nn.softmax(logits, -1)
                o = jnp.einsum("bhts,bshd->bthd", probs,
                               cv.astype(jnp.float32)).astype(q.dtype)
                # scalar li + separated advanced indices: broadcast
                # dims move to the FRONT, so the payload is [N, Hkv, D]
                self.kc = self.kc.at[li, :, tok_pages, offs, :].set(
                    k[rows_j, poss_j].astype(self.kc.dtype))
                self.vc = self.vc.at[li, :, tok_pages, offs, :].set(
                    v[rows_j, poss_j].astype(self.vc.dtype))
                return o

            x = self._layer_forward(
                tuple(wl[li] for wl in w["layers"]), x, pos0, attend)
        self.prefills += B

    def catch_up(self, tokens, lens, tables):
        """Write the draft-KV row for a plain (fallback) tick's carry
        token at position ``lens``; the proposal is discarded. Keeps
        the draft cache continuous across sampled ticks."""
        _d, self.kc, self.vc = self._window_jit(
            self._weights, tokens[:, None], lens, tables,
            self.kc, self.vc)

    def warmup(self, tables):
        """Compile the window widths serving will actually use (C=2
        always; C=1 only when spec_tokens >= 2) on dummy operands —
        writes land in the engine's scratch page, and the compile time
        lands in the engine's gated cold-start number."""
        jnp = self._jnp
        b = self.engine.max_slots
        zeros = np.zeros((b,), np.int32)
        lens = jnp.ones((b,), jnp.int32)
        self.propose(zeros, zeros, lens, tables,
                     min(self.engine.spec_tokens, 2))
