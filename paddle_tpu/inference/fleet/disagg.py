"""Disaggregated prefill/decode serving.

Long prompts stall decode ticks: a chunked-prefill pass shares the tick
with decode, so every running request's inter-token latency absorbs the
prefill compute. Disaggregation (docs/SERVING.md) splits the work onto
two engines — in deployment, two meshes:

- the **prefill worker** (`prefill_only=True`) admits requests, runs
  chunked prefill, samples the first token, and owns the prefix cache
  (warm system prompts never leave it);
- the **decode worker** receives finished prefills over an explicit
  transfer seam and runs pure decode ticks (plus speculative decoding
  when a draft model is attached).

The seam is `ContinuousBatchingEngine.extract()` → `inject()`: the KV
pages + resume state move as a host snapshot (the swap-out machinery),
and the decode worker's swap-restore admission path scatters them into
its own pages. The transfer is bitwise — exact caches round-trip
unchanged through the host copy, int8 caches move raw codes+scales —
so greedy disaggregated output is IDENTICAL to the single-engine path
(asserted in tests/test_fleet.py). Each handoff is traced as a
per-request ``handoff`` mark and counted with its payload bytes.
"""
from __future__ import annotations

from ... import telemetry as _telemetry
from ...telemetry import trace as _trace
from ..serving import ContinuousBatchingEngine, _kv_nbytes

__all__ = ["DisaggregatedEngine"]

_HANDOFFS = _telemetry.counter(
    "serving_handoffs_total",
    "prefill->decode KV transfers (docs/SERVING.md)")
_HANDOFF_BYTES = _telemetry.counter(
    "serving_handoff_bytes_total",
    "KV snapshot bytes crossing the prefill->decode seam")


class DisaggregatedEngine:
    """Same surface as ContinuousBatchingEngine (submit/step/cancel/
    run_until_complete/load/prefix_match_pages), backed by a prefill
    worker + a decode worker; usable as a FleetRouter replica."""

    def __init__(self, model, prefill_slots=2, decode_slots=4,
                 page_size=64, max_seq_len=None, max_new_tokens=32,
                 eos_token_id=None, seed=0, prefill_chunk=32,
                 prefill_pages=None, decode_pages=None,
                 enable_prefix_cache=False, int8_kv=False,
                 draft_model=None, spec_tokens=4, rid_base=0):
        if prefill_chunk is None:
            raise ValueError("disaggregated prefill requires chunked "
                             "prefill (prefill_chunk=...)")
        # the prefill half: admissions + chunked prefill + prefix cache;
        # never decodes (prefill_only), so its pool only ever holds
        # prompt pages
        self.prefill = ContinuousBatchingEngine(
            model, max_slots=prefill_slots, page_size=page_size,
            num_pages=prefill_pages, max_seq_len=max_seq_len,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            seed=seed, prefill_chunk=prefill_chunk,
            enable_prefix_cache=enable_prefix_cache, int8_kv=int8_kv,
            prefill_only=True, rid_base=rid_base)
        # the decode half: restores handed-off snapshots and decodes;
        # keeps chunked prefill for preemption-recompute resumes
        self.decode = ContinuousBatchingEngine(
            model, max_slots=decode_slots, page_size=page_size,
            num_pages=decode_pages, max_seq_len=max_seq_len,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            seed=seed, prefill_chunk=prefill_chunk, int8_kv=int8_kv,
            draft_model=draft_model, spec_tokens=spec_tokens,
            rid_base=rid_base)
        if self.prefill.int8_kv != self.decode.int8_kv:
            raise RuntimeError("prefill/decode workers resolved different "
                               "KV modes — the handoff seam moves raw "
                               "pages and needs one format")
        self.max_slots = decode_slots      # router capacity signal
        self.handoffs = 0
        self.handoff_bytes = 0
        self._cancelled = {}

    # -- engine surface -----------------------------------------------------
    def submit(self, prompt_ids, **kwargs):
        # handed-off requests bypass the decode worker's submit()
        # validation — enforce its feasibility bounds here, or an
        # oversized request would head-of-line-block the decode queue
        # forever (its swap-restore admission can never allocate)
        total = len(prompt_ids) + self.decode.max_new_tokens
        if self.decode._draft is not None and (
                total + self.decode.spec_tokens > self.decode.max_seq):
            raise ValueError(
                f"request needs {total} tokens + "
                f"{self.decode.spec_tokens} spec headroom > "
                f"max_seq_len {self.decode.max_seq}")
        page = self.decode.page
        spec_pad = (self.decode.spec_tokens
                    if self.decode._draft is not None else 0)
        need = (total + spec_pad + page - 1) // page
        if need > self.decode.pool.num_pages:
            raise ValueError(
                f"request needs {need} pages > decode worker pool size "
                f"{self.decode.pool.num_pages}")
        return self.prefill.submit(prompt_ids, **kwargs)

    def cancel(self, rid, reason="user"):
        return (self.prefill.cancel(rid, reason=reason)
                or self.decode.cancel(rid, reason=reason))

    @property
    def cancelled(self):
        """PERSISTENT merged cancellation dict: the halves' dicts drain
        into it (the engines document theirs as drained-by-callers), so
        a FleetRouter popping entries here mutates real state instead
        of a per-call merged copy."""
        for src in (self.prefill.cancelled, self.decode.cancelled):
            while src:
                rid, reason = src.popitem()
                self._cancelled[rid] = reason
        return self._cancelled

    def prefix_match_pages(self, tokens):
        return self.prefill.prefix_match_pages(tokens)

    def load(self):
        """Router signal: queue depth spans BOTH halves (a request
        waiting anywhere delays first token); slots are the decode
        worker's (the throughput bound)."""
        p, d = self.prefill.load(), self.decode.load()
        return {
            "queue_depth": (p["queue_depth"] + p["occupied_slots"]
                            + d["queue_depth"]),
            "occupied_slots": d["occupied_slots"],
            "free_slots": d["free_slots"],
            "kv_free_fraction": min(p["kv_free_fraction"],
                                    d["kv_free_fraction"]),
        }

    def _handoff(self):
        """Move every finished prefill to the decode worker: extract
        (swap-out + release on the prefill side, prefix pages retained
        in its cache) → inject (decode-side swap-restore admission)."""
        eng = self.prefill
        for i, r in enumerate(list(eng._slots)):
            if (r is None or not r.generated
                    or r.prefill_pos < len(r.seq_tokens)):
                continue
            if eng._finished(r):
                # already complete (eos on the first token / max_new=1):
                # nothing to decode — leave it for the prefill worker's
                # own retire, whose result step() merges into the
                # returned completions
                continue
            req = eng.extract(i)
            size = (_kv_nbytes(req.swapped["k"])
                    + _kv_nbytes(req.swapped["v"]))
            self.handoffs += 1
            self.handoff_bytes += size
            _HANDOFFS.inc()
            _HANDOFF_BYTES.inc(size)
            _trace.async_instant(
                "handoff", req.rid,
                {"pages": req.swapped["n"], "bytes": size})
            self.decode.inject(req)

    def step(self):
        """One disaggregated tick: prefill tick → handoff sweep →
        decode tick. Completions come off the decode worker, PLUS any
        request the prefill worker retired itself (complete at first
        token, so it never crossed the seam)."""
        done = self.prefill.step()
        self._handoff()
        out = self.decode.step()
        out.update(done)
        return out

    def run_until_complete(self, max_ticks=10000):
        done = {}
        for _ in range(max_ticks):
            done.update(self.step())
            if (not self.prefill._waiting and not self.decode._waiting
                    and all(s is None for s in self.prefill._slots)
                    and all(s is None for s in self.decode._slots)):
                return done
        raise TimeoutError("disaggregated serving loop did not drain")

    def warmup(self, sample=False):
        b = self.prefill.warmup(sample=sample)
        b += self.decode.warmup(sample=sample)
        self.build_seconds = b
        return b
