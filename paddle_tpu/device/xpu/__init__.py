"""paddle.device.xpu surface — delegates to the accelerator runtime."""
from ...device import synchronize  # noqa: F401
from ..cuda import empty_cache  # noqa: F401

__all__ = ["synchronize", "empty_cache"]
