"""paddle.device.cuda (parity surface) — on the TPU build these APIs
address the ACCELERATOR (the reference's cuda namespace is its generic
'the accelerator' surface): streams/events/synchronize/memory stats
delegate to the device runtime over the TPU chip."""
from ...device import (  # noqa: F401
    Event,
    Stream,
    current_stream,
    stream_guard,
    synchronize,
)

__all__ = [
    "Stream", "Event", "current_stream", "synchronize", "device_count",
    "empty_cache", "max_memory_allocated", "max_memory_reserved",
    "memory_allocated", "memory_reserved", "stream_guard",
    "get_device_properties", "get_device_name", "get_device_capability",
    "reset_max_memory_allocated", "reset_max_memory_reserved",
]


def device_count():
    import jax

    return len(jax.devices())


def _stats(device=None):
    import jax

    d = jax.devices()[device or 0] if not hasattr(device, "platform") else device
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None):
    return int(_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    return int(_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    return int(_stats(device).get("bytes_reserved",
                                  _stats(device).get("bytes_limit", 0)))


def max_memory_reserved(device=None):
    return int(_stats(device).get("largest_alloc_size",
                                  max_memory_allocated(device)))


def reset_max_memory_allocated(device=None):
    pass  # XLA's allocator owns peak tracking; no reset hook


def reset_max_memory_reserved(device=None):
    pass


def empty_cache():
    import gc

    gc.collect()  # dropping refs releases XLA buffers


def get_device_properties(device=None):
    import jax

    d = jax.devices()[device or 0] if not hasattr(device, "platform") else device

    class _Props:
        name = d.device_kind
        total_memory = int(_stats(d).get("bytes_limit", 0))
        major, minor = 0, 0
        multi_processor_count = 1

    return _Props()


def get_device_name(device=None):
    import jax

    return jax.devices()[device or 0].device_kind


def get_device_capability(device=None):
    return (0, 0)  # CUDA compute capability has no TPU analogue
