"""Device / Place abstraction.

Parity target: the reference's ``phi::Place`` (``paddle/phi/common/place.h:31``)
and ``paddle.device`` python API.  On TPU there is a single accelerator type;
``TPUPlace`` is first-class (the reference survey calls for a new enum value),
``CPUPlace`` maps to the XLA CPU client, and CUDA aliases are accepted for
source compatibility but resolve to the default accelerator.
"""
from __future__ import annotations

import os
import threading

import jax


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self._device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.device_type, self._device_id))

    def is_tpu_place(self):
        return self.device_type == "tpu"

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_gpu_place(self):
        return False


class TPUPlace(Place):
    device_type = "tpu"


class CPUPlace(Place):
    device_type = "cpu"


class CUDAPlace(TPUPlace):
    """Source-compat alias: code written for GPU runs on the accelerator."""

    device_type = "tpu"


class CUDAPinnedPlace(CPUPlace):
    device_type = "cpu"


class XPUPlace(TPUPlace):
    device_type = "tpu"


class CustomPlace(TPUPlace):
    device_type = "tpu"

    def __init__(self, dev_type="tpu", device_id=0):
        super().__init__(device_id)


_state = threading.local()
_platform_cache = [None]


def _accelerator_platform():
    """The current jax platform name — WITHOUT initializing device backends.

    Querying jax.default_backend() creates the PJRT client (on real TPU pods
    that can block on the fabric); we answer from JAX_PLATFORMS when set and
    only fall back to a real (cached) backend query on explicit demand.
    """
    env = os.environ.get("JAX_PLATFORMS", "")
    if env:
        return env.split(",")[0].strip() or "cpu"
    if _platform_cache[0] is None:
        try:
            _platform_cache[0] = jax.default_backend()
        except RuntimeError:  # pragma: no cover
            _platform_cache[0] = "cpu"
    return _platform_cache[0]


def set_device(device: str):
    """paddle.device.set_device — accepts 'tpu', 'tpu:0', 'cpu', 'gpu:0'...

    GPU/XPU/custom names are treated as the accelerator for compatibility.
    """
    device = str(device)
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if name in ("cpu",):
        _state.place = CPUPlace(idx)
    else:
        _state.place = TPUPlace(idx)
    return get_device()


def get_device() -> str:
    p = _current_place()
    return f"{p.device_type}:{p.get_device_id()}"


def _current_place() -> Place:
    p = getattr(_state, "place", None)
    if p is None:
        plat = _accelerator_platform()
        p = CPUPlace(0) if plat == "cpu" else TPUPlace(0)
        _state.place = p
    return p


def jax_device_for(place: Place | None = None):
    """Map a Place to a concrete jax.Device, or None for "default device".

    Returning None lets callers skip jax.device_put entirely — arrays land on
    the default device lazily without forcing backend initialization.
    """
    if place is None:
        return None
    devs = jax.devices("cpu") if place.is_cpu_place() else jax.devices()
    return devs[place.get_device_id() % len(devs)]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def cuda_device_count() -> int:  # compat
    return 0


def get_all_device_type():
    return ["cpu", "tpu"]


def get_available_device():
    return [f"tpu:{i}" for i in range(device_count())]


# -- stream/event surface (parity: python/paddle/device) --------------------
# XLA owns scheduling on TPU; streams/events are API-compatible no-ops that
# preserve program semantics (synchronize flushes pending dispatch).

class Stream:
    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def set_stream(stream):
    global _current_stream
    _current_stream = stream
    return stream


import contextlib as _ctx


@_ctx.contextmanager
def stream_guard(stream):
    old = current_stream()
    set_stream(stream)
    try:
        yield
    finally:
        set_stream(old)


def synchronize(device=None):
    """Block until all dispatched work completes."""
    import jax

    (jax.device_put(0.0) + 0).block_until_ready()


def get_cudnn_version():
    return None


class IPUPlace:
    pass


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_distribute():
    return True


def is_compiled_with_custom_device(device_type=None):
    return False


def get_all_custom_device_type():
    return []


def get_available_custom_device():
    return []
