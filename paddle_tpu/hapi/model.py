"""High-level Model API (parity: python/paddle/hapi/model.py:1472 — fit :2200).

Training loops run through jit.TrainStep by default: one compiled XLA program
per step (forward+backward+update with donated buffers) — eager fallback via
``Model.prepare(..., use_jit=False)``.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from ..core.tensor import Tensor
from ..metric import Metric
from .callbacks import CallbackList, ProgBarLogger, ModelCheckpoint


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self._use_jit = True
        self._train_step = None
        self._step_mesh = None
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None, use_jit=True):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        self._metrics = list(self._metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError("metrics must be paddle_tpu.metric.Metric")
        self._use_jit = use_jit
        self._train_step = None
        self._step_mesh = None

    # ------------------------------------------------------------------
    @staticmethod
    def _active_mesh():
        from ..distributed.fleet import active_mesh

        return active_mesh()

    def _compute_loss(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        if callable(self._loss):
            loss = self._loss(*(list(outs) + list(labs)))
        else:
            raise RuntimeError("prepare() with a loss before training")
        if isinstance(loss, (list, tuple)):
            loss = sum(loss[1:], loss[0])
        if loss.size != 1:
            loss = loss.mean()
        return loss

    def _split_batch(self, data):
        if isinstance(data, (list, tuple)):
            data = list(data)
        else:
            data = [data]
        n_in = len(self._inputs) if self._inputs else 1
        inputs = data[:n_in]
        labels = data[n_in:]
        return inputs, labels

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is not None else []
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        if self._use_jit:
            # the mesh is part of the compiled step's identity: if
            # fleet.init (or a mesh teardown) happened after the step was
            # built, rebuild it — otherwise a later fit() would silently
            # train unsharded (or vice versa) on call-order accidents
            if (self._train_step is not None
                    and self._step_mesh is not self._active_mesh()):
                self._train_step = None
            if self._train_step is None:
                n_inputs = len(inputs)

                def step_fn(*batch):
                    ins, labs = batch[:n_inputs], batch[n_inputs:]
                    outputs = self.network(*ins)
                    return self._compute_loss(outputs, labs)

                # under an active fleet/auto-parallel mesh, Model.fit
                # scales with zero user code change: the whole step is
                # compiled over the mesh (batch sharded over dp, params
                # by their placements — reference: hapi Model under
                # fleet.distributed_model, hapi/model.py)
                mesh = self._active_mesh()
                if mesh is not None:
                    from ..distributed.parallel_step import ShardedTrainStep

                    self._train_step = ShardedTrainStep(
                        self.network, step_fn, self._optimizer, mesh)
                else:
                    from ..jit import TrainStep

                    self._train_step = TrainStep(self.network, step_fn,
                                                 self._optimizer)
                self._step_mesh = mesh
            loss = self._train_step(*(list(inputs) + list(labels)))
            metrics_out = self._eval_metrics_on_batch(inputs, labels) if self._metrics else []
            return [float(loss.item())] + metrics_out
        # eager path
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics_out = self._update_metrics(outputs, labels)
        return [float(loss.item())] + metrics_out

    def _eval_metrics_on_batch(self, inputs, labels):
        with paddle.no_grad():
            self.network.eval()
            outputs = self.network(*inputs)
            self.network.train()
        return self._update_metrics(outputs, labels)

    def _update_metrics(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        res = []
        for m in self._metrics:
            computed = m.compute(*(list(outs) + list(labels)))
            r = m.update(computed)
            res.append(r)
        return res

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is not None else []
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        with paddle.no_grad():
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels) if self._loss else None
        metrics_out = self._update_metrics(outputs, labels)
        out = [float(loss.item())] if loss is not None else []
        return out + metrics_out

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with paddle.no_grad():
            out = self.network(*inputs)
        return out

    # ------------------------------------------------------------------
    def fit(
        self,
        train_data=None,
        eval_data=None,
        batch_size=1,
        epochs=1,
        eval_freq=1,
        log_freq=10,
        save_dir=None,
        save_freq=1,
        verbose=2,
        drop_last=False,
        shuffle=True,
        num_workers=0,
        callbacks=None,
        accumulate_grad_batches=1,
        num_iters=None,
    ):
        from ..io import DataLoader, Dataset

        if isinstance(train_data, Dataset):
            train_loader = DataLoader(
                train_data, batch_size=batch_size, shuffle=shuffle,
                drop_last=drop_last, num_workers=num_workers,
            )
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size)
        else:
            eval_loader = eval_data

        cbks = CallbackList(callbacks, model=self, verbose=verbose,
                            metrics=self._metrics_names(), log_freq=log_freq,
                            save_dir=save_dir, save_freq=save_freq)
        cbks.on_train_begin()
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, data in enumerate(train_loader):
                if num_iters is not None and step >= num_iters:
                    break
                cbks.on_train_batch_begin(step)
                inputs, labels = self._split_batch(data)
                outs = self.train_batch(inputs, labels)
                logs = self._make_logs(outs)
                logs["step"] = step
                logs["batch_size"] = (
                    inputs[0].shape[0] if hasattr(inputs[0], "shape") else batch_size
                )
                cbks.on_train_batch_end(step, logs)
            if self._optimizer is not None and self._optimizer._lr_scheduler is not None:
                self._optimizer._lr_scheduler.step()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0, callbacks=None, num_iters=None):
        from ..io import DataLoader, Dataset

        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size)
        else:
            loader = eval_data
        for m in self._metrics:
            m.reset()
        logs = {}
        losses = []
        for step, data in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            inputs, labels = self._split_batch(data)
            outs = self.eval_batch(inputs, labels)
            if self._loss:
                losses.append(outs[0])
            logs = self._make_logs(outs)
        if losses:
            logs["loss"] = float(np.mean(losses))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset

        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size)
        else:
            loader = test_data
        outputs = []
        for data in loader:
            inputs, _ = self._split_batch(data)
            out = self.predict_batch(inputs)
            outputs.append(out)
        return outputs

    def _metrics_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _make_logs(self, outs):
        logs = {}
        names = self._metrics_names()
        i = 0
        if self._loss:
            logs["loss"] = outs[0]
            i = 1
        for m in self._metrics:
            r = m.accumulate()
            n = m.name()
            if isinstance(n, list):
                for nn, rr in zip(n, r if isinstance(r, list) else [r]):
                    logs[nn] = rr
            else:
                logs[n] = r
        return logs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        from .. import framework_io

        if training:
            framework_io.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                if self._train_step is not None:
                    self._train_step.sync_optimizer_state()
                framework_io.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from .. import jit

            # the Model's declared input specs drive the inference export
            # (reference: Model.save uses self._inputs for jit.save)
            jit.save(self.network, path,
                     input_spec=self._inputs or None)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework_io
        import os

        param_path = path + ".pdparams" if not path.endswith(".pdparams") else path
        self.network.set_state_dict(framework_io.load(param_path))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(framework_io.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary — layer table + param counts."""
    rows = []
    total_params = 0
    trainable_params = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = 0
        for p in layer.parameters(include_sublayers=False):
            n_params += p.size
            total_params += p.size
            if p.trainable:
                trainable_params += p.size
        rows.append((name or layer.__class__.__name__, layer.__class__.__name__, n_params))
    lines = ["-" * 64]
    lines.append(f"{'Layer (type)':<40}{'Params':>12}")
    lines.append("-" * 64)
    for name, cls, n in rows:
        lines.append(f"{name + ' (' + cls + ')':<40}{n:>12,}")
    lines.append("-" * 64)
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    print("\n".join(lines))
    return {"total_params": total_params, "trainable_params": trainable_params}
