"""hapi callbacks (parity: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._start = time.time()
        self._samples = 0

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        logs = logs or {}
        self._samples += logs.get("batch_size", 0)
        if self.verbose and step % self.log_freq == 0:
            items = []
            for k, v in logs.items():
                if k in ("step", "batch_size"):
                    continue
                if isinstance(v, numbers.Number):
                    items.append(f"{k}: {v:.4f}")
            elapsed = max(time.time() - self._start, 1e-9)
            ips = self._samples / elapsed
            print(f"Epoch {self.epoch} step {step}: " + ", ".join(items) + f" | {ips:.1f} samples/sec")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            logs = logs or {}
            items = [
                f"{k}: {v:.4f}" for k, v in logs.items()
                if isinstance(v, numbers.Number) and k not in ("step", "batch_size")
            ]
            print(f"Epoch {epoch} end: " + ", ".join(items))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            import os

            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            import os

            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1, min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.best = None
        self.wait = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        v = logs.get(self.monitor)
        if v is None:
            return
        improved = (
            self.best is None
            or (self.mode == "min" and v < self.best - self.min_delta)
            or (self.mode == "max" and v > self.best + self.min_delta)
        )
        if improved:
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self.model and self.model._optimizer:
            sched = self.model._optimizer._lr_scheduler
            if sched is not None:
                sched.step()


class CallbackList:
    def __init__(self, callbacks=None, model=None, **params):
        self.callbacks = list(callbacks or [])
        verbose = params.get("verbose", 2)
        if not any(isinstance(c, ProgBarLogger) for c in self.callbacks) and verbose:
            self.callbacks.insert(0, ProgBarLogger(params.get("log_freq", 10), verbose))
        if params.get("save_dir") and not any(isinstance(c, ModelCheckpoint) for c in self.callbacks):
            self.callbacks.append(ModelCheckpoint(params.get("save_freq", 1), params.get("save_dir")))
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_train_begin(self, logs=None):
        self._call("on_train_begin", logs)

    def on_train_end(self, logs=None):
        self._call("on_train_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_train_batch_begin(self, step, logs=None):
        self._call("on_train_batch_begin", step, logs)

    def on_train_batch_end(self, step, logs=None):
        self._call("on_train_batch_end", step, logs)
