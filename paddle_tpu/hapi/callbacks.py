"""hapi callbacks (parity: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._start = time.time()
        self._samples = 0

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        logs = logs or {}
        self._samples += logs.get("batch_size", 0)
        if self.verbose and step % self.log_freq == 0:
            items = []
            for k, v in logs.items():
                if k in ("step", "batch_size"):
                    continue
                if isinstance(v, numbers.Number):
                    items.append(f"{k}: {v:.4f}")
            elapsed = max(time.time() - self._start, 1e-9)
            ips = self._samples / elapsed
            print(f"Epoch {self.epoch} step {step}: " + ", ".join(items) + f" | {ips:.1f} samples/sec")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            logs = logs or {}
            items = [
                f"{k}: {v:.4f}" for k, v in logs.items()
                if isinstance(v, numbers.Number) and k not in ("step", "batch_size")
            ]
            print(f"Epoch {epoch} end: " + ", ".join(items))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            import os

            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            import os

            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1, min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.best = None
        self.wait = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        v = logs.get(self.monitor)
        if v is None:
            return
        improved = (
            self.best is None
            or (self.mode == "min" and v < self.best - self.min_delta)
            or (self.mode == "max" and v > self.best + self.min_delta)
        )
        if improved:
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self.model and self.model._optimizer:
            sched = self.model._optimizer._lr_scheduler
            if sched is not None:
                sched.step()


class CallbackList:
    def __init__(self, callbacks=None, model=None, **params):
        self.callbacks = list(callbacks or [])
        verbose = params.get("verbose", 2)
        if not any(isinstance(c, ProgBarLogger) for c in self.callbacks) and verbose:
            self.callbacks.insert(0, ProgBarLogger(params.get("log_freq", 10), verbose))
        if params.get("save_dir") and not any(isinstance(c, ModelCheckpoint) for c in self.callbacks):
            self.callbacks.append(ModelCheckpoint(params.get("save_freq", 1), params.get("save_dir")))
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_train_begin(self, logs=None):
        self._call("on_train_begin", logs)

    def on_train_end(self, logs=None):
        self._call("on_train_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_train_batch_begin(self, step, logs=None):
        self._call("on_train_batch_begin", step, logs)

    def on_train_batch_end(self, step, logs=None):
        self._call("on_train_batch_end", step, logs)


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer lr when a monitored metric stops improving
    (parity: hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            # same inference rule as EarlyStopping above: loss-like metrics
            # minimize, everything else (acc/f1/precision/auc...) maximizes
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.best = float("-inf") if mode == "max" else float("inf")
        self.wait = 0
        self.cooldown_counter = 0

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def _metric(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return v

    def _step(self, logs):
        cur = self._metric(logs)
        if cur is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(float(cur)):
            self.best = float(cur)
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    new_lr = max(float(opt.get_lr()) * self.factor,
                                 self.min_lr)
                    opt.set_lr(new_lr)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr -> {new_lr:.3e}")
                self.cooldown_counter = self.cooldown
                self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        # epoch-end only: eval metrics land in the epoch logs, and hooking
        # on_eval_end too would double-count an epoch against `patience`
        self._step(logs)


class VisualDL(Callback):
    """Scalar logger (parity: hapi VisualDL callback). The visualdl
    package is not in the TPU image, so scalars append to
    ``{log_dir}/scalars.jsonl`` — same data, greppable format."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        rec = {}
        for k, v in (logs or {}).items():
            if isinstance(v, (int, float)):
                rec[k] = v
            elif isinstance(v, (list, tuple)) and v and \
                    isinstance(v[0], (int, float)):
                rec[k] = v[0]
        # the cumulative counter orders records across epochs; a per-epoch
        # logs["step"] (last batch index) must not clobber it
        rec["step"] = self._step
        rec["tag"] = tag
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1

    def on_epoch_end(self, epoch, logs=None):
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class WandbCallback(Callback):
    """Weights & Biases logger. wandb is not installed in the TPU image;
    without it this callback raises at construction with guidance
    (matching the reference's hard dependency) unless ``anonymous_ok``."""

    def __init__(self, project=None, anonymous_ok=False, **kwargs):
        super().__init__()
        try:
            import wandb  # noqa: F401
        except ImportError:
            if not anonymous_ok:
                raise ImportError(
                    "WandbCallback requires the wandb package (not in the "
                    "TPU image); pass anonymous_ok=True to no-op, or use "
                    "the VisualDL callback's jsonl scalars")
            self._wandb = None
        else:
            import wandb

            self._wandb = wandb.init(project=project, **kwargs)

    def on_epoch_end(self, epoch, logs=None):
        if self._wandb is not None:
            self._wandb.log(dict(logs or {}, epoch=epoch))
