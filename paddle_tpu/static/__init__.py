"""paddle.static — compatibility surface.

The reference's Program/Executor machinery (SURVEY §3.5) is replaced by
jax.jit whole-graph compilation; this module keeps the commonly-used symbols
(InputSpec, name scopes, io helpers) so static-style code imports cleanly.
"""
from __future__ import annotations

import contextlib

import numpy as np


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), str(ndarray.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def save(layer, path, **kwargs):
    from .. import jit

    jit.save(layer, path, **kwargs)


def load(path, **kwargs):
    from .. import jit

    return jit.load(path, **kwargs)
