"""paddle.static — Program/Executor over tape capture.

Capability parity: the reference's static graph stack (SURVEY §3.5:
`Executor.run` base/executor.py:1693 -> StandaloneExecutor ->
PirInterpreter). TPU-native redesign: a `Program` is a recording of the
ops executed under ``program_guard`` (every framework op flows through
``core.dispatch.apply_op``, which appends replayable closures here — the
analogue of op-desc insertion into a Block). `Executor.run` replays the
recording with feeds substituted; when an optimizer registered via
``minimize`` the replay becomes a jitted train step (value_and_grad +
functional optimizer update), i.e. the whole Program compiles to one XLA
program exactly like the dygraph TrainStep path.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core.tensor import Parameter, Tensor

_PROGRAM_STACK = []


def _active_program():
    return _PROGRAM_STACK[-1] if _PROGRAM_STACK else None


class Program:
    """parity: base/framework.py Program (op recording + feeds)."""

    def __init__(self):
        self.feeds = {}        # name -> placeholder Tensor
        self.records = []      # (replay_fn, in_tensors, out_tensors)
        self._op_names = []    # op name per record (registry metadata key)
        self._minimize = None  # (optimizer, loss Tensor)
        self.random_seed = None

    # -- recording hooks (called from core.dispatch.apply_op) -------------
    def _record(self, replay_fn, in_tensors, out_tensors, op_name=None):
        self.records.append((replay_fn, list(in_tensors), list(out_tensors)))
        self._op_names.append(op_name or getattr(replay_fn, "__name__", "op"))

    def op_names(self):
        """Recorded op names in program order (framework.Program.ops)."""
        return list(self._op_names)

    def op_specs(self):
        """(name, OpSpec|None) per recorded op — the YAML metadata view."""
        from ..ops.registry import get_op_spec

        return [(n, get_op_spec(n)) for n in self._op_names]

    def trainable_params(self):
        seen, out = set(), []
        opt = self._minimize[0] if self._minimize else None
        allow = (None if opt is None or opt._parameter_list is None
                 else {id(p) for p in opt._parameter_list})
        for _, ins, _ in self.records:
            for t in ins:
                if (isinstance(t, Parameter) and t.trainable
                        and id(t) not in seen
                        and (allow is None or id(t) in allow)):
                    seen.add(id(t))
                    out.append(t)
        return out

    # -- Program surface ---------------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p.feeds = dict(self.feeds)
        p.records = list(self.records)
        p._op_names = list(self._op_names)
        if not for_test:
            p._minimize = self._minimize
        return p

    def list_vars(self):
        return list(self.feeds.values())

    @property
    def num_blocks(self):
        return 1

    def ir_module(self, fetch_list):
        """The program's IR form (N20 closure, r4): a pure traced
        function over (params, feeds) exposing jaxpr inspection,
        paddle.ir pass application, and StableHLO serialization — the
        capability triplet of `pir::Program` + PassManager +
        serialize_deserialize (reference: paddle/pir/include/core,
        fluid/pir/serialize_deserialize) on the jaxpr/StableHLO IR this
        framework standardises on."""
        return IrProgram(self, fetch_list)


class IrProgram:
    """IR view of a recorded static Program (see Program.ir_module)."""

    def __init__(self, program, fetch_list):
        from jax import tree_util

        self._feed_names = sorted(program.feeds.keys())
        feed_tensors = [program.feeds[n] for n in self._feed_names]
        params = program.trainable_params()
        self._params = params
        self._fetch_list = list(fetch_list)

        def pure(param_arrays, feed_arrays):
            env = {}
            for t, a in zip(feed_tensors, feed_arrays):
                env[id(t)] = a
            for t, a in zip(params, param_arrays):
                env[id(t)] = a
            for replay_fn, ins, outs in program.records:
                ins_a = [env.get(id(t), t._data) for t in ins]
                out = replay_fn(ins_a)
                for t, a in zip(outs, tree_util.tree_flatten(out)[0]):
                    env[id(t)] = a
            return [env.get(id(f), getattr(f, "_data", None))
                    for f in fetch_list]

        self._pure = pure
        self._jit = None

    def _args(self, feed):
        param_arrays = [p._data for p in self._params]
        feed_arrays = [Tensor(np.asarray(feed[n]))._data
                       for n in self._feed_names]
        return param_arrays, feed_arrays

    def jaxpr(self, feed):
        """ClosedJaxpr of the program over this feed signature — the
        inspectable SSA IR (pir::Program::Print analogue)."""
        import jax

        return jax.make_jaxpr(self._pure)(*self._args(feed))

    def apply(self, *patterns, dce=True):
        """Run paddle.ir rewrite patterns (+DCE) over the program — the
        PassManager slot. Returns self; subsequent run()/jaxpr()/
        serialize() see the rewritten program."""
        from ..ir import PatternRewriter

        rw = PatternRewriter(list(patterns), dce=dce)
        self._pure = rw.rewrite(self._pure)
        self._jit = None
        return self

    def run(self, feed, return_numpy=True):
        import jax

        if self._jit is None:
            self._jit = jax.jit(self._pure)
        outs = self._jit(*self._args(feed))
        if return_numpy:
            return [np.asarray(o) if o is not None else None for o in outs]
        return [Tensor(o) if o is not None else None for o in outs]

    def serialize(self, path, feed):
        """Portable artifact: StableHLO bytes (jax.export, weights
        embedded as constants) — loadable without the Python program."""
        import jax
        from jax import export as jax_export

        param_arrays, feed_arrays = self._args(feed)

        def with_weights(*feeds):
            return self._pure(param_arrays, list(feeds))

        exported = jax_export.export(jax.jit(with_weights))(
            *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in feed_arrays])
        with open(path, "wb") as f:
            f.write(exported.serialize())
        return path

    @staticmethod
    def deserialize(path):
        """Load a serialized program as a callable(feed_arrays...)."""
        from jax import export as jax_export

        with open(path, "rb") as f:
            exported = jax_export.deserialize(f.read())
        return exported.call


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _PROGRAM_STACK.append(main_program)
    try:
        yield
    finally:
        _PROGRAM_STACK.pop()


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder (parity: paddle.static.data)."""
    import jax.numpy as jnp

    from .. import dtypes as _dt

    concrete = [1 if (s is None or int(s) < 0) else int(s) for s in shape]
    t = Tensor(jnp.zeros(concrete, _dt.convert_dtype(dtype).np_dtype),
               stop_gradient=True, name=name)
    t._declared_shape = [None if (s is None or int(s) < 0) else int(s)
                         for s in shape]
    prog = _active_program() or _default_main
    prog.feeds[name] = t
    return t


class Executor:
    """parity: base/executor.py:1237 Executor."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        import jax
        from jax import tree_util

        from ..inference import Predictor as _Predictor

        if isinstance(program, _Predictor):
            # loaded inference model (load_inference_model contract)
            pred = program
            for name, arr in (feed or {}).items():
                h = pred.get_input_handle(name)
                h.copy_from_cpu(np.asarray(arr))
            pred.run()
            outs = [pred.get_output_handle(n).copy_to_cpu()
                    for n in (fetch_list or pred.get_output_names())]
            return outs if return_numpy else [Tensor(o) for o in outs]
        program = program if isinstance(program, Program) else (
            program or _default_main)
        feed = feed or {}
        fetch_list = fetch_list or []
        if not program.records:  # startup program: params already live
            return [None for _ in fetch_list]

        feed_names = sorted(program.feeds.keys() & feed.keys())
        feed_tensors = [program.feeds[n] for n in feed_names]
        params = program.trainable_params()

        def forward(param_arrays, feed_arrays):
            env = {}
            for t, a in zip(feed_tensors, feed_arrays):
                env[id(t)] = a
            for t, a in zip(params, param_arrays):
                env[id(t)] = a
            for replay_fn, ins, outs in program.records:
                ins_a = [env.get(id(t), t._data) for t in ins]
                out = replay_fn(ins_a)
                out_leaves = tree_util.tree_flatten(out)[0]
                for t, a in zip(outs, out_leaves):
                    env[id(t)] = a
            return env

        feed_arrays = [Tensor(np.asarray(feed[n]))._data for n in feed_names]
        param_arrays = [p._data for p in params]

        if program._minimize is not None:
            opt, loss_t = program._minimize

            def train_step(param_arrays, feed_arrays, lr, opt_state):
                def loss_of(pa):
                    env = forward(pa, feed_arrays)
                    return env[id(loss_t)], env

                (loss, env), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(param_arrays)
                named = {str(i): a for i, a in enumerate(param_arrays)}
                gnamed = {str(i): g for i, g in enumerate(grads)}
                new_named, new_state = opt.functional_update(
                    named, gnamed, opt_state, lr)
                new_params = [new_named[str(i)]
                              for i in range(len(param_arrays))]
                fetches = [env.get(id(f), getattr(f, "_data", None))
                           for f in fetch_list]
                return new_params, new_state, fetches

            if not hasattr(program, "_opt_state"):
                import jax.numpy as jnp

                named = {str(i): a for i, a in enumerate(param_arrays)}
                state = opt.functional_state(named)
                # seed from eager slots (set_state_dict resume path) —
                # same contract as jit.TrainStep._init_opt_state; COPY so
                # later donation/deletion can't reach the restored arrays
                for i, p in enumerate(params):
                    slots = opt._slots.get(id(p))
                    if slots:
                        st = dict(state[str(i)])
                        for k, v in slots.items():
                            if k in st:
                                st[k] = jnp.array(
                                    v._data if isinstance(v, Tensor) else v,
                                    copy=True)
                        state[str(i)] = st
                program._opt_state = state
                program._compiled = jax.jit(train_step)
            new_params, program._opt_state, fetches = program._compiled(
                param_arrays, feed_arrays, opt.get_lr(), program._opt_state)
            for p, a in zip(params, new_params):
                p._data = a
            opt._step_count += 1
        else:
            def eval_step(param_arrays, feed_arrays):
                env = forward(param_arrays, feed_arrays)
                return [env.get(id(f), getattr(f, "_data", None))
                        for f in fetch_list]

            if not hasattr(program, "_compiled_eval"):
                program._compiled_eval = jax.jit(eval_step)
            fetches = program._compiled_eval(param_arrays, feed_arrays)

        if return_numpy:
            return [np.asarray(f) if f is not None else None
                    for f in fetches]
        return [Tensor(f) if f is not None else None for f in fetches]

    def close(self):
        pass


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), str(ndarray.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def save(layer, path, **kwargs):
    from .. import jit

    jit.save(layer, path, **kwargs)


def load(path, **kwargs):
    from .. import jit

    return jit.load(path, **kwargs)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """parity: paddle.static.gradients — eager fallback via autograd."""
    from .. import autograd

    return autograd.grad(targets, inputs, grad_outputs=target_gradients,
                         retain_graph=True)


def cpu_places(device_count=None):
    return ["cpu"]


def device_guard(device=None):
    return contextlib.nullcontext()


# ---------------------------------------------------------------------------
# deployment surface (save/load_inference_model over the jit.save artifact)
# ---------------------------------------------------------------------------
def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """parity: static.save_inference_model — emits the SAME StableHLO
    artifact jit.save writes, from a recorded static Program.

    feed_vars/fetch_vars: the `static.data` placeholders and program
    outputs; `program` defaults to the default main program."""
    import numpy as np

    import jax

    from ..jit import _pack_weights, _ARTIFACT_VERSION
    from jax import export as jax_export
    import json
    import os as _os

    program = program or default_main_program()
    feed_list = list(feed_vars) if isinstance(
        feed_vars, (list, tuple)) else [feed_vars]
    fetch_list = list(fetch_vars) if isinstance(
        fetch_vars, (list, tuple)) else [fetch_vars]

    # persistables = recorded input Tensors that are neither feeds nor
    # produced by an earlier record (intermediate activations are program
    # values, not weights)
    feed_ids = {id(v) for v in feed_list}
    produced = {id(o) for _, _, outs in program.records for o in outs}
    names, weights, seen = [], [], set()
    for _, ins, _ in program.records:
        for t in ins:
            if id(t) in feed_ids or id(t) in seen or id(t) in produced:
                continue
            seen.add(id(t))
            names.append(getattr(t, "name", None) or f"param_{len(names)}")
            weights.append(t._data)

    def pure(ws, *feeds):
        sub = dict(zip((id(t) for t in seen_list), ws))
        env = {}
        for v, f in zip(feed_list, feeds):
            env[id(v)] = f
        for (replay, ins, outs) in program.records:
            args = [env.get(id(t), sub.get(id(t), t._data)) for t in ins]
            res = replay(args)
            import jax.tree_util as tu

            leaves = [x for x in tu.tree_leaves(res)]
            for o, leaf in zip(outs, leaves):
                env[id(o)] = leaf
        return tuple(env[id(v)] for v in fetch_list)

    seen_list = [t for _, ins, _ in program.records for t in ins
                 if id(t) in seen]
    # dedupe preserving order
    uniq, ul = set(), []
    for t in seen_list:
        if id(t) not in uniq:
            uniq.add(id(t)); ul.append(t)
    seen_list = ul

    # declared None/-1 dims export as symbolic so the artifact serves any
    # size on those axes (same contract as jit.save + InputSpec). One
    # SHARED scope, and the dim NAME is shared by axis index across feeds
    # ("_dyn0" = every feed's dynamic axis 0): multiple feeds with a
    # dynamic batch axis combine (add/concat/matmul) because the export
    # knows the sizes are equal — the dominant shared-batch contract.
    from jax import export as _jx

    scope = _jx.SymbolicScope()
    avals = []
    for v in feed_list:
        decl = getattr(v, "_declared_shape", None) or list(v.shape)
        if any(d is None for d in decl):
            names = [f"_dyn{ax}" if d is None else str(int(d))
                     for ax, d in enumerate(decl)]
            sym = _jx.symbolic_shape(", ".join(names), scope=scope)
            avals.append(jax.ShapeDtypeStruct(tuple(sym), v._data.dtype))
        else:
            avals.append(jax.ShapeDtypeStruct(tuple(decl), v._data.dtype))
    exported = jax_export.export(jax.jit(pure))(
        [w for w in weights], *avals)

    _os.makedirs(_os.path.dirname(_os.path.abspath(path_prefix)) or ".",
                 exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    packed, params_meta = _pack_weights(weights, names)
    with open(path_prefix + ".pdiparams", "wb") as f:
        np.savez(f, **packed)
    meta = {
        "version": _ARTIFACT_VERSION,
        "params": params_meta,
        "inputs": [{"shape": [
            -1 if d is None else int(d)
            for d in (getattr(v, "_declared_shape", None) or v.shape)],
            "dtype": str(v._data.dtype)} for v in feed_list],
        "input_names": [getattr(v, "name", f"feed_{i}")
                        for i, v in enumerate(feed_list)],
        "outputs": {"kind": "tuple", "items": [
            {"kind": "leaf", "index": i} for i in range(len(fetch_list))]},
    }
    with open(path_prefix + ".pdmeta.json", "w") as f:
        json.dump(meta, f)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """parity: static.load_inference_model -> (program-like predictor,
    feed_names, fetch_names). The returned object runs via
    Executor.run(loaded, feed=..., fetch_list=...) or directly."""
    from ..inference import Config, Predictor

    pred = Predictor(Config(path_prefix))
    return pred, pred.get_input_names(), pred.get_output_names()


# -- program/persistable (de)serialization over the artifact bytes ---------
_SERIALIZE_MEMO = {}


def _serialize_artifact(feed_vars, fetch_vars, program):
    import tempfile

    program = program or default_main_program()
    fv = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    ov = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    key = (id(program), len(program.records),
           tuple(id(v) for v in fv), tuple(id(v) for v in ov))
    if key in _SERIALIZE_MEMO:   # serialize_program + serialize_persistables
        return _SERIALIZE_MEMO[key]  # back-to-back export only once
    with tempfile.TemporaryDirectory() as d:
        p = save_inference_model(d + "/m", feed_vars, fetch_vars,
                                 program=program)
        with open(p + ".pdmodel", "rb") as f:
            model = f.read()
        with open(p + ".pdiparams", "rb") as f:
            params = f.read()
    _SERIALIZE_MEMO.clear()
    _SERIALIZE_MEMO[key] = (model, params)
    return model, params


def serialize_program(feed_vars, fetch_vars, program=None):
    return _serialize_artifact(feed_vars, fetch_vars, program)[0]


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None):
    return _serialize_artifact(feed_vars, fetch_vars, program)[1]


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    from jax import export as jax_export

    return jax_export.deserialize(bytearray(data))


def deserialize_persistables(program, data, executor=None):
    """name -> typed ndarray (decoded via the self-describing npz keys
    _pack_weights embeds)."""
    import io as _io

    import numpy as np

    z = np.load(_io.BytesIO(data), allow_pickle=False)
    out = {}
    i = 0
    while f"w{i}" in z.files:
        name = str(z[f"w{i}_name"]) if f"w{i}_name" in z.files else f"w{i}"
        dtype = str(z[f"w{i}_dtype"]) if f"w{i}_dtype" in z.files else "float32"
        shape = (z[f"w{i}_shape"].tolist()
                 if f"w{i}_shape" in z.files else [-1])
        import ml_dtypes  # noqa: F401

        out[name] = np.frombuffer(
            z[f"w{i}"].tobytes(), np.dtype(dtype)).reshape(shape)
        i += 1
    # reference contract: restoring persistables takes effect on the
    # program (callers often discard the return value)
    if program is not None:
        set_program_state(program, out)
    return out


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program


# -- program state ----------------------------------------------------------
def load_program_state(model_path, var_list=None):
    import numpy as np

    from ..jit import load_artifact

    _, weights, meta = load_artifact(model_path)
    return {pm["name"]: np.asarray(w)
            for pm, w in zip(meta["params"], weights)}


def set_program_state(program, state_dict):
    for _, ins, _ in program.records:
        for t in ins:
            n = getattr(t, "name", None)
            if n in state_dict:
                import jax.numpy as jnp

                t._data = jnp.asarray(state_dict[n])


# -- small compat -----------------------------------------------------------
Variable = Tensor  # static-graph name for a framework tensor


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import numpy as np

    import paddle_tpu as paddle

    t = paddle.to_tensor(np.full(shape, value, dtype))
    t.name = name or f"global_var_{id(t)}"
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import numpy as np

    p = Parameter.__new__(Parameter)
    import jax.numpy as jnp

    if default_initializer is not None:
        import paddle_tpu as paddle

        t = paddle.empty(shape, dtype)
        default_initializer(t)
        arr = t._data
    else:
        arr = jnp.zeros(shape, dtype)
    Parameter.__init__(p, arr, trainable=True)
    p.name = name or f"create_param_{id(p)}"
    return p


def global_scope():
    return {"_scope": "global"}


@contextlib.contextmanager
def scope_guard(scope):
    yield


class BuildStrategy:
    """Compilation knobs record (XLA decides; kept for API parity)."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self._program, item)


class ExponentialMovingAverage:
    """EMA over trainable parameters (parity: static.ExponentialMovingAverage).

    update() folds current param values into the shadow; apply() swaps the
    shadow in (context manager restores on exit)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._thres_steps = thres_steps
        self._shadow = {}
        self._backup = {}
        self._step = 0

    def update(self, parameters=None):
        params = parameters or [
            p for p in default_main_program().trainable_params()
            if p.trainable]
        if not params:
            raise ValueError(
                "ExponentialMovingAverage.update(): no parameters — pass "
                "them explicitly (eager mode) or record a program with "
                "trainable Parameters first")
        self._step += 1
        # warm-up schedule only when thres_steps is requested (reference:
        # flat decay otherwise)
        d = (min(self._decay, (1 + self._step) / (10 + self._step))
             if self._thres_steps is not None else self._decay)
        for p in params:
            key = id(p)
            prev = self._shadow.get(key)
            self._shadow[key] = (
                p._data if prev is None else d * prev + (1 - d) * p._data)
            self._shadow.setdefault("_ref_%d" % key, p)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        refs = [(k, self._shadow[k]) for k in self._shadow
                if isinstance(k, int)]
        for key, shadow in refs:
            p = self._shadow["_ref_%d" % key]
            self._backup[key] = p._data
            p._data = shadow
        try:
            yield
        finally:
            if need_restore:
                for key, _ in refs:
                    p = self._shadow["_ref_%d" % key]
                    p._data = self._backup.pop(key)

    def restore(self, executor=None):
        for key, arr in list(self._backup.items()):
            self._shadow["_ref_%d" % key]._data = arr
            del self._backup[key]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """parity: static.py_func — host-python op inside the program via
    jax.pure_callback (the TPU path for arbitrary python)."""
    import jax
    import numpy as np

    from ..core.dispatch import apply_op

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    avals = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
             for o in outs]

    def _cb(*arrays):
        res = func(*[np.asarray(a) for a in arrays])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r) for r in res)

    def _fwd_call(*arrays):
        res = jax.pure_callback(_cb, tuple(avals), *arrays)
        return res if len(res) > 1 else res[0]

    if backward_func is None:
        def _run(*arrays):
            # non-differentiable host op (reference: no backward_func)
            return jax.tree_util.tree_map(
                jax.lax.stop_gradient, _fwd_call(*arrays))
    else:
        in_avals = [jax.ShapeDtypeStruct(tuple(np.shape(a._data)),
                                         a._data.dtype) for a in xs]

        @jax.custom_vjp
        def _run(*arrays):
            return _fwd_call(*arrays)

        def _vjp_fwd(*arrays):
            return _fwd_call(*arrays), arrays

        def _vjp_bwd(res_arrays, g):
            def _bcb(*args):
                n = len(res_arrays)
                grads = backward_func(*[np.asarray(a) for a in args])
                grads = grads if isinstance(grads, (list, tuple)) else [grads]
                return tuple(np.asarray(x) for x in grads)

            gl = g if isinstance(g, (list, tuple)) else (g,)
            return tuple(jax.pure_callback(
                _bcb, tuple(in_avals), *res_arrays, *gl))

        _run.defvjp(_vjp_fwd, _vjp_bwd)

    return apply_op(_run, *xs, _op_name="py_func")


def Print(input, first_n=-1, message=None, summarize=20, **kwargs):
    """parity: static.Print — debug-print a tensor inside the program."""
    import jax

    from ..core.dispatch import apply_op

    def _p(a):
        msg = (message or "Print").replace("{", "{{").replace("}", "}}")
        jax.debug.print(msg + ": {}", a)
        return a

    return apply_op(_p, input, _op_name="print")


class WeightNormParamAttr:
    """parity: static.WeightNormParamAttr — carried to nn.utils.weight_norm."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=200, topk=1,
        slide_steps=1):
    import numpy as np

    from ..metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(np.asarray(input.numpy()), np.asarray(label.numpy()))
    import paddle_tpu as paddle

    return paddle.to_tensor(np.asarray(m.accumulate(), np.float32))


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metrics (parity: static.ctr_metric_bundle): returns (auc,
    batch_auc) tensors over the batch."""
    a = auc(input, label)
    return a, a


def cuda_places(device_ids=None):
    return ["tpu"]  # accelerator places; the mesh addresses real chips


def xpu_places(device_ids=None):
    return ["tpu"]


# -- IPU compat (other-vendor accelerator surface; n/a on TPU) --------------
@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    yield


def set_ipu_shard(call_func, index=-1, stage=-1):
    return call_func


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError(
            "IPU is another vendor's accelerator; on TPU use "
            "fleet.DistributedStrategy / auto-parallel Strategy")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "IPU is another vendor's accelerator; programs compile via XLA")


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """parity: static.append_backward — record grads for the recorded
    program's parameters; returns [(param, grad)] like the reference."""
    from .. import autograd

    params = parameter_list or [
        t for _, ins, _ in default_main_program().records for t in ins
        if isinstance(t, Parameter)]
    # dedupe preserving order
    seen, uniq = set(), []
    for p in params:
        if id(p) not in seen:
            seen.add(id(p)); uniq.append(p)
    grads = autograd.grad(loss, uniq, retain_graph=True, allow_unused=True)
    return list(zip(uniq, grads))


from . import nn  # noqa: F401,E402
