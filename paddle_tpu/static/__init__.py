"""paddle.static — Program/Executor over tape capture.

Capability parity: the reference's static graph stack (SURVEY §3.5:
`Executor.run` base/executor.py:1693 -> StandaloneExecutor ->
PirInterpreter). TPU-native redesign: a `Program` is a recording of the
ops executed under ``program_guard`` (every framework op flows through
``core.dispatch.apply_op``, which appends replayable closures here — the
analogue of op-desc insertion into a Block). `Executor.run` replays the
recording with feeds substituted; when an optimizer registered via
``minimize`` the replay becomes a jitted train step (value_and_grad +
functional optimizer update), i.e. the whole Program compiles to one XLA
program exactly like the dygraph TrainStep path.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core.tensor import Parameter, Tensor

_PROGRAM_STACK = []


def _active_program():
    return _PROGRAM_STACK[-1] if _PROGRAM_STACK else None


class Program:
    """parity: base/framework.py Program (op recording + feeds)."""

    def __init__(self):
        self.feeds = {}        # name -> placeholder Tensor
        self.records = []      # (replay_fn, in_tensors, out_tensors)
        self._op_names = []    # op name per record (registry metadata key)
        self._minimize = None  # (optimizer, loss Tensor)
        self.random_seed = None

    # -- recording hooks (called from core.dispatch.apply_op) -------------
    def _record(self, replay_fn, in_tensors, out_tensors, op_name=None):
        self.records.append((replay_fn, list(in_tensors), list(out_tensors)))
        self._op_names.append(op_name or getattr(replay_fn, "__name__", "op"))

    def op_names(self):
        """Recorded op names in program order (framework.Program.ops)."""
        return list(self._op_names)

    def op_specs(self):
        """(name, OpSpec|None) per recorded op — the YAML metadata view."""
        from ..ops.registry import get_op_spec

        return [(n, get_op_spec(n)) for n in self._op_names]

    def trainable_params(self):
        seen, out = set(), []
        opt = self._minimize[0] if self._minimize else None
        allow = (None if opt is None or opt._parameter_list is None
                 else {id(p) for p in opt._parameter_list})
        for _, ins, _ in self.records:
            for t in ins:
                if (isinstance(t, Parameter) and t.trainable
                        and id(t) not in seen
                        and (allow is None or id(t) in allow)):
                    seen.add(id(t))
                    out.append(t)
        return out

    # -- Program surface ---------------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p.feeds = dict(self.feeds)
        p.records = list(self.records)
        p._op_names = list(self._op_names)
        if not for_test:
            p._minimize = self._minimize
        return p

    def list_vars(self):
        return list(self.feeds.values())

    @property
    def num_blocks(self):
        return 1


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _PROGRAM_STACK.append(main_program)
    try:
        yield
    finally:
        _PROGRAM_STACK.pop()


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder (parity: paddle.static.data)."""
    import jax.numpy as jnp

    from .. import dtypes as _dt

    concrete = [1 if (s is None or int(s) < 0) else int(s) for s in shape]
    t = Tensor(jnp.zeros(concrete, _dt.convert_dtype(dtype).np_dtype),
               stop_gradient=True, name=name)
    prog = _active_program() or _default_main
    prog.feeds[name] = t
    return t


class Executor:
    """parity: base/executor.py:1237 Executor."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        import jax
        from jax import tree_util

        program = program if isinstance(program, Program) else (
            program or _default_main)
        feed = feed or {}
        fetch_list = fetch_list or []
        if not program.records:  # startup program: params already live
            return [None for _ in fetch_list]

        feed_names = sorted(program.feeds.keys() & feed.keys())
        feed_tensors = [program.feeds[n] for n in feed_names]
        params = program.trainable_params()

        def forward(param_arrays, feed_arrays):
            env = {}
            for t, a in zip(feed_tensors, feed_arrays):
                env[id(t)] = a
            for t, a in zip(params, param_arrays):
                env[id(t)] = a
            for replay_fn, ins, outs in program.records:
                ins_a = [env.get(id(t), t._data) for t in ins]
                out = replay_fn(ins_a)
                out_leaves = tree_util.tree_flatten(out)[0]
                for t, a in zip(outs, out_leaves):
                    env[id(t)] = a
            return env

        feed_arrays = [Tensor(np.asarray(feed[n]))._data for n in feed_names]
        param_arrays = [p._data for p in params]

        if program._minimize is not None:
            opt, loss_t = program._minimize

            def train_step(param_arrays, feed_arrays, lr, opt_state):
                def loss_of(pa):
                    env = forward(pa, feed_arrays)
                    return env[id(loss_t)], env

                (loss, env), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(param_arrays)
                named = {str(i): a for i, a in enumerate(param_arrays)}
                gnamed = {str(i): g for i, g in enumerate(grads)}
                new_named, new_state = opt.functional_update(
                    named, gnamed, opt_state, lr)
                new_params = [new_named[str(i)]
                              for i in range(len(param_arrays))]
                fetches = [env.get(id(f), getattr(f, "_data", None))
                           for f in fetch_list]
                return new_params, new_state, fetches

            if not hasattr(program, "_opt_state"):
                named = {str(i): a for i, a in enumerate(param_arrays)}
                program._opt_state = opt.functional_state(named)
                program._compiled = jax.jit(train_step)
            new_params, program._opt_state, fetches = program._compiled(
                param_arrays, feed_arrays, opt.get_lr(), program._opt_state)
            for p, a in zip(params, new_params):
                p._data = a
            opt._step_count += 1
        else:
            def eval_step(param_arrays, feed_arrays):
                env = forward(param_arrays, feed_arrays)
                return [env.get(id(f), getattr(f, "_data", None))
                        for f in fetch_list]

            if not hasattr(program, "_compiled_eval"):
                program._compiled_eval = jax.jit(eval_step)
            fetches = program._compiled_eval(param_arrays, feed_arrays)

        if return_numpy:
            return [np.asarray(f) if f is not None else None
                    for f in fetches]
        return [Tensor(f) if f is not None else None for f in fetches]

    def close(self):
        pass


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), str(ndarray.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def save(layer, path, **kwargs):
    from .. import jit

    jit.save(layer, path, **kwargs)


def load(path, **kwargs):
    from .. import jit

    return jit.load(path, **kwargs)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """parity: paddle.static.gradients — eager fallback via autograd."""
    from .. import autograd

    return autograd.grad(targets, inputs, grad_outputs=target_gradients,
                         retain_graph=True)


def cpu_places(device_count=None):
    return ["cpu"]


def device_guard(device=None):
    return contextlib.nullcontext()
