"""paddle.static.nn (parity: python/paddle/static/nn) — static-graph
layer builders and control flow.

TPU-native: builders create Parameters and run the SAME functional ops
the dygraph layers use (every call records into the active Program via
apply_op); control flow (`cond`, `while_loop`, `case`, `switch_case`)
lowers to `lax.cond`/`lax.while_loop` so data-dependent branching stays
compiled instead of breaking the graph.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Parameter, Tensor
from . import create_parameter, py_func  # noqa: F401

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case",
    "cond", "static_pylayer", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_expand",
]


def _param(shape, dtype="float32", init=None):
    p = create_parameter(shape, dtype, default_initializer=init)
    return p


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


# -- layer builders ---------------------------------------------------------
def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """static.nn.fc — flatten trailing dims, affine, optional activation."""
    import paddle_tpu.nn.functional as F

    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = _param([in_dim, size], str(np.asarray(x.numpy()).dtype))
    b = None if bias_attr is False else _param([size])
    flat = x.reshape(list(x.shape[:num_flatten_dims]) + [in_dim])
    out = F.linear(flat, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    import paddle_tpu.nn.functional as F

    w = _param(list(size), dtype)
    return F.embedding(input, w, padding_idx=padding_idx, sparse=is_sparse)


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     dtype="float32", **kwargs):
    """PS-backed large embedding: pulls rows from the fleet sparse table
    when PS mode is active, dense embedding otherwise."""
    from ..distributed.fleet import _ps_state

    if _ps_state.get("client") is not None:
        from ..distributed.ps import sparse_embedding_lookup

        client = _ps_state["client"]
        client.create_sparse_table("sparse_embedding", dim=int(size[-1]))
        return sparse_embedding_lookup(client, "sparse_embedding",
                                       np.asarray(input.numpy()),
                                       int(size[-1]))
    return embedding(input, size, padding_idx=padding_idx, dtype=dtype)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, **kwargs):
    import paddle_tpu.nn.functional as F

    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale, bias = _param([c]), _param([c])
    scale._data = jnp.ones([c], jnp.float32)
    mean = Tensor(jnp.zeros([c], jnp.float32))
    var = Tensor(jnp.ones([c], jnp.float32))
    out = F.batch_norm(input, mean, var, weight=scale, bias=bias,
                       training=not is_test, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None):
    import paddle_tpu.nn.functional as F

    shape = list(input.shape[begin_norm_axis:])
    w = _param(shape) if scale else None
    if w is not None:
        w._data = jnp.ones(shape, jnp.float32)
    b = _param(shape) if shift else None
    out = F.layer_norm(input, shape, weight=w, bias=b, epsilon=epsilon)
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW"):
    import paddle_tpu.nn.functional as F

    c = input.shape[1]
    w, b = _param([c]), _param([c])
    w._data = jnp.ones([c], jnp.float32)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None):
    import paddle_tpu.nn.functional as F

    c = input.shape[1]
    w, b = _param([c]), _param([c])
    w._data = jnp.ones([c], jnp.float32)
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, **kwargs):
    """Normalization by accumulated batch statistics (CTR models)."""
    mean = input.mean(axis=0, keepdim=True)
    std = ((input - mean) ** 2).mean(axis=0, keepdim=True)
    out = (input - mean) / (std + epsilon).sqrt()
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def _conv(input, num_filters, filter_size, stride, padding, dilation,
          groups, nd, transpose=False):
    import paddle_tpu.nn.functional as F

    c_in = input.shape[1]
    ks = ([filter_size] * nd if isinstance(filter_size, int)
          else list(filter_size))
    if transpose:
        w = _param([c_in, num_filters // (groups or 1)] + ks)
        fn = F.conv2d_transpose if nd == 2 else F.conv3d_transpose
    else:
        w = _param([num_filters, c_in // (groups or 1)] + ks)
        fn = F.conv2d if nd == 2 else F.conv3d
    return fn(input, w, bias=None, stride=stride, padding=padding,
              dilation=dilation, groups=groups or 1)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           act=None, **kwargs):
    out = _conv(input, num_filters, filter_size, stride, padding,
                dilation, groups, nd=2)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, **kwargs):
    return _conv(input, num_filters, filter_size, stride, padding,
                 dilation, groups, nd=3)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=3,
                     stride=1, padding=0, dilation=1, groups=None, **kw):
    return _conv(input, num_filters, filter_size, stride, padding,
                 dilation, groups, nd=2, transpose=True)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=3,
                     stride=1, padding=0, dilation=1, groups=None, **kw):
    return _conv(input, num_filters, filter_size, stride, padding,
                 dilation, groups, nd=3, transpose=True)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=None, deformable_groups=1,
                  **kwargs):
    from ..vision.ops import deform_conv2d as _dc

    c_in = input.shape[1]
    ks = ([filter_size] * 2 if isinstance(filter_size, int)
          else list(filter_size))
    w = _param([num_filters, c_in // (groups or 1)] + ks)
    return _dc(input, offset, w, mask=mask, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups or 1)


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    import paddle_tpu.nn.functional as F

    w = _param([size, x.shape[-1], y.shape[-1]])
    b = _param([size])
    out = F.bilinear(x, y, w, b)
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    import paddle_tpu.nn.functional as F

    n = (1 if mode == "all"
         else x.shape[1] if mode == "channel" else int(np.prod(x.shape[1:])))
    alpha = _param([n])
    alpha._data = jnp.full([n], 0.25, jnp.float32)
    if mode == "element":
        alpha._data = alpha._data.reshape([1] + list(x.shape[1:]))
    return F.prelu(x, alpha)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Functional spectral norm of a weight tensor."""
    def _sn(w):
        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1).astype(
            jnp.float32)
        u = jnp.ones((mat.shape[0],), jnp.float32) / np.sqrt(mat.shape[0])
        v = None
        for _ in range(max(power_iters, 1)):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ mat @ v
        return (w / sigma.astype(w.dtype))

    return apply_op(_sn, weight, _op_name="spectral_norm")


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        **kwargs):
    """Noise-contrastive estimation loss with uniform negative sampling."""
    import paddle_tpu as paddle

    dim = input.shape[-1]
    w = _param([num_total_classes, dim])
    b = _param([num_total_classes])

    def _nce(h, y, wv, bv):
        n = h.shape[0]
        key = jax.random.PRNGKey(0)
        neg = jax.random.randint(key, (n, num_neg_samples), 0,
                                 num_total_classes)
        pos_logit = jnp.sum(h * wv[y.reshape(-1)], -1) + bv[y.reshape(-1)]
        neg_logit = jnp.einsum("nd,nkd->nk", h, wv[neg]) + bv[neg]
        pos_loss = jax.nn.softplus(-pos_logit)
        neg_loss = jax.nn.softplus(neg_logit).sum(-1)
        return (pos_loss + neg_loss).reshape(n, 1)

    return apply_op(_nce, input, label, w, b, _op_name="nce")


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (`row_conv`): out[t] = sum_{i<=k} w[i]*x[t+i]."""
    import paddle_tpu.nn.functional as F

    d = input.shape[-1]
    w = _param([future_context_size + 1, d])

    def _rc(x, wv):
        k = wv.shape[0]
        pads = [(0, 0)] * x.ndim
        pads[1] = (0, k - 1)
        xp = jnp.pad(x, pads)
        out = sum(xp[:, i:i + x.shape[1]] * wv[i] for i in range(k))
        return out.astype(x.dtype)

    out = apply_op(_rc, input, w, _op_name="row_conv")
    if act:
        out = getattr(F, act)(out)
    return out


# -- sequence ops (padded batches; the lod-free TPU form) -------------------
def sequence_softmax(input, axis=1, **kwargs):
    import paddle_tpu.nn.functional as F

    return F.softmax(input, axis=axis)


def sequence_pool(input, pool_type="sum", **kwargs):
    pool_type = pool_type.lower()
    if pool_type in ("sum",):
        return input.sum(axis=1)
    if pool_type in ("average", "avg", "mean"):
        return input.mean(axis=1)
    if pool_type == "max":
        return input.max(axis=1)
    if pool_type == "sqrt":
        return input.sum(axis=1) / float(np.sqrt(input.shape[1]))
    if pool_type == "first":
        return sequence_first_step(input)
    if pool_type == "last":
        return sequence_last_step(input)
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(input):
    return input[:, 0]


def sequence_last_step(input):
    return input[:, -1]


def sequence_expand(x, y, ref_level=-1):
    def _se(xa, ya):
        rep = ya.shape[1] // max(xa.shape[1], 1)
        return jnp.repeat(xa, rep, axis=1)

    return apply_op(_se, x, y, _op_name="sequence_expand")


def sequence_conv(input, num_filters, filter_size=3, padding=True,
                  param_attr=None, bias_attr=None, act=None, **kwargs):
    import paddle_tpu.nn.functional as F

    d = input.shape[-1]
    w = _param([filter_size * d, num_filters])

    def _sc(x, wv):
        k = filter_size
        half = (k - 1) // 2
        pads = [(0, 0)] * x.ndim
        pads[1] = (half, k - 1 - half)
        xp = jnp.pad(x, pads)
        windows = jnp.concatenate(
            [xp[:, i:i + x.shape[1]] for i in range(k)], axis=-1)
        return windows @ wv

    out = apply_op(_sc, input, w, _op_name="sequence_conv")
    if act:
        out = getattr(F, act)(out)
    return out


# -- control flow (lax-lowered: stays compiled) -----------------------------
def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """lax.cond over Tensor-returning branches (static_nn/control_flow)."""
    def _cond(p):
        return jax.lax.cond(
            jnp.asarray(p).reshape(()).astype(bool),
            lambda: _unwrap_tree(true_fn()),
            lambda: _unwrap_tree(false_fn()),
        )

    return apply_op(_cond, pred, _op_name="cond")


def _unwrap_tree(out):
    from jax import tree_util

    return tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, out,
        is_leaf=lambda x: isinstance(x, Tensor))


def case(pred_fn_pairs, default=None, name=None):
    """First true predicate wins (reference static.nn.case)."""
    def build(i):
        if i == len(pred_fn_pairs):
            if default is None:
                return lambda: _unwrap_tree(pred_fn_pairs[-1][1]())
            return lambda: _unwrap_tree(default())
        p, fn = pred_fn_pairs[i]
        nxt = build(i + 1)
        return lambda: jax.lax.cond(
            jnp.asarray(_unwrap(p)).reshape(()).astype(bool),
            lambda: _unwrap_tree(fn()), nxt)

    def _case():
        return build(0)()

    return apply_op(_case, _op_name="case")


def switch_case(branch_index, branch_fns, default=None, name=None):
    def _sw(idx):
        fns = branch_fns
        if isinstance(fns, dict):
            keys = sorted(fns)
            ordered = [fns[k] for k in keys]
            # map arbitrary integer keys onto dense positions
            pos = sum(jnp.where(jnp.asarray(idx) == k, i, 0)
                      for i, k in enumerate(keys))
            branches = [(lambda f=f: _unwrap_tree(f())) for f in ordered]
            if default is not None:
                branches.append(lambda: _unwrap_tree(default()))
                known = sum((jnp.asarray(idx) == k).astype(jnp.int32)
                            for k in keys)
                pos = jnp.where(known > 0, pos, len(ordered))
            return jax.lax.switch(pos, branches)
        branches = [(lambda f=f: _unwrap_tree(f())) for f in fns]
        return jax.lax.switch(jnp.clip(jnp.asarray(idx), 0,
                                       len(branches) - 1), branches)

    return apply_op(_sw, branch_index, _op_name="switch_case")


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """lax.while_loop over Tensor loop state."""
    def _wl(*state):
        def c(s):
            out = cond(*[Tensor(a) for a in s])
            return jnp.asarray(_unwrap(out)).reshape(()).astype(bool)

        def b(s):
            out = body(*[Tensor(a) for a in s])
            out = out if isinstance(out, (list, tuple)) else [out]
            return tuple(_unwrap(o) for o in out)

        return jax.lax.while_loop(c, b, tuple(state))

    return apply_op(_wl, *loop_vars, _op_name="while_loop")


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """Custom fwd/bwd region in a static program (static_pylayer op)."""
    if backward_fn is None:
        out = forward_fn(*inputs)
        return out

    xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]

    @jax.custom_vjp
    def _run(*arrays):
        out = forward_fn(*[Tensor(a) for a in arrays])
        return _unwrap_tree(out)

    def _fwd(*arrays):
        return _run(*arrays), arrays

    def _bwd(res, g):
        gl = g if isinstance(g, (list, tuple)) else (g,)
        grads = backward_fn(*[Tensor(a) for a in gl])
        grads = grads if isinstance(grads, (list, tuple)) else [grads]
        return tuple(_unwrap(x) for x in grads)

    _run.defvjp(_fwd, _bwd)
    return apply_op(_run, *xs, _op_name="static_pylayer")
