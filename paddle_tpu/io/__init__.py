"""paddle.io — datasets, samplers, DataLoader.

Parity: python/paddle/io (DataLoader at io/reader.py:262, workers at
io/dataloader/worker.py).  The loader runs a background prefetch thread that
collates numpy batches and stages them to device ahead of consumption —
the TPU-appropriate equivalent of the reference's shared-memory worker pool
(host→HBM transfer overlaps compute; heavy decode work can still use
num_workers threads).
"""
from __future__ import annotations

import itertools
import math
import queue as _queue
import threading

import numpy as np

from ..core.tensor import Tensor
from .. import framework


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        counts = [int(math.floor(n * f)) for f in lengths]
        counts[-1] += n - sum(counts)
        lengths = counts
    n = sum(lengths)
    perm = np.random.permutation(n).tolist()
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset : offset + l]))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Sample randomly from a fixed subset of indices (io parity)."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        import numpy as np

        order = np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in order])

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(
            len(self.weights), self.num_samples, replace=self.replacement, p=p
        )
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (parity: io/dataloader/batch_sampler.py)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
            self.epoch += 1
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return Tensor(_to_jax(np.stack(batch)))
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s._data for s in batch]))
    if isinstance(sample, (int, np.integer)):
        return Tensor(_to_jax(np.asarray(batch, dtype=np.int64)))
    if isinstance(sample, (float, np.floating)):
        return Tensor(_to_jax(np.asarray(batch, dtype=np.float32)))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(fields)) for fields in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _to_jax(arr):
    import jax

    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return jax.device_put(arr)


class _DataLoaderIter:
    def __init__(self, loader):
        self.loader = loader
        self._iter = self._make_gen()
        if loader.prefetch_factor > 0:
            self._q = _queue.Queue(maxsize=loader.prefetch_factor)
            self._done = object()
            self._thread = threading.Thread(target=self._producer, daemon=True)
            self._thread.start()
        else:
            self._q = None

    def _make_gen(self):
        loader = self.loader
        collate = loader.collate_fn or default_collate_fn
        ds = loader.dataset
        if isinstance(ds, IterableDataset):
            def gen():
                batch = []
                for sample in ds:
                    batch.append(sample)
                    if len(batch) == loader.batch_size:
                        yield collate(batch)
                        batch = []
                if batch and not loader.drop_last:
                    yield collate(batch)

            return gen()

        def gen():
            for idx_batch in loader.batch_sampler:
                samples = [ds[i] for i in idx_batch]
                yield collate(samples)

        return gen()

    def _producer(self):
        try:
            for item in self._iter:
                self._q.put(item)
        finally:
            self._q.put(self._done)

    def __next__(self):
        if self._q is None:
            return next(self._iter)
        item = self._q.get()
        if item is self._done:
            raise StopIteration
        return item

    def __iter__(self):
        return self


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn
        self.drop_last = drop_last
        self.prefetch_factor = prefetch_factor if use_buffer_reader else 0
        self.num_workers = num_workers
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not isinstance(dataset, IterableDataset):
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )
            self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size

    def __iter__(self):
        return _DataLoaderIter(self)

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("IterableDataset DataLoader has no len()")


def get_worker_info():
    return None
