"""paddle.io — datasets, samplers, DataLoader.

Parity: python/paddle/io (DataLoader at io/reader.py:262, workers at
io/dataloader/worker.py). num_workers > 0 starts real OS worker processes
(fork) that fetch+collate numpy batches and hand them to the parent through
POSIX shared memory — the reference's mmap_allocator transport
(phi/core/memory/allocation/mmap_allocator.cc) rebuilt on
multiprocessing.shared_memory. The parent additionally runs a prefetch
thread that stages ready batches to device ahead of consumption (host→HBM
overlap). Workers never touch jax: transport is numpy; device placement
happens in the parent.
"""
from __future__ import annotations

import itertools
import math
import multiprocessing as _mp
import os
import queue as _queue
import threading

import numpy as np

from ..core.tensor import Tensor
from .. import framework


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        counts = [int(math.floor(n * f)) for f in lengths]
        counts[-1] += n - sum(counts)
        lengths = counts
    n = sum(lengths)
    perm = np.random.permutation(n).tolist()
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset : offset + l]))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Sample randomly from a fixed subset of indices (io parity)."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        import numpy as np

        order = np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in order])

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(
            len(self.weights), self.num_samples, replace=self.replacement, p=p
        )
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (parity: io/dataloader/batch_sampler.py)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
            self.epoch += 1
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return Tensor(_to_jax(np.stack(batch)))
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s._data for s in batch]))
    if isinstance(sample, (int, np.integer)):
        return Tensor(_to_jax(np.asarray(batch, dtype=np.int64)))
    if isinstance(sample, (float, np.floating)):
        return Tensor(_to_jax(np.asarray(batch, dtype=np.float32)))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(fields)) for fields in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _to_jax(arr):
    import jax

    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return jax.device_put(arr)


# --------------------------------------------------------------------------
# multiprocess workers + shared-memory transport
# --------------------------------------------------------------------------
def _collate_np(batch):
    """Worker-side collate: numpy only (workers never touch jax)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return [
            _collate_np(list(fields)) for fields in zip(*batch)
        ]
    if isinstance(sample, dict):
        return {k: _collate_np([d[k] for d in batch]) for k in sample}
    return batch


def _tree_to_np(obj):
    """Normalize a collated pytree so it can ride shared memory."""
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, (list, tuple)):
        return [_tree_to_np(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _tree_to_np(v) for k, v in obj.items()}
    return obj


def _flatten_arrays(obj, out):
    """Replace np arrays with {"@arr": i} markers, collecting them in out."""
    if isinstance(obj, np.ndarray):
        out.append(obj)
        return {"@arr": len(out) - 1}
    if isinstance(obj, (list, tuple)):
        return [_flatten_arrays(o, out) for o in obj]
    if isinstance(obj, dict):
        return {k: _flatten_arrays(v, out) for k, v in obj.items()}
    return obj


def _unflatten_arrays(obj, arrays):
    if isinstance(obj, dict) and "@arr" in obj and len(obj) == 1:
        return arrays[obj["@arr"]]
    if isinstance(obj, list):
        return [_unflatten_arrays(o, arrays) for o in obj]
    if isinstance(obj, dict):
        return {k: _unflatten_arrays(v, arrays) for k, v in obj.items()}
    return obj


def _shm_pack(batch):
    """(structure, metas, shm_name|None): arrays concatenated into one
    SharedMemory segment; the structure references them by index."""
    from multiprocessing import shared_memory

    arrays = []
    struct = _flatten_arrays(batch, arrays)
    if not arrays:
        return struct, [], None
    total = sum(int(a.nbytes) for a in arrays)
    shm = shared_memory.SharedMemory(create=True, size=max(total, 1))
    metas = []
    off = 0
    for a in arrays:
        a = np.ascontiguousarray(a)
        shm.buf[off:off + a.nbytes] = a.tobytes()
        metas.append((str(a.dtype), tuple(a.shape), off, int(a.nbytes)))
        off += a.nbytes
    name = shm.name
    # ownership transfers to the parent: without unregistering, the worker's
    # resource tracker unlinks the segment the moment the worker exits —
    # before the parent has read it
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass
    shm.close()  # segment persists until the parent unlinks it
    return struct, metas, name


def _shm_unpack(struct, metas, name):
    from multiprocessing import shared_memory

    if name is None:
        return _unflatten_arrays(struct, [])
    shm = shared_memory.SharedMemory(name=name)
    try:
        arrays = []
        for dtype, shape, off, nbytes in metas:
            # bytes() copies out without keeping an exported pointer into
            # the segment (a live np view would make shm.close() fail)
            raw = bytes(shm.buf[off:off + nbytes])
            arrays.append(np.frombuffer(raw, dtype=np.dtype(dtype))
                          .reshape(shape))
        return _unflatten_arrays(struct, arrays)
    finally:
        shm.close()
        shm.unlink()


def _safe_put(result_q, stop_evt, tag, payload):
    """Deliver unless the parent asked for shutdown; on abort, unlink the
    payload's shm segment ourselves (the parent will never see it)."""
    while not stop_evt.is_set():
        try:
            result_q.put((tag, payload), timeout=0.2)
            return True
        except _queue.Full:
            continue
    _unlink_payload(payload)
    return False


def _worker_loop(dataset, collate_fn, index_q, result_q, stop_evt, wid,
                 num_workers, worker_init_fn, use_shm):
    """Runs in the child process: fetch -> collate -> shm -> result queue."""
    global _worker_ctx
    _worker_ctx = WorkerInfo(id=wid, num_workers=num_workers,
                             dataset=dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    try:
        while not stop_evt.is_set():
            try:
                item = index_q.get(timeout=0.2)
            except _queue.Empty:
                continue
            if item is None:
                return
            bidx, idxs = item
            samples = [dataset[i] for i in idxs]
            batch = _tree_to_np(
                collate_fn(samples) if collate_fn is not None
                else _collate_np(samples))
            payload = _shm_pack(batch) if use_shm else (batch, None, None)
            if not _safe_put(result_q, stop_evt, bidx, payload):
                return
    except KeyboardInterrupt:
        pass
    except Exception as e:  # surface the traceback to the parent
        import traceback

        result_q.put(("error", (wid, f"{e}\n{traceback.format_exc()}", None)))


class _MultiprocessIter:
    """Parent side of the worker pool: dispatch index batches round-robin,
    reorder results, rebuild device tensors from shm payloads."""

    def __init__(self, loader):
        self.loader = loader
        ctx = _mp.get_context("fork")
        self._index_q = ctx.Queue()
        self._stop = ctx.Event()
        self._result_q = ctx.Queue(
            maxsize=max(2, loader.prefetch_factor) * loader.num_workers)
        self._batches = list(loader.batch_sampler)
        self._n = len(self._batches)
        self._next = 0
        self._buffer = {}
        self._workers = []
        for w in range(loader.num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, loader.collate_fn, self._index_q,
                      self._result_q, self._stop, w, loader.num_workers,
                      loader.worker_init_fn, loader.use_shared_memory),
                daemon=True,
            )
            p.start()
            self._workers.append(p)
        for bidx, idxs in enumerate(self._batches):
            self._index_q.put((bidx, list(idxs)))
        for _ in self._workers:
            self._index_q.put(None)

    def _shutdown(self):
        _pool_shutdown(self._stop, self._workers, self._result_q,
                       self._buffer)
        self._workers = []
        self._buffer = {}

    def close(self):
        self._shutdown()

    def __next__(self):
        if self._next >= self._n:
            self._shutdown()
            raise StopIteration
        while self._next not in self._buffer:
            try:
                bidx, payload = self._result_q.get(timeout=5.0)
            except _queue.Empty:
                dead = [i for i, p in enumerate(self._workers)
                        if not p.is_alive()]
                if dead and self._result_q.empty():
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} exited unexpectedly")
                continue
            if bidx == "error":
                wid, tb, _ = payload
                self._shutdown()
                raise RuntimeError(f"DataLoader worker {wid} raised:\n{tb}")
            self._buffer[bidx] = payload
        struct, metas, name = self._buffer.pop(self._next)
        self._next += 1
        batch = _shm_unpack(struct, metas, name)
        return _np_tree_to_tensors(batch)

    def __iter__(self):
        return self

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


def _unlink_payload(payload):
    from multiprocessing import shared_memory

    name = payload[2] if isinstance(payload, tuple) and len(payload) == 3 \
        else None
    if isinstance(name, str):
        try:
            seg = shared_memory.SharedMemory(name=name)
            seg.close()
            seg.unlink()
        except FileNotFoundError:
            pass


def _pool_shutdown(stop_evt, workers, result_q, buffer):
    """Cooperative pool teardown with no shm leaks: signal stop, drain the
    queue (unlinking undelivered payloads) until workers exit, then reap."""
    import time as _time

    stop_evt.set()
    for payload in buffer.values():
        _unlink_payload(payload)
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        try:
            tag, payload = result_q.get(timeout=0.1)
            if tag != "error":
                _unlink_payload(payload)
            continue
        except (_queue.Empty, EOFError, OSError):
            pass
        if not any(p.is_alive() for p in workers):
            break
    # final sweep after all workers exited
    while True:
        try:
            tag, payload = result_q.get_nowait()
        except (_queue.Empty, EOFError, OSError):
            break
        if tag != "error":
            _unlink_payload(payload)
    for p in workers:
        if p.is_alive():
            p.terminate()
        p.join(timeout=1.0)


def _worker_loop_iterable(dataset, collate_fn, batch_size, drop_last,
                          result_q, stop_evt, wid, num_workers,
                          worker_init_fn, use_shm):
    """Iterable-dataset worker: every worker consumes the FULL stream
    (reference worker semantics — shard inside the dataset via
    get_worker_info(), else data duplicates across workers)."""
    global _worker_ctx
    _worker_ctx = WorkerInfo(id=wid, num_workers=num_workers,
                             dataset=dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    try:
        def emit(seq, samples):
            batch = _tree_to_np(
                collate_fn(samples) if collate_fn is not None
                else _collate_np(samples))
            payload = _shm_pack(batch) if use_shm else (batch, None, None)
            return _safe_put(result_q, stop_evt, ("b", wid, seq), payload)

        seq = 0
        batch = []
        for sample in dataset:
            if stop_evt.is_set():
                return
            batch.append(sample)
            if len(batch) == batch_size:
                if not emit(seq, batch):
                    return
                seq += 1
                batch = []
        if batch and not drop_last:
            if not emit(seq, batch):
                return
            seq += 1
        _safe_put(result_q, stop_evt, ("end", wid, seq), (None, None, None))
    except KeyboardInterrupt:
        pass
    except Exception as e:
        import traceback

        result_q.put(("error", (wid, f"{e}\n{traceback.format_exc()}", None)))


class _MultiprocessIterableIter:
    """Worker pool over an IterableDataset: results interleaved round-robin
    across workers (w0.b0, w1.b0, w0.b1, ...)."""

    def __init__(self, loader):
        self.loader = loader
        ctx = _mp.get_context("fork")
        self._stop = ctx.Event()
        self._result_q = ctx.Queue(
            maxsize=max(2, loader.prefetch_factor) * loader.num_workers)
        self._buffer = {}
        self._ends = {}  # wid -> total batches produced
        self._cursor = [0] * loader.num_workers  # next seq per worker
        self._turn = 0
        self._workers = []
        for w in range(loader.num_workers):
            p = ctx.Process(
                target=_worker_loop_iterable,
                args=(loader.dataset, loader.collate_fn, loader.batch_size,
                      loader.drop_last, self._result_q, self._stop, w,
                      loader.num_workers, loader.worker_init_fn,
                      loader.use_shared_memory),
                daemon=True,
            )
            p.start()
            self._workers.append(p)

    def _shutdown(self):
        _pool_shutdown(self._stop, self._workers, self._result_q,
                       self._buffer)
        self._workers = []
        self._buffer = {}

    def close(self):
        self._shutdown()

    def _advance_turn(self):
        n = len(self._cursor)
        for _ in range(n):
            self._turn = (self._turn + 1) % n
            w = self._turn
            if w not in self._ends or self._cursor[w] < self._ends[w]:
                return True
        return False

    def __next__(self):
        n = len(self._cursor)
        while True:
            w = self._turn
            if w in self._ends and self._cursor[w] >= self._ends[w]:
                # this worker is exhausted; find one that isn't
                if not self._advance_turn():
                    self._shutdown()
                    raise StopIteration
                continue
            want = ("b", w, self._cursor[w])
            if want in self._buffer:
                payload = self._buffer.pop(want)
                self._cursor[w] += 1
                self._advance_turn()
                batch = _shm_unpack(*payload)
                return _np_tree_to_tensors(batch)
            try:
                tag, payload = self._result_q.get(timeout=5.0)
            except _queue.Empty:
                dead = [i for i, p in enumerate(self._workers)
                        if not p.is_alive() and i not in self._ends]
                if dead and self._result_q.empty():
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} exited unexpectedly")
                continue
            if tag == "error":
                wid, tb, _ = payload
                self._shutdown()
                raise RuntimeError(f"DataLoader worker {wid} raised:\n{tb}")
            if tag[0] == "end":
                self._ends[tag[1]] = tag[2]
            else:
                self._buffer[tag] = payload

    def __iter__(self):
        return self

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


def _np_tree_to_tensors(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(_to_jax(obj))
    if isinstance(obj, list):
        return [_np_tree_to_tensors(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _np_tree_to_tensors(v) for k, v in obj.items()}
    return obj


class _DataLoaderIter:
    def __init__(self, loader):
        self.loader = loader
        self._iter = self._make_gen()
        if loader.prefetch_factor > 0:
            self._q = _queue.Queue(maxsize=loader.prefetch_factor)
            self._done = object()
            self._thread = threading.Thread(target=self._producer, daemon=True)
            self._thread.start()
        else:
            self._q = None

    def _make_gen(self):
        loader = self.loader
        collate = loader.collate_fn or default_collate_fn
        ds = loader.dataset
        if isinstance(ds, IterableDataset):
            def gen():
                batch = []
                for sample in ds:
                    batch.append(sample)
                    if len(batch) == loader.batch_size:
                        yield collate(batch)
                        batch = []
                if batch and not loader.drop_last:
                    yield collate(batch)

            return gen()

        def gen():
            for idx_batch in loader.batch_sampler:
                samples = [ds[i] for i in idx_batch]
                yield collate(samples)

        return gen()

    def _producer(self):
        try:
            for item in self._iter:
                self._q.put(item)
        finally:
            self._q.put(self._done)

    def __next__(self):
        if self._q is None:
            return next(self._iter)
        item = self._q.get()
        if item is self._done:
            raise StopIteration
        return item

    def __iter__(self):
        return self


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn
        self.drop_last = drop_last
        self.prefetch_factor = prefetch_factor if use_buffer_reader else 0
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not isinstance(dataset, IterableDataset):
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )
            self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size

    def __iter__(self):
        if self.num_workers > 0:
            if self.batch_sampler is not None:
                return _MultiprocessIter(self)
            return _MultiprocessIterableIter(self)
        return _DataLoaderIter(self)

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("IterableDataset DataLoader has no len()")


class WorkerInfo:
    """Visible inside worker processes via get_worker_info()
    (parity: io/dataloader/worker.py WorkerInfo)."""

    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_ctx = None  # set by _worker_loop inside each worker process


def get_worker_info():
    return _worker_ctx
