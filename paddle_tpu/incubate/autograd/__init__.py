"""paddle.incubate.autograd (parity: primapi) — jax primitives ARE the
prim system, so enable/disable are honest toggles over an always-on
capability; the functional transforms re-export paddle.autograd's."""
from ...autograd.functional import Hessian, Jacobian, jvp, vjp  # noqa: F401
from ...autograd import grad  # noqa: F401

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "forward_grad", "grad"]

_PRIM = {"enabled": True}  # jax composes from primitives unconditionally


def enable_prim():
    _PRIM["enabled"] = True


def disable_prim():
    # cannot actually leave primitive-land on this backend; record intent
    _PRIM["enabled"] = False


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode grad (primapi.forward_grad) = jvp tangents."""
    import paddle_tpu as paddle

    def fn(*xs):
        return outputs(*xs) if callable(outputs) else outputs

    if callable(outputs):
        raise TypeError("forward_grad takes computed outputs; use "
                        "paddle.incubate.autograd.jvp for callables")
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    tangents = grad_inputs or [paddle.ones_like(x) for x in ins]
    # recompute via vjp-of-vjp would lose fwd-mode; use autograd.functional
    from ...autograd.functional import jvp as _jvp

    raise NotImplementedError(
        "forward_grad over recorded static programs is not supported on "
        "the TPU build; call paddle.incubate.autograd.jvp(fn, xs, v)")
