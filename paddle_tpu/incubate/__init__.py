"""paddle.incubate — fused-op APIs (Pallas-backed on TPU) + extras."""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import distributed  # noqa: F401


def autotune(config=None):
    # XLA autotunes compiled programs natively; kept for API parity.
    return None


# -- incubate top-level API (parity: python/paddle/incubate/__init__.py) ----
def softmax_mask_fuse(x, mask, name=None):
    import jax

    from ..core.dispatch import apply_op

    return apply_op(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask,
                    _op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x):
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    def _smf(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((a.shape[-2], s), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)

    return apply_op(_smf, x, _op_name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    from ..core.dispatch import apply_op
    import jax.numpy as jnp

    red = {"none": lambda a: a, 0: lambda a: a,
           "sum": jnp.sum, 1: jnp.sum,
           "mean": jnp.mean, 2: jnp.mean}[reduction]
    return apply_op(red, x, _op_name="identity_loss")


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, **kw):
    raise NotImplementedError(
        "graph_khop_sampler: host-side sampling; use numpy/scipy graph "
        "sampling and feed the sampled subgraph")


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1, **kw):
    raise NotImplementedError(
        "graph_sample_neighbors: host-side sampling; use numpy/scipy graph "
        "sampling and feed the sampled subgraph")


def graph_reindex(x, neighbors, count, **kw):
    raise NotImplementedError("graph_reindex: host-side preprocessing step")


from ..geometric import (  # noqa: E402,F401
    segment_max, segment_mean, segment_min, segment_sum)


class LookAhead:
    """LookAhead optimizer wrapper (parity: incubate/optimizer/lookahead.py):
    slow weights track fast weights every k steps."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step = 0
        self._slow = None

    def step(self):
        import jax.numpy as jnp

        self.inner_optimizer.step()
        self._step += 1
        params = self.inner_optimizer._parameter_list or []
        if self._slow is None:
            self._slow = [p._data for p in params]
        if self._step % self.k == 0:
            for p, slow in zip(params, self._slow):
                new_slow = slow + self.alpha * (p._data - slow)
                p._data = new_slow
            self._slow = [p._data for p in params]

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)


class ModelAverage:
    """Exponential/window parameter averaging (incubate/optimizer/
    modelaverage.py): apply() swaps in averaged weights for eval."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameters = list(parameters or [])
        self._sums = None
        self._count = 0

    def step(self):
        if self._sums is None:
            self._sums = [p._data * 0 for p in self._parameters]
        self._sums = [s + p._data for s, p in zip(self._sums,
                                                  self._parameters)]
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            backup = [p._data for p in self._parameters]
            if self._count:
                for p, s in zip(self._parameters, self._sums):
                    p._data = s / self._count
            try:
                yield
            finally:
                if need_restore:
                    for p, b in zip(self._parameters, backup):
                        p._data = b

        return ctx()

    def restore(self, executor=None):
        pass


def inference(*args, **kwargs):
    raise NotImplementedError(
        "paddle.incubate.inference: serve jitted programs via jax.export/"
        "StableHLO (see paddle.onnx.export)")


from . import autograd  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
