"""paddle.incubate — fused-op APIs (Pallas-backed on TPU) + extras."""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import distributed  # noqa: F401


def autotune(config=None):
    # XLA autotunes compiled programs natively; kept for API parity.
    return None
