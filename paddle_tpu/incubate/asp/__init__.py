"""ASP: 2:4 structured sparsity (parity: incubate/asp/asp.py:233,319,536
and the mask-generation/check algorithms of incubate/asp/utils.py).

Mask semantics match the reference: `prune_model` computes an n:m mask per
eligible weight with a selectable algorithm (`mask_1d` keeps the n
largest-magnitude of every m along the input dim; `mask_2d_greedy` /
`mask_2d_best` enforce the pattern along BOTH dims of each m x m block —
the layout the reference generates for sparse-tensor-core friendly
weights), `decorate` wraps the optimizer so masks are re-applied after
every step (OptimizerWithSparsityGuarantee), and the `check_mask_1d/2d` /
`check_sparsity` validators mirror utils.py. Excluded layers are honored
by both prune_model and the step hook.
"""
from __future__ import annotations

import itertools

import numpy as np
import jax.numpy as jnp

from .. import nn as _nn  # noqa: F401  (import cycle guard)

# masks live ON the param object (p._asp_mask): a global dict keyed
# by id(param) collides when CPython reuses a freed id — a stale
# mask from a dead model would silently corrupt a new one
_EXCLUDED = set()      # param names excluded from pruning


# ---------------------------------------------------------------------------
# mask generation (utils.py get_mask_1d / get_mask_2d_greedy / _best)
# ---------------------------------------------------------------------------
def get_mask_1d(w: np.ndarray, n=2, m=4) -> np.ndarray:
    """Keep the n largest-|w| of every m consecutive along the last dim."""
    if w.size % m:
        return np.ones_like(w)
    flat = w.reshape(-1, m)
    idx = np.argsort(-np.abs(flat), axis=1)[:, :n]
    mask = np.zeros_like(flat)
    np.put_along_axis(mask, idx, 1.0, axis=1)
    return mask.reshape(w.shape)


def _blocks_2d(w, m):
    rows, cols = w.shape
    return w.reshape(rows // m, m, cols // m, m).transpose(0, 2, 1, 3)


def _unblocks_2d(b, shape, m):
    rows, cols = shape
    return b.transpose(0, 2, 1, 3).reshape(rows, cols)


def get_mask_2d_greedy(w: np.ndarray, n=2, m=4) -> np.ndarray:
    """n:m in BOTH directions of every m x m block, greedy by magnitude
    (utils.py get_mask_2d_greedy). Vectorized across all blocks: the
    m*m-step selection scan runs once over the whole [B] batch of blocks,
    so a 4096x4096 weight prunes in milliseconds, not minutes."""
    if w.ndim != 2 or w.shape[0] % m or w.shape[1] % m:
        return get_mask_1d(w, n, m)
    blocks = _blocks_2d(np.abs(w), m)           # [R, C, m, m]
    R, C = blocks.shape[:2]
    flat = blocks.reshape(-1, m * m)            # [B, m*m]
    B = flat.shape[0]
    order = np.argsort(-flat, axis=1)           # [B, m*m] descending
    rows_of = order // m
    cols_of = order % m
    row_cnt = np.zeros((B, m), np.int32)
    col_cnt = np.zeros((B, m), np.int32)
    mask = np.zeros((B, m * m), np.float32)
    bidx = np.arange(B)
    for step in range(m * m):
        i = rows_of[:, step]
        j = cols_of[:, step]
        ok = (row_cnt[bidx, i] < n) & (col_cnt[bidx, j] < n)
        sel = order[:, step]
        mask[bidx[ok], sel[ok]] = 1.0
        row_cnt[bidx[ok], i[ok]] += 1
        col_cnt[bidx[ok], j[ok]] += 1
    # completion: pure greedy can strand a block below n*m kept entries
    # (a skipped cell may be the only one left for its row). Those blocks
    # get the exhaustive-best pattern instead, so every block is exactly
    # n-per-row and n-per-column (the reference's masks are always full).
    deficient = mask.sum(1) < n * m
    if deficient.any():
        if (n, m) not in _PATTERN_CACHE:
            _PATTERN_CACHE[(n, m)] = _valid_2d_patterns(n, m)
        pats = _PATTERN_CACHE[(n, m)]
        scores = np.einsum("bi,pi->bp", flat[deficient],
                           pats.reshape(len(pats), -1))
        mask[deficient] = pats.reshape(len(pats), -1)[scores.argmax(1)]
    out = mask.reshape(R, C, m, m)
    return _unblocks_2d(out, w.shape, m).astype(w.dtype)


def _valid_2d_patterns(n, m):
    """All m x m 0/1 matrices with every row and column summing to n."""
    patterns = []
    rows = [np.array(p) for p in itertools.combinations(range(m), n)]
    for choice in itertools.product(rows, repeat=m):
        mat = np.zeros((m, m), np.float32)
        for i, cols in enumerate(choice):
            mat[i, cols] = 1.0
        if (mat.sum(0) == n).all():
            patterns.append(mat)
    return np.stack(patterns)  # [P, m, m]


_PATTERN_CACHE = {}


def get_mask_2d_best(w: np.ndarray, n=2, m=4) -> np.ndarray:
    """Exhaustive best n:m-in-both-dims pattern per m x m block
    (utils.py get_mask_2d_best; 90 valid patterns at 2:4)."""
    if w.ndim != 2 or w.shape[0] % m or w.shape[1] % m:
        return get_mask_1d(w, n, m)
    if (n, m) not in _PATTERN_CACHE:
        _PATTERN_CACHE[(n, m)] = _valid_2d_patterns(n, m)
    pats = _PATTERN_CACHE[(n, m)]               # [P, m, m]
    blocks = _blocks_2d(np.abs(w), m)           # [R, C, m, m]
    scores = np.einsum("rcij,pij->rcp", blocks, pats)
    best = scores.argmax(-1)                    # [R, C]
    out = pats[best]                            # [R, C, m, m]
    return _unblocks_2d(out, w.shape, m).astype(w.dtype)


_MASK_ALGOS = {
    "mask_1d": get_mask_1d,
    "mask_2d_greedy": get_mask_2d_greedy,
    "mask_2d_best": get_mask_2d_best,
}


# ---------------------------------------------------------------------------
# checking (utils.py check_mask_1d / check_mask_2d / check_sparsity)
# ---------------------------------------------------------------------------
def check_mask_1d(mat, n=2, m=4) -> bool:
    arr = np.asarray(mat)
    if arr.size % m:
        return False
    return bool((np.count_nonzero(arr.reshape(-1, m), axis=1) <= n).all())


def check_mask_2d(mat, n=2, m=4) -> bool:
    arr = np.asarray(mat)
    if arr.ndim != 2 or arr.shape[0] % m or arr.shape[1] % m:
        return False
    blocks = _blocks_2d(arr != 0, m)
    return bool(
        (blocks.sum(-1) <= n).all() and (blocks.sum(-2) <= n).all())


def check_sparsity(tensor, n=2, m=4, func_name="check_mask_1d") -> bool:
    fn = check_mask_2d if "2d" in str(func_name) else check_mask_1d
    return fn(np.asarray(
        tensor.numpy() if hasattr(tensor, "numpy") else tensor), n, m)


def calculate_density(tensor) -> float:
    arr = np.asarray(tensor.numpy() if hasattr(tensor, "numpy") else tensor)
    return float((arr != 0).sum() / arr.size)


# ---------------------------------------------------------------------------
# prune + training guarantee (asp.py prune_model / decorate)
# ---------------------------------------------------------------------------
_EXTRA_SUPPORTED = {}


def add_supported_layer(layer, pruning_func=None):
    """Register an extra layer TYPE (or type name) whose `weight` should
    be pruned by prune_model; `pruning_func(weight, n, m) -> mask`
    overrides the default mask algorithm for that layer
    (asp.py add_supported_layer)."""
    key = layer if isinstance(layer, type) else str(layer)
    _EXTRA_SUPPORTED[key] = pruning_func


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every trainable Linear weight (minus excluded)."""
    from paddle_tpu import nn

    algo = _MASK_ALGOS[mask_algo]
    extra_types = tuple(t for t in _EXTRA_SUPPORTED if isinstance(t, type))
    extra_names = {t for t in _EXTRA_SUPPORTED if isinstance(t, str)}
    pruned = {}
    for name, layer in model.named_sublayers():
        supported = (isinstance(layer, nn.Linear)
                     or isinstance(layer, extra_types)
                     or type(layer).__name__ in extra_names)
        if not supported or not hasattr(layer, "weight"):
            continue
        custom = None
        for key, fn in _EXTRA_SUPPORTED.items():
            if fn is not None and (
                    (isinstance(key, type) and isinstance(layer, key))
                    or type(layer).__name__ == key):
                custom = fn
                break
        layer_algo = custom or algo
        p = layer.weight
        pname = getattr(p, "name", name + ".weight")
        if name in _EXCLUDED or pname in _EXCLUDED:
            continue
        w = np.asarray(p.numpy())
        mask = layer_algo(w, n, m)
        p._data = jnp.asarray(w * mask, p._data.dtype)
        if with_mask:
            p._asp_mask = jnp.asarray(mask, p._data.dtype)
        pruned[name] = mask
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update
    (parity: asp.py decorate -> OptimizerWithSparsityGuarantee).
    Idempotent: decorating twice must not stack mask re-applications."""
    if getattr(optimizer, "_asp_decorated", False):
        return optimizer
    orig_step = optimizer.step

    def step(*args, **kwargs):
        out = orig_step(*args, **kwargs)
        for p in optimizer._parameter_list:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._data = p._data * mask
        return out

    optimizer.step = step
    optimizer._asp_decorated = True
    return optimizer


def reset_excluded_layers(model=None):
    _EXCLUDED.clear()


def set_excluded_layers(model=None, param_names=()):
    """Exclude layers (by sublayer name or param name) from pruning
    (asp.py set_excluded_layers)."""
    _EXCLUDED.update(param_names)
