"""ASP: 2:4 structured sparsity (parity: incubate/asp/asp.py:233,319,536).

Mask semantics match the reference: `prune_model` computes a 2:4 mask per
eligible weight (keep the 2 largest-magnitude of every 4 along the input
dim), `decorate` wraps the optimizer so masks are re-applied after every
step, keeping pruned weights at exactly zero through training.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .. import nn as _nn  # noqa: F401  (import cycle guard)

_MASKS = {}  # id(param) -> jnp mask


def _mask_2to4(w: np.ndarray) -> np.ndarray:
    flat = w.reshape(-1, 4) if w.size % 4 == 0 else None
    if flat is None:
        return np.ones_like(w)
    idx = np.argsort(-np.abs(flat), axis=1)[:, :2]
    mask = np.zeros_like(flat)
    np.put_along_axis(mask, idx, 1.0, axis=1)
    return mask.reshape(w.shape)


def calculate_density(tensor) -> float:
    arr = np.asarray(tensor.numpy() if hasattr(tensor, "numpy") else tensor)
    return float((arr != 0).sum() / arr.size)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to every >=2D trainable weight of Linear layers."""
    from paddle_tpu import nn

    pruned = {}
    for name, layer in model.named_sublayers():
        if not isinstance(layer, nn.Linear):
            continue
        p = layer.weight
        w = np.asarray(p.numpy())
        mask = _mask_2to4(w)
        p._data = jnp.asarray(w * mask, p._data.dtype)
        _MASKS[id(p)] = jnp.asarray(mask, p._data.dtype)
        pruned[name] = mask
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update
    (parity: asp.py decorate -> OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step(*args, **kwargs):
        out = orig_step(*args, **kwargs)
        for p in optimizer._parameter_list:
            mask = _MASKS.get(id(p))
            if mask is not None:
                p._data = p._data * mask
        return out

    optimizer.step = step
    return optimizer


def reset_excluded_layers(model=None):
    pass


def set_excluded_layers(model=None, param_names=()):
    pass
