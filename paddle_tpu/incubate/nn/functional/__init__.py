"""Fused-op functional APIs (parity: python/paddle/incubate/nn/functional).

Reference implements these as hand-written CUDA fusions
(phi/kernels/fusion/gpu); on TPU they are either Pallas kernels (flash
attention path) or straight-line jnp that XLA fuses into single kernels —
measured to fuse fully under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1, bias=None, residual=None, quant_scale=-1, **kw):
    def _frms(a, w, b, bias_in, res):
        if bias_in is not None:
            a = a + bias_in
        if res is not None:
            a = a + res
        ax = begin_norm_axis % a.ndim
        rows = 1
        for s in a.shape[:-1]:
            rows *= s
        if (ax == a.ndim - 1 and b is None and rows % 8 == 0
                and jax.default_backend() == "tpu"):
            from ....ops.pallas import rms_norm as _pallas_rms

            return _pallas_rms(a, w, epsilon)
        axes = tuple(range(ax, a.ndim))
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        out = out * w
        if b is not None:
            out = out + b
        return out

    return apply_op(_frms, x, norm_weight, norm_bias, bias, residual, _op_name="fused_rms_norm")


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon=1e-5, begin_norm_axis=-1, bias=None, residual=None, **kw):
    def _fln(a, w, b, bias_in, res):
        if bias_in is not None:
            a = a + bias_in
        if res is not None:
            a = a + res
        ax = begin_norm_axis % a.ndim
        axes = tuple(range(ax, a.ndim))
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = ((af - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out

    return apply_op(_fln, x, norm_weight, norm_bias, bias, residual, _op_name="fused_layer_norm")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None, position_ids=None, use_neox_rotary_style=True, time_major=False, rotary_emb_base=10000.0):
    """parity: incubate/nn/functional/fused_rotary_position_embedding."""

    def _rope_one(x, sin_t, cos_t):
        if x is None:
            return None
        # x: [B, S, H, D]
        d = x.shape[-1]
        if sin_t is None:
            pos = jnp.arange(x.shape[1], dtype=jnp.float32)
            inv = rotary_emb_base ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
            freqs = jnp.outer(pos, inv)
            sin_l = jnp.sin(freqs)
            cos_l = jnp.cos(freqs)
        else:
            sin_l = sin_t.reshape(sin_t.shape[-2], -1)[:, : d // 2]
            cos_l = cos_t.reshape(cos_t.shape[-2], -1)[:, : d // 2]
        sin_b = sin_l[None, :, None, :]
        cos_b = cos_l[None, :, None, :]
        if use_neox_rotary_style:
            x1, x2 = x[..., : d // 2], x[..., d // 2 :]
            o1 = x1 * cos_b - x2 * sin_b
            o2 = x2 * cos_b + x1 * sin_b
            return jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        o1 = x1 * cos_b - x2 * sin_b
        o2 = x2 * cos_b + x1 * sin_b
        out = jnp.stack([o1, o2], axis=-1).reshape(x.shape)
        return out.astype(x.dtype)

    def _rope(q_, k_, v_, sin_t, cos_t):
        return tuple(_rope_one(t, sin_t, cos_t) for t in (q_, k_, v_) if t is not None)

    outs = apply_op(_rope, q, k, v, sin, cos, _op_name="fused_rope")
    res = []
    it = iter(outs)
    for t in (q, k, v):
        res.append(next(it) if t is not None else None)
    return tuple(res)


def swiglu(x, y=None, name=None):
    from ....nn.functional.activation import swiglu as _swiglu

    return _swiglu(x, y)


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None, act_method="gelu", **kw):
    def _fba(a, b):
        if b is not None:
            a = a + b
        if act_method in ("gelu", "geglu"):
            return jax.nn.gelu(a)
        if act_method in ("swiglu",):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2
        return jax.nn.relu(a)

    return apply_op(_fba, x, bias, _op_name="fused_bias_act")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def _fl(a, w, b):
        if transpose_weight:
            w = w.T
        out = jnp.matmul(a, w)
        if b is not None:
            out = out + b
        return out

    return apply_op(_fl, x, weight, bias, _op_name="fused_linear")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    from ....nn.functional.common import dropout

    return dropout(x, p, training=training, mode=mode) + y
