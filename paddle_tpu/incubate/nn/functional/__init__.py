"""Fused-op functional APIs (parity: python/paddle/incubate/nn/functional).

Reference implements these as hand-written CUDA fusions
(phi/kernels/fusion/gpu); on TPU they are either Pallas kernels (flash
attention path) or straight-line jnp that XLA fuses into single kernels —
measured to fuse fully under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1, bias=None, residual=None, quant_scale=-1, **kw):
    """RMS norm with optional fused bias/residual add.

    Matches the reference contract (incubate/nn/functional/fused_rms_norm.py:59):
    with ``residual`` the op returns ``(out, residual_out)`` where
    ``residual_out = x (+bias) + residual`` is the updated residual stream;
    without it, just ``out``. On TPU the residual+norm path runs the fused
    Pallas kernel (ops/pallas/add_rms_norm.py — one VMEM pass emits both)."""
    def _frms(a, w, b, bias_in, res):
        if bias_in is not None:
            a = a + bias_in
        ax = begin_norm_axis % a.ndim
        rows = 1
        for s in a.shape[:-1]:
            rows *= s
        from ....ops.pallas import on_tpu_device

        fast = (ax == a.ndim - 1 and b is None and rows % 8 == 0
                and on_tpu_device())
        if res is not None:
            if fast:
                from ....ops.pallas.add_rms_norm import add_rms_norm

                y, out = add_rms_norm(a, res, w, epsilon)
                return out, y
            a = a + res
        if fast:
            # res is always None here (the fast+residual case returned above)
            from ....ops.pallas import rms_norm as _pallas_rms

            return _pallas_rms(a, w, epsilon)
        axes = tuple(range(ax, a.ndim))
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        out = out * w
        if b is not None:
            out = out + b
        return (out, a) if res is not None else out

    return apply_op(_frms, x, norm_weight, norm_bias, bias, residual, _op_name="fused_rms_norm")


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon=1e-5, begin_norm_axis=-1, bias=None, residual=None, **kw):
    def _fln(a, w, b, bias_in, res):
        if bias_in is not None:
            a = a + bias_in
        if res is not None:
            a = a + res
        ax = begin_norm_axis % a.ndim
        axes = tuple(range(ax, a.ndim))
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = ((af - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        # reference contract: residual path returns (out, residual_out)
        return (out, a) if res is not None else out

    return apply_op(_fln, x, norm_weight, norm_bias, bias, residual, _op_name="fused_layer_norm")


def _apply_rotary(x, sin, cos, neox):
    """Shared rotary core: x [..., D] with sin/cos broadcastable [..., D/2].
    neox rotates halves; interleaved pairs otherwise."""
    d = x.shape[-1]
    if neox:
        x1, x2 = x[..., : d // 2], x[..., d // 2:]
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        ).astype(x.dtype)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape).astype(x.dtype)


def _rotary_sin_cos(pos, d, theta):
    """Standard rope table rows for integer positions `pos` -> [T, D/2]."""
    inv = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    freqs = pos.astype(jnp.float32)[..., None] * inv
    return jnp.sin(freqs), jnp.cos(freqs)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None, position_ids=None, use_neox_rotary_style=True, time_major=False, rotary_emb_base=10000.0):
    """parity: incubate/nn/functional/fused_rotary_position_embedding."""

    def _rope_one(x, sin_t, cos_t):
        if x is None:
            return None
        # x: [B, S, H, D]
        d = x.shape[-1]
        if sin_t is None:
            pos = jnp.arange(x.shape[1], dtype=jnp.float32)
            sin_l, cos_l = _rotary_sin_cos(pos, d, rotary_emb_base)
        else:
            sin_l = sin_t.reshape(sin_t.shape[-2], -1)[:, : d // 2]
            cos_l = cos_t.reshape(cos_t.shape[-2], -1)[:, : d // 2]
        sin_b = sin_l[None, :, None, :]
        cos_b = cos_l[None, :, None, :]
        return _apply_rotary(x, sin_b, cos_b, use_neox_rotary_style)

    def _rope(q_, k_, v_, sin_t, cos_t):
        return tuple(_rope_one(t, sin_t, cos_t) for t in (q_, k_, v_) if t is not None)

    outs = apply_op(_rope, q, k, v, sin, cos, _op_name="fused_rope")
    res = []
    it = iter(outs)
    for t in (q, k, v):
        res.append(next(it) if t is not None else None)
    return tuple(res)


def swiglu(x, y=None, name=None):
    from ....nn.functional.activation import swiglu as _swiglu

    return _swiglu(x, y)


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None, act_method="gelu", **kw):
    def _fba(a, b):
        if b is not None:
            a = a + b
        if act_method in ("gelu", "geglu"):
            return jax.nn.gelu(a)
        if act_method in ("swiglu",):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2
        return jax.nn.relu(a)

    return apply_op(_fba, x, bias, _op_name="fused_bias_act")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def _fl(a, w, b):
        if transpose_weight:
            w = w.T
        out = jnp.matmul(a, w)
        if b is not None:
            out = out + b
        return out

    return apply_op(_fl, x, weight, bias, _op_name="fused_linear")


def _quantize_rows_int8(a):
    """Per-row absmax int8 quantisation: a [R, H] -> (q int8, scale [R,1]).
    ONE implementation, shared with the chunked-CE head — the int8 parity
    gate probes the same quantizer every int8 path runs (lazy import: the
    fused-CE module is a leaf, but this package loads early)."""
    from ....nn.functional.fused_cross_entropy import _quantize_rows

    return _quantize_rows(a)


@jax.custom_vjp
def _int8_head_core(hc, w2, qw, sw):
    """int8 x int8 LM-head matmul: per-token-row scales on h, per-vocab-
    row scales on w — on int8-capable MXUs (v5e: 2x the bf16 rate) this
    halves the head's forward cost. VERDICT r3 slot: the optional int8
    weight-only LM-head, behind PTPU_INT8_HEAD with a parity test.

    The weight quantisation (qw, sw) is computed ONCE by the caller and
    passed in — re-quantising the [V, H] matrix inside every CE chunk
    (and again in each chunk's checkpointed backward) was a measured
    share of the flag's regression. ``w2`` rides along only so the
    straight-through backward can use the REAL weights."""
    qh, sh = _quantize_rows_int8(hc)
    acc = jnp.einsum("ch,vh->cv", qh, qw,
                     preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * sh * sw.T


def _int8_head_fwd(hc, w2, qw, sw):
    return _int8_head_core(hc, w2, qw, sw), (hc, w2)


def _int8_head_bwd(res, g):
    # wide backward: the quantised forward approximates the loss surface,
    # but gradients flow through the REAL weights (straight-through) —
    # the standard weight-quantised-training recipe
    import numpy as _np

    hc, w2 = res
    gf = g.astype(jnp.float32)
    dh = (gf @ w2.astype(jnp.float32)).astype(hc.dtype)
    dw = jnp.einsum("cv,ch->vh", gf,
                    hc.astype(jnp.float32)).astype(w2.dtype)
    # the quantised operands are derived values: int8 qw gets the float0
    # cotangent integers require; sw gets zeros (w2's dw is the real
    # grad). Shapes derive from w2 — qw matches it, sw is [V, 1] f32.
    dqw = _np.zeros(w2.shape, jax.dtypes.float0)
    dsw = jnp.zeros((w2.shape[0], 1), jnp.float32)
    return dh, dw, dqw, dsw


_int8_head_core.defvjp(_int8_head_fwd, _int8_head_bwd)


def _int8_head_logits(hc, w, transpose_y, qw=None, sw=None):
    w2 = w if transpose_y else w.T          # [V, H]
    if qw is None:
        qw, sw = _quantize_rows_int8(w2)
    return _int8_head_core(hc, w2, qw, sw)


def fused_linear_cross_entropy(x, weight, labels, transpose_y=True,
                               chunk_size=512, ignore_index=-100, name=None):
    """LM-head matmul + softmax cross entropy WITHOUT materializing the
    [N, vocab] logits (capability slot: the reference's fused CE path —
    c_softmax_with_cross_entropy / fused kernels in phi/kernels/fusion).

    Chunks the flattened rows; each chunk computes its logits with fp32
    accumulation, takes logsumexp, and is dropped — jax.checkpoint makes the
    backward recompute per chunk, so peak memory is O(chunk_size * vocab)
    instead of O(N * vocab). Returns the mean loss over non-ignored rows.

    x: [..., H] hidden states; weight: [V, H] (transpose_y=True, the tied
    embedding layout) or [H, V]; labels: [...] int.
    """
    def _flce(h, w, y):
        import os as _os

        H = h.shape[-1]
        hf = h.reshape(-1, H)
        yf = y.reshape(-1).astype(jnp.int32)
        n = hf.shape[0]
        # perf knob: bigger chunks = fewer serialized lax.map steps, more
        # logits resident (O(chunk * vocab) fp32)
        c = min(max(1, int(_os.environ.get("PTPU_CE_CHUNK", chunk_size))), n)
        pad = (-n) % c
        if pad:
            hf = jnp.concatenate([hf, jnp.zeros((pad, H), hf.dtype)])
            yf = jnp.concatenate([yf, jnp.full((pad,), ignore_index, yf.dtype)])
        valid = (yf != ignore_index)
        hs = hf.reshape(-1, c, H)
        ys = jnp.where(valid, yf, 0).reshape(-1, c)
        ms = valid.astype(jnp.float32).reshape(-1, c)

        spec = "ch,vh->cv" if transpose_y else "ch,hv->cv"
        # parity-gated default (PTPU_INT8_HEAD forces either way) — the
        # same resolver as the chunked-CE head, docs/PERF.md
        from ....nn.functional.fused_cross_entropy import int8_head_enabled

        int8_head = int8_head_enabled()
        if int8_head:
            # quantise the [V, H] weight ONCE for all chunks (and their
            # checkpointed backward recomputes)
            w2_full = w if transpose_y else w.T
            qw_full, sw_full = _quantize_rows_int8(
                jax.lax.stop_gradient(w2_full))

        def chunk_fn(args):
            hc, yc, mc = args
            if int8_head:
                logits = _int8_head_logits(hc, w, transpose_y,
                                           qw=qw_full, sw=sw_full)
            else:
                logits = jnp.einsum(spec, hc, w,
                                    preferred_element_type=jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, yc[:, None], axis=-1)[:, 0]
            return ((lse - gold) * mc).sum()

        sums = jax.lax.map(jax.checkpoint(chunk_fn), (hs, ys, ms))
        count = jnp.maximum(ms.sum(), 1.0)
        return sums.sum() / count

    return apply_op(_flce, x, weight, labels,
                    _op_name="fused_linear_cross_entropy")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    from ....nn.functional.common import dropout

    return dropout(x, p, training=training, mode=mode) + y


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    def _fmb(a, b, bias_a):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b)
        if bias_a is not None:
            out = out + bias_a
        return out

    return apply_op(_fmb, x, y, bias, _op_name="fused_matmul_bias")


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "none": lambda a: a, "": lambda a: a}[activation]
    return apply_op(act, out, _op_name="fused_linear_activation")


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               transpose_qkv_wb=False, name=None):
    """Functional fused MHA (fused_transformer.py parity).

    qkv_weight: [3, H, D, E] (or [E, 3E] with transpose_qkv_wb).
    """
    from .... import framework
    from ....nn.functional.flash_attention import sdpa_arrays

    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention cache_kv: use the kv-cache decode "
            "path (models/llama.py generate) or masked_multihead_attention")
    drop_key = (framework.next_rng_key()
                if training and dropout_rate > 0.0 else None)
    attn_key = (framework.next_rng_key()
                if training and attn_dropout_rate > 0.0 else None)

    def _fmha(xa, qkvw, lw, pls, plb, lns, lnb, qkvb, lb, mask):
        b, s, e = xa.shape
        h = xa
        if pre_layer_norm:
            mean = jnp.mean(h.astype(jnp.float32), -1, keepdims=True)
            var = jnp.var(h.astype(jnp.float32), -1, keepdims=True)
            h = ((h - mean) * jax.lax.rsqrt(var + pre_ln_epsilon)).astype(xa.dtype)
            if pls is not None:
                h = h * pls
            if plb is not None:
                h = h + plb
        if transpose_qkv_wb:
            nh = num_heads
            qkv = h @ qkvw
            if qkvb is not None:
                qkv = qkv + qkvb
            q, k, v = jnp.split(qkv, 3, axis=-1)
            hd = e // nh
        else:
            three, nh, hd, _ = qkvw.shape
            qkv = jnp.einsum("bse,nhde->bsnhd", h, qkvw)
            if qkvb is not None:
                qkv = qkv + qkvb[None, None]
            q, k, v = qkv[:, :, 0].reshape(b, s, nh * hd), \
                qkv[:, :, 1].reshape(b, s, nh * hd), \
                qkv[:, :, 2].reshape(b, s, nh * hd)
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)
        if mask is not None or attn_key is not None:
            from ....nn.functional.flash_attention import _xla_sdpa

            out = _xla_sdpa(q, k, v, mask=mask,
                            dropout=attn_dropout_rate if attn_key is not None else 0.0,
                            key=attn_key)
        else:
            out = sdpa_arrays(q, k, v, causal=False)
        out = out.reshape(b, s, nh * hd)
        out = out @ lw
        if lb is not None:
            out = out + lb
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1.0 - dropout_rate,
                                        out.shape)
            out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
        if add_residual:
            out = xa + out
        if not pre_layer_norm:
            mean = jnp.mean(out.astype(jnp.float32), -1, keepdims=True)
            var = jnp.var(out.astype(jnp.float32), -1, keepdims=True)
            out = ((out - mean) * jax.lax.rsqrt(var + ln_epsilon)).astype(xa.dtype)
            if lns is not None:
                out = out * lns
            if lnb is not None:
                out = out + lnb
        return out

    return apply_op(_fmha, x, qkv_weight, linear_weight, pre_ln_scale,
                    pre_ln_bias, ln_scale, ln_bias, qkv_bias, linear_bias,
                    attn_mask, _op_name="fused_multi_head_attention")


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, name=None):
    from .... import framework

    key1 = (framework.next_rng_key()
            if training and dropout1_rate > 0.0 else None)
    key2 = (framework.next_rng_key()
            if training and dropout2_rate > 0.0 else None)

    def _ffn(xa, w1, w2, b1, b2, s1, sb1, s2, sb2):
        h = xa
        def ln(a, scale, bias, eps):
            mean = jnp.mean(a.astype(jnp.float32), -1, keepdims=True)
            var = jnp.var(a.astype(jnp.float32), -1, keepdims=True)
            out = ((a - mean) * jax.lax.rsqrt(var + eps)).astype(a.dtype)
            if scale is not None:
                out = out * scale
            if bias is not None:
                out = out + bias
            return out

        if pre_layer_norm:
            h = ln(h, s1, sb1, ln1_epsilon)
        act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]
        h = act(h @ w1 + (b1 if b1 is not None else 0))
        if key1 is not None:
            keep = jax.random.bernoulli(key1, 1.0 - dropout1_rate, h.shape)
            h = jnp.where(keep, h / (1.0 - dropout1_rate), 0.0)
        h = h @ w2 + (b2 if b2 is not None else 0)
        if key2 is not None:
            keep = jax.random.bernoulli(key2, 1.0 - dropout2_rate, h.shape)
            h = jnp.where(keep, h / (1.0 - dropout2_rate), 0.0)
        out = xa + h
        if not pre_layer_norm:
            out = ln(out, s2, sb2, ln2_epsilon)
        return out

    return apply_op(_ffn, x, linear1_weight, linear2_weight, linear1_bias,
                    linear2_bias, ln1_scale, ln1_bias, ln2_scale, ln2_bias,
                    _op_name="fused_feedforward")


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-05, cache_kvs=None, pre_caches=None,
                            seq_lens=None, rotary_embs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False, mode=None,
                            trans_qkvw=True, ring_id=-1, name=None, **kw):
    """Stacked fused decoder inference layers (context/prefill form)."""
    if cache_kvs is not None or time_step is not None or pre_caches is not None:
        raise NotImplementedError(
            "fused_multi_transformer incremental decode (cache_kvs/"
            "time_step): use models/llama.py generate() — the fixed-shape "
            "kv-cache decode path")
    out = x
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        out = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i] if ln_scales else None,
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            pre_layer_norm=pre_layer_norm, activation=activation,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            training=training)
    return out


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True,
                                           mode="upscale_in_train",
                                           name=None):
    from .... import framework

    dkey = (framework.next_rng_key()
            if training and dropout_rate > 0.0 else None)

    def _f(xa, res, b, s, lb):
        h = xa + (b if b is not None else 0)
        if dkey is not None:
            keep = jax.random.bernoulli(dkey, 1.0 - dropout_rate, h.shape)
            h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
        h = h + res
        mean = jnp.mean(h.astype(jnp.float32), -1, keepdims=True)
        var = jnp.var(h.astype(jnp.float32), -1, keepdims=True)
        out = ((h - mean) * jax.lax.rsqrt(var + ln_epsilon)).astype(xa.dtype)
        if s is not None:
            out = out * s
        if lb is not None:
            out = out + lb
        return out

    return apply_op(_f, x, residual, bias, ln_scale, ln_bias,
                    _op_name="fused_bias_dropout_residual_ln")


def fused_moe(x, gate_weight, expert_weights1, expert_biases1,
              expert_weights2, expert_biases2, moe_topk=2,
              norm_topk_prob=True, group_moe=False, name=None):
    """Fused MoE FFN (fusion/gpu fused_moe parity): top-k gate + stacked
    expert FFNs via the GShard dense-dispatch einsums."""
    from ....incubate.distributed.models.moe import _dense_dispatch_combine

    if group_moe:
        raise NotImplementedError("fused_moe group_moe")

    def _moe(xa, gw, w1, b1, w2, b2):
        shape = xa.shape
        m = shape[-1]
        flat = xa.reshape(-1, m)
        logits = flat @ gw
        e = logits.shape[-1]
        val, idx = jax.lax.top_k(logits, moe_topk)
        cap = flat.shape[0]  # full capacity: no drops in the fused op
        ei, comb = _dense_dispatch_combine(flat, idx, val, e, cap)
        if not norm_topk_prob:
            # reference weights by the full-softmax prob of each selected
            # expert (sum < 1); comb rows are renormalised — rescale back
            full = jax.nn.softmax(logits, -1)
            sel = jnp.take_along_axis(full, idx, -1).sum(-1)
            comb = comb * sel[:, None, None]
        h = jnp.einsum("ecm,emh->ech", ei, w1)
        if b1 is not None:
            h = h + b1[:, None]
        h = jax.nn.gelu(h)
        y = jnp.einsum("ech,ehm->ecm", h, w2)
        if b2 is not None:
            y = y + b2[:, None]
        out = jnp.einsum("nec,ecm->nm", comb.astype(jnp.float32),
                         y.astype(jnp.float32)).astype(xa.dtype)
        return out.reshape(shape)

    return apply_op(_moe, x, gate_weight, expert_weights1, expert_biases1,
                    expert_weights2, expert_biases2, _op_name="fused_moe")


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0,
                                               name=None):
    """Varlen attention: per-sequence validity masks over padded batches.
    Layout [B, H, S, D] (matches the cutlass op)."""
    import math as _math

    def _vl(q, k, v, sl, kvl, m):
        b, h, s, d = q.shape
        sc = scale if scale is not None else 1.0 / _math.sqrt(d)
        logits = jnp.einsum("bhsd,bhtd->bhst", q * sc, k)
        kpos = jnp.arange(k.shape[2])[None, None, None, :]
        valid = kpos < kvl.reshape(-1)[:, None, None, None]
        if causal:
            qpos = jnp.arange(s)[None, None, :, None]
            valid = valid & (kpos <= qpos + pre_cache_length)
        if m is not None:
            logits = logits + m
        logits = jnp.where(valid, logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        return jnp.einsum("bhst,bhtd->bhsd", probs, v)

    return apply_op(_vl, query, key, value, seq_lens, kv_seq_lens, mask,
                    _op_name="varlen_attention")


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default",
                               out_scale=-1, quant_round_type=1,
                               quant_max_bound=127.0,
                               quant_min_bound=-127.0, name=None):
    """Single-token decode attention over a [2, B, H, MaxLen, D] cache
    (fusion/gpu masked_multihead_attention parity)."""
    def _mmha(xa, cache, b_in, mask, seq_lens):
        b = xa.shape[0]
        two, _, h, max_len, d = cache.shape
        qkv = xa.reshape(b, 3, h, d)
        if b_in is not None:
            qkv = qkv + b_in.reshape(1, 3, h, d)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        # per-batch write position = that row's current length
        if seq_lens is not None:
            cur = seq_lens.reshape(-1).astype(jnp.int32)  # [B]
        else:
            cur = jnp.zeros((b,), jnp.int32)
        bidx = jnp.arange(b)
        kc = cache[0].at[bidx, :, cur, :].set(k.astype(cache.dtype))
        vc = cache[1].at[bidx, :, cur, :].set(v.astype(cache.dtype))
        from ....ops.pallas import log_path_once, on_tpu_device

        if mask is None and on_tpu_device() and d <= 256 and max_len % 8 == 0:
            # pallas decode kernel (decode_attention.py): online softmax,
            # KV streamed through VMEM — the masked_multihead_attention
            # fusion slot on TPU
            from ....ops.pallas.decode_attention import decode_attention

            log_path_once("mmha", "pallas_decode")
            out = decode_attention(q.astype(kc.dtype), kc, vc, cur + 1)
            return out.reshape(b, h * d), jnp.stack([kc, vc])
        log_path_once("mmha", "xla_decode")
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
        logits = jnp.einsum("bhd,bhtd->bht", q * scale, kc)
        valid = (jnp.arange(max_len)[None, None, :]
                 <= cur[:, None, None])
        logits = jnp.where(valid, logits, -1e30)
        if mask is not None:
            logits = logits + mask.reshape(b, 1, -1)[:, :, :max_len]
        probs = jax.nn.softmax(logits, -1)
        out = jnp.einsum("bht,bhtd->bhd", probs, vc)
        return out.reshape(b, h * d), jnp.stack([kc, vc])

    return apply_op(_mmha, x, cache_kv, bias, src_mask, sequence_lengths,
                    _op_name="masked_multihead_attention")


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size):
    def _g(a, b):
        return jnp.max(a), jnp.max(b)

    return apply_op(_g, seq_lens_encoder, seq_lens_decoder,
                    _op_name="blha_get_max_len")


def paged_attention(q, k_pages, v_pages, block_tables, lengths, scale=None):
    """TPU-native paged-KV decode attention (the clean entry over the
    pallas kernel; `block_multihead_attention` is the reference-shaped
    wrapper). q [B, Hq, D]; pages [Hkv, NumPages, PageSize, D]."""
    from ....ops.pallas.decode_attention import paged_attention as _pa

    def _run(qa, kp, vp, bt, ln):
        return _pa(qa, kp, vp, bt, ln, scale=scale)

    return apply_op(_run, q, k_pages, v_pages, block_tables, lengths,
                    _op_name="paged_attention")


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets, cum_offsets, cu_seqlens_q,
                              cu_seqlens_k, block_tables, pre_key_cache=None,
                              pre_value_cache=None, cache_k_quant_scales=None,
                              cache_v_quant_scales=None,
                              cache_k_dequant_scales=None,
                              cache_v_dequant_scales=None, qkv_out_scale=None,
                              qkv_bias=None, out_shift=None, out_smooth=None,
                              max_enc_len_this_time=None,
                              max_dec_len_this_time=None, rope_emb=None,
                              mask=None, tgt_mask=None, max_seq_len=-1,
                              block_size=64, use_neox_style=False,
                              rope_theta=10000.0, **kwargs):
    """Paged-KV attention (parity: fusion/gpu block_multi_head_attention;
    python surface `incubate/nn/functional/block_multihead_attention.py:56`).

    Reference cache layout [MaxBlockNum, H, BlockSize, D] with
    block_tables [B, BlocksPerSeq]. Decode steps (every live slot's
    seq_lens_this_time <= 1) run the pallas paged kernel
    (`ops/pallas/decode_attention.py`) — finished slots (== 0) are simply
    excluded from the batch; prefill writes each sequence's tokens into
    its pages and runs causal attention per sequence (eager path — the
    serving engine drives steps eagerly). KV-cache int8 quantization is
    not implemented (raises). Returns (out, qkv, key_cache, value_cache).
    """
    import numpy as _np

    if any(s is not None for s in (cache_k_quant_scales, cache_v_quant_scales,
                                   cache_k_dequant_scales,
                                   cache_v_dequant_scales, qkv_out_scale,
                                   out_shift, out_smooth)):
        raise NotImplementedError(
            "block_multihead_attention: int8 KV-cache / output quantization "
            "is not implemented on the TPU path")

    def _to_arr(t):
        return t.value if hasattr(t, "value") else (
            t._data if hasattr(t, "_data") else t)

    qkv_a = _to_arr(qkv)
    kc = _to_arr(key_cache)
    vc = _to_arr(value_cache)
    tables = _to_arr(block_tables).astype(jnp.int32)
    enc = _np.asarray(_to_arr(seq_lens_encoder)).reshape(-1)
    dec = _np.asarray(_to_arr(seq_lens_decoder)).reshape(-1)
    this = _np.asarray(_to_arr(seq_lens_this_time)).reshape(-1)
    rope = None if rope_emb is None else _to_arr(rope_emb)
    tmask = None if tgt_mask is None else _to_arr(tgt_mask)
    pmask = None if mask is None else _to_arr(mask)
    b = this.shape[0]
    nblocks, h, bsz, d = kc.shape           # h = kv heads
    hq = qkv_a.shape[-1] // d - 2 * h       # GQA: qkv packs [hq + 2*h] heads

    if qkv_bias is not None:
        qkv_a = qkv_a + _to_arr(qkv_bias).reshape(1, -1)

    def _split_qkv(rows):
        """[T, (hq+2h)*d] -> q [T,hq,d], k [T,h,d], v [T,h,d]."""
        t = rows.shape[0]
        flat = rows.reshape(t, hq + 2 * h, d)
        return flat[:, :hq], flat[:, hq:hq + h], flat[:, hq + h:]

    def _rope_at(x, pos, seq_idx):
        """Rotary at integer positions, [T, H, D]. Uses the CALLER's rope
        table (rope_emb [2, B, max_seq, 1, D/2]: [0]=cos rows, [1]=sin —
        NTK/linear scaling arrives through the table, never recomputed)."""
        if rope is not None:
            cos_t = rope[0, seq_idx, pos].reshape(pos.shape[0], 1, -1)
            sin_t = rope[1, seq_idx, pos].reshape(pos.shape[0], 1, -1)
        else:
            sin_t, cos_t = _rotary_sin_cos(pos, d, rope_theta)
            sin_t, cos_t = sin_t[:, None, :], cos_t[:, None, :]
        return _apply_rotary(x, sin_t, cos_t, use_neox_style)

    use_rope = rope_emb is not None
    live = this > 0

    if (this[live] == 1).all() and (enc == 0).all():
        # ---- decode: one token per LIVE slot, pallas paged kernel ------
        active = _np.nonzero(live)[0]                       # slot ids, in order
        ba = len(active)
        act = jnp.asarray(active, jnp.int32)
        cur = jnp.asarray(dec[active], jnp.int32)           # cached lengths
        tab_a = tables[act]                                 # [Ba, pages]

        def _decode(rows, kc, vc):
            q, k, v = _split_qkv(rows)                      # [Ba, hq|h, D]
            if use_rope:
                q = _rope_at(q, cur, act)
                k = _rope_at(k, cur, act)
            page_ids = tab_a[jnp.arange(ba), cur // bsz]    # [Ba]
            offs = cur % bsz
            kc = kc.at[page_ids, :, offs, :].set(k.astype(kc.dtype))
            vc = vc.at[page_ids, :, offs, :].set(v.astype(vc.dtype))
            from ....ops.pallas import log_path_once

            if tmask is None:
                from ....ops.pallas.decode_attention import (
                    paged_attention as _pa,
                )

                log_path_once("blha", "pallas_paged")
                out = _pa(q, jnp.swapaxes(kc, 0, 1), jnp.swapaxes(vc, 0, 1),
                          tab_a, cur + 1)
            else:
                # masked decode: dense gather fallback (kernel is unmasked)
                log_path_once("blha", "xla_paged_masked")
                kd = jnp.swapaxes(kc[tab_a], 1, 2).reshape(ba, h, -1, d)
                vd = jnp.swapaxes(vc[tab_a], 1, 2).reshape(ba, h, -1, d)
                s = kd.shape[2]
                kd = jnp.repeat(kd, hq // h, 1).astype(jnp.float32)
                vd = jnp.repeat(vd, hq // h, 1).astype(jnp.float32)
                logits = jnp.einsum(
                    "bhd,bhtd->bht", q.astype(jnp.float32) / (d ** 0.5), kd)
                valid = jnp.arange(s)[None, None, :] <= cur[:, None, None]
                logits = jnp.where(valid, logits, -1e30)
                logits = logits + tmask.reshape(b, 1, -1)[act, :, :s]
                out = jnp.einsum("bht,bhtd->bhd",
                                 jax.nn.softmax(logits, -1), vd)
            return out.reshape(ba, hq * d).astype(rows.dtype), kc, vc

        out, kc, vc = apply_op(_decode, qkv_a, kc, vc, _op_name="blha_decode")
    else:
        # ---- prefill / mixed: eager per-sequence causal attention -------
        from ....ops.pallas import log_path_once

        log_path_once("blha", "xla_prefill")
        cu = _np.zeros(b + 1, _np.int64)
        _np.cumsum(this, out=cu[1:])

        def _prefill(qkv_a, kc, vc):
            outs = []
            for i in range(b):
                t = int(this[i])
                if t == 0:
                    continue
                q, k, v = _split_qkv(qkv_a[int(cu[i]): int(cu[i]) + t])
                start = int(dec[i])
                pos = jnp.arange(start, start + t)
                if use_rope:
                    q, k = _rope_at(q, pos, i), _rope_at(k, pos, i)
                pids = tables[i, (_np.arange(start, start + t) // bsz)]
                offs = jnp.asarray(_np.arange(start, start + t) % bsz)
                kc = kc.at[pids, :, offs, :].set(k.astype(kc.dtype))
                vc = vc.at[pids, :, offs, :].set(v.astype(vc.dtype))
                # causal attention over this sequence's full cache
                total = start + t
                npg = (total + bsz - 1) // bsz
                kseq = jnp.concatenate(
                    [kc[tables[i, pg]] for pg in range(npg)], axis=1)[:, :total]
                vseq = jnp.concatenate(
                    [vc[tables[i, pg]] for pg in range(npg)], axis=1)[:, :total]
                if hq != h:                                  # GQA repeat
                    kseq = jnp.repeat(kseq, hq // h, axis=0)
                    vseq = jnp.repeat(vseq, hq // h, axis=0)
                logits = jnp.einsum(
                    "thd,hxd->htx", q.astype(jnp.float32) / (d ** 0.5),
                    kseq.astype(jnp.float32))
                qpos = pos[None, :, None]
                kpos = jnp.arange(total)[None, None, :]
                logits = jnp.where(kpos <= qpos, logits, -1e30)
                if pmask is not None:
                    logits = logits + pmask[i, 0][start:start + t, :total][None]
                probs = jax.nn.softmax(logits, -1)
                o = jnp.einsum("htx,hxd->thd", probs, vseq.astype(jnp.float32))
                outs.append(o.reshape(t, hq * d).astype(qkv_a.dtype))
            return jnp.concatenate(outs, axis=0), kc, vc

        out, kc, vc = apply_op(_prefill, qkv_a, kc, vc,
                               _op_name="blha_prefill")

    from ....core.tensor import Tensor as _T

    def _wrap(x):
        return x if isinstance(x, _T) else _T(x)

    return _wrap(out), qkv, _wrap(kc), _wrap(vc)

from .fp8 import fp8_gemm, fp8_linear  # noqa: E402,F401
