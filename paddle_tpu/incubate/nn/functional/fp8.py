"""FP8 (e4m3) matmul path with per-tensor dynamic scales.

Capability slot: the reference's fp8 gemm fusion kernels
(``phi/kernels/fusion/fp8_gemm/``). TPU-native form: quantise both
operands to ``float8_e4m3fn`` with per-tensor absmax scales and let the
MXU run the narrow matmul (fp8 ops double the MXU rate on fp8-capable
TPUs; on older chips XLA upcasts, keeping the path portable). The
backward runs in the ORIGINAL dtype (bf16/fp32) through a custom_vjp —
the standard fp8-training recipe (forward narrow, gradients wide).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op

E4M3_MAX = 448.0


def _quantize(a):
    """Per-tensor absmax scaling into e4m3. Returns (q, scale)."""
    amax = jnp.max(jnp.abs(a.astype(jnp.float32)))
    scale = jnp.maximum(amax / E4M3_MAX, 1e-12)
    q = (a.astype(jnp.float32) / scale).astype(jnp.float8_e4m3fn)
    return q, scale


@jax.custom_vjp
def _fp8_matmul(x, w):
    qx, sx = _quantize(x)
    qw, sw = _quantize(w)
    out = jnp.matmul(qx, qw, preferred_element_type=jnp.float32)
    return (out * (sx * sw)).astype(x.dtype)


def _fp8_fwd(x, w):
    return _fp8_matmul(x, w), (x, w)


def _fp8_bwd(res, g):
    x, w = res
    # wide backward: dgrad/wgrad precision limits fp8 training far more
    # than the forward does
    gw = g.astype(jnp.float32)
    dx = jnp.matmul(gw, jnp.swapaxes(w.astype(jnp.float32), -1, -2))
    xw = x.astype(jnp.float32)
    x2 = xw.reshape(-1, xw.shape[-1])
    g2 = gw.reshape(-1, gw.shape[-1])
    dw = jnp.matmul(x2.T, g2)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_fp8_matmul.defvjp(_fp8_fwd, _fp8_bwd)


def fp8_gemm(x, y, transpose_x=False, transpose_y=False, name=None):
    """FP8 (e4m3) matmul: ``x @ y`` with per-tensor dynamic scales on both
    operands and a wide (fp32-accumulated) backward.

    x: [..., M, K] (2D+); y: [K, N]. transpose flags mirror paddle.matmul.
    """
    def _run(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        return _fp8_matmul(a, b)

    return apply_op(_run, x, y, _op_name="fp8_gemm")


def fp8_linear(x, weight, bias=None, name=None):
    """Linear layer forward on the fp8 path: ``x @ W (+ b)``."""
    def _run(a, w, b):
        out = _fp8_matmul(a, w)
        if b is not None:
            out = out + b
        return out

    return apply_op(_run, x, weight, bias, _op_name="fp8_linear")
