"""FP8 (e4m3) matmul path with per-tensor dynamic scales.

Capability slot: the reference's fp8 gemm fusion kernels
(``phi/kernels/fusion/fp8_gemm/``). TPU-native form: quantise both
operands to ``float8_e4m3fn`` with per-tensor absmax scales and let the
MXU run the narrow matmul (fp8 ops double the MXU rate on fp8-capable
TPUs; on older chips XLA upcasts, keeping the path portable). The
backward runs in the ORIGINAL dtype (bf16/fp32) through a custom_vjp —
the standard fp8-training recipe (forward narrow, gradients wide).

The numerics live in :mod:`paddle_tpu.quant.gemm` — one shared quantizer
implementation (the int8-head discipline): this module keeps only the
paddle-flavoured ``apply_op`` entry points, the scale-clamp epsilon is the
repo-wide ``memory.SCALE_EPS``, and the per-call inline absmax is the
shared delayed-scaling core run with an empty history (it bootstraps from
the current step's amax, which *is* the inline recipe).
"""
from __future__ import annotations

import jax.numpy as jnp

from ....core.dispatch import apply_op
from ....quant.gemm import E4M3_MAX, inline_scaled_gemm  # noqa: F401


def _fp8_matmul(x, w):
    return inline_scaled_gemm(x, w, dtype="fp8")


def fp8_gemm(x, y, transpose_x=False, transpose_y=False, name=None):
    """FP8 (e4m3) matmul: ``x @ y`` with per-tensor dynamic scales on both
    operands and a wide (fp32-accumulated) backward.

    x: [..., M, K] (2D+); y: [K, N]. transpose flags mirror paddle.matmul.
    """
    def _run(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        return _fp8_matmul(a, b)

    return apply_op(_run, x, y, _op_name="fp8_gemm")


def fp8_linear(x, weight, bias=None, name=None):
    """Linear layer forward on the fp8 path: ``x @ W (+ b)``."""
    def _run(a, w, b):
        out = _fp8_matmul(a, w)
        if b is not None:
            out = out + b
        return out

    return apply_op(_run, x, weight, bias, _op_name="fp8_linear")
