"""incubate.nn fused layers (parity: python/paddle/incubate/nn/layer/*).

On TPU the "fusion" is the compiler's: these layers express the same math
as straight-line jnp that XLA fuses into the surrounding matmuls; the
attention core rides the Pallas flash kernel via nn.functional.
"""
from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.incubate.nn import functional as FF


class FusedLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_features], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x):
        return FF.fused_linear(x, self.weight, self.bias,
                               self.transpose_weight)


class FusedDropoutAdd(nn.Layer):
    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return FF.fused_dropout_add(x, y, self.p, self.training, self.mode)


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        h = F.dropout(x + self.linear_bias, self.dropout_rate,
                      training=self.training)
        return F.layer_norm(h + residual, [h.shape[-1]], self.ln_scale,
                            self.ln_bias, self.epsilon)


class FusedMultiHeadAttention(nn.Layer):
    """parity: incubate/nn/layer/fused_transformer.py FusedMultiHeadAttention."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        one = nn.initializer.Constant(1.0)
        self.qkv_weight = self.create_parameter([embed_dim, 3 * embed_dim])
        self.qkv_bias = self.create_parameter([3 * embed_dim], is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim])
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.pre_ln_scale = self.create_parameter([embed_dim],
                                                  default_initializer=one)
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter([embed_dim],
                                              default_initializer=one)
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, query, attn_mask=None, cache=None):
        x = query
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self.epsilon)
        b, s, _ = x.shape
        hd = self.embed_dim // self.num_heads
        qkv = x.matmul(self.qkv_weight) + self.qkv_bias
        q, k, v = paddle.split(qkv, 3, axis=-1)
        q = q.reshape([b, s, self.num_heads, hd])
        k = k.reshape([b, s, self.num_heads, hd])
        v = v.reshape([b, s, self.num_heads, hd])
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, is_causal=False,
            training=self.training)
        out = out.reshape([b, s, self.embed_dim])
        out = out.matmul(self.linear_weight) + self.linear_bias
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], self.ln_scale,
                               self.ln_bias, self.epsilon)
        return out


class FusedFeedForward(nn.Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        one = nn.initializer.Constant(1.0)
        self.linear1_weight = self.create_parameter([d_model, dim_feedforward])
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter([dim_feedforward, d_model])
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        self.ln_scale = self.create_parameter([d_model],
                                              default_initializer=one)
        self.ln_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        residual = src
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, [self.d_model], self.ln_scale, self.ln_bias,
                             self.epsilon)
        act = getattr(F, self.activation)
        h = act(x.matmul(self.linear1_weight) + self.linear1_bias)
        h = F.dropout(h, self.act_dropout_rate, training=self.training)
        h = h.matmul(self.linear2_weight) + self.linear2_bias
        h = F.dropout(h, self.dropout_rate, training=self.training)
        out = residual + h
        if not self.normalize_before:
            out = F.layer_norm(out, [self.d_model], self.ln_scale,
                               self.ln_bias, self.epsilon)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(nn.Layer):
    """Stacked fused decoder layers (inference-style API)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1, nranks=1,
                 ring_id=-1, name=None, **kw):
        super().__init__()
        self.layers = nn.LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward, dropout_rate,
                activation, normalize_before=normalize_before)
            for _ in range(num_layers)
        ])

    def forward(self, src, attn_mask=None, caches=None, **kw):
        x = src
        for layer in self.layers:
            x = layer(x, src_mask=attn_mask)
        return x
