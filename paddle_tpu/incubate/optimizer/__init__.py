"""paddle.incubate.optimizer — LBFGS graduated into paddle.optimizer."""
from ...optimizer import LBFGS  # noqa: F401

__all__ = ["LBFGS"]
