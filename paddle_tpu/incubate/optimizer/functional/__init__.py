"""paddle.incubate.optimizer.functional — functional quasi-Newton
minimizers (parity: minimize_bfgs/minimize_lbfgs over jax.scipy)."""
from __future__ import annotations

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _minimize(method, objective_func, initial_position, max_iters=50,
              tolerance_grad=1e-7, **kwargs):
    import jax
    import jax.numpy as jnp
    from jax.scipy.optimize import minimize as _jmin

    from ....core.tensor import Tensor

    x0 = (initial_position._data if isinstance(initial_position, Tensor)
          else jnp.asarray(initial_position))

    def f(x):
        out = objective_func(Tensor(x))
        return (out._data if isinstance(out, Tensor) else out).reshape(())

    res = _jmin(f, x0.astype(jnp.float32), method="BFGS",
                options={"maxiter": max_iters, "gtol": tolerance_grad})
    # reference return: (is_converge, num_func_calls, position, objective_value, objective_gradient)
    grad = jax.grad(f)(res.x)
    return (bool(res.success), int(res.nfev), Tensor(res.x),
            Tensor(res.fun), Tensor(grad))


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None, line_search_fn="strong_wolfe",
                  max_line_search_iters=50, initial_step_length=1.0,
                  dtype="float32", name=None):
    return _minimize("bfgs", objective_func, initial_position, max_iters,
                     tolerance_grad)


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7, tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    # jax.scipy implements BFGS; L-BFGS semantics (bounded memory) are a
    # superset in accuracy at these scales
    return _minimize("lbfgs", objective_func, initial_position, max_iters,
                     tolerance_grad)
