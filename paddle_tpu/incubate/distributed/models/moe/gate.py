"""Gate submodule alias (parity: incubate/distributed/models/moe/gate/)."""
from . import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
