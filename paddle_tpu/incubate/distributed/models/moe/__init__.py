"""Mixture-of-Experts with expert parallelism — TPU-native.

Capability parity: reference `python/paddle/incubate/distributed/models/moe/
moe_layer.py:261` (fastmoe-style MoELayer over global_scatter/global_gather
NCCL all-to-all) and the gates under `moe/gate/`.

TPU-first redesign: routing is GShard-style DENSE dispatch — one-hot
dispatch/combine tensors contracted with einsum, so the whole layer is
three MXU matmul groups (gate, dispatch, combine) plus the expert FFNs,
all inside one XLA program. Expert parallelism is a sharding, not a
communication pattern: stacked expert params are Shard(0) over the chosen
mesh axis and the [E, C, M] dispatch buffer carries the same constraint —
GSPMD inserts the all-to-all over ICI (replacing global_scatter/gather).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.dispatch import apply_op
from paddle_tpu.core.tensor import Tensor


class BaseGate(nn.Layer):
    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss


class NaiveGate(BaseGate):
    """Top-k softmax gate, no aux loss (moe/gate/naive_gate.py:28)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores=False):
        gate = self.gate(inp)
        val, idx = paddle.topk(gate, k=self.top_k, axis=-1)
        if return_all_scores:
            return val, idx, gate
        return val, idx


class GShardGate(BaseGate):
    """Top-2 gate with load-balance aux loss (moe/gate/gshard_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        super().__init__(num_expert, world_size)
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = topk
        self.capacity = capacity

    def forward(self, x):
        logits = self.gate(x)
        val, idx = paddle.topk(logits, k=self.top_k, axis=-1)

        def _aux(lg, top_idx):
            probs = jax.nn.softmax(lg.astype(jnp.float32), axis=-1)
            e = lg.shape[-1]
            me = jnp.mean(probs.reshape(-1, e), axis=0)
            ce = jnp.mean(
                jax.nn.one_hot(top_idx[..., 0].reshape(-1), e), axis=0
            )
            return jnp.sum(me * ce) * float(e)

        self.set_loss(apply_op(_aux, logits, idx, _op_name="gshard_aux"))
        return val, idx


class SwitchGate(BaseGate):
    """Top-1 switch-transformer gate with aux loss (moe/gate/switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(num_expert, world_size)
        assert topk == 1, "switch gate is top-1"
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = 1
        self.switch_eps = switch_eps

    def forward(self, x):
        logits = self.gate(x)
        if self.training:
            noise = paddle.rand(logits.shape)
            logits = logits + (noise * 2.0 - 1.0) * self.switch_eps
        val, idx = paddle.topk(logits, k=1, axis=-1)

        def _aux(lg, top_idx):
            probs = jax.nn.softmax(lg.astype(jnp.float32), axis=-1)
            e = lg.shape[-1]
            me = jnp.mean(probs.reshape(-1, e), axis=0)
            ce = jnp.mean(jax.nn.one_hot(top_idx.reshape(-1), e), axis=0)
            return jnp.sum(me * ce) * float(e)

        self.set_loss(apply_op(_aux, logits, idx, _op_name="switch_aux"))
        return val, idx


def _dense_dispatch_combine(x, idx, val, num_expert, capacity):
    """GShard dense dispatch on arrays.

    x [N, M], idx [N, k] int, val [N, k] gate scores. Returns
    (expert_inputs [E, C, M], combine [N, E, C]).
    """
    n, m = x.shape
    k = idx.shape[-1]
    probs = jax.nn.softmax(val.astype(jnp.float32), axis=-1)

    onehot = jax.nn.one_hot(idx, num_expert, dtype=jnp.float32)  # [N, k, E]
    # position of each (token, slot) in its expert's buffer; k=0 first
    flat = jnp.swapaxes(onehot, 0, 1).reshape(k * n, num_expert)
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # [k*N, E]
    pos = jnp.swapaxes(pos_flat.reshape(k, n, num_expert), 0, 1)  # [N,k,E]
    pos = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [N, k]
    keep = pos < capacity
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [N, k, C]
    disp = jnp.einsum("nke,nkc->nkec", onehot,
                      pos_oh * keep[..., None].astype(jnp.float32))
    dispatch = jnp.sum(disp, axis=1)  # [N, E, C]
    combine = jnp.sum(disp * probs[..., None, None], axis=1)  # [N, E, C]
    expert_inputs = jnp.einsum("nec,nm->ecm", dispatch, x.astype(jnp.float32))
    return expert_inputs.astype(x.dtype), combine.astype(x.dtype)


class MoELayer(nn.Layer):
    """parity: moe_layer.py:261 MoELayer(d_model, experts, gate, ...).

    experts: LayerList of expert Layers (each maps [C, M] -> [C, M']), or a
    single Layer applied per-expert slice. capacity_factor bounds tokens
    per expert; overflow tokens are dropped (their combine weight is 0),
    matching GShard semantics.
    ep_axis: mesh axis to shard experts over (expert parallelism); None
    leaves placement to GSPMD via the expert parameters' shardings.
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, recompute_ctx=None,
                 capacity_factor=2.0, ep_axis=None):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, (list, tuple)):
            experts = nn.LayerList(list(experts))
        self.experts = experts
        self.num_expert = len(experts)
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis

        if gate is None:
            gate = {"type": "gshard", "top_k": 2}
        if isinstance(gate, dict):
            typ = gate.get("type", "gshard")
            topk = gate.get("top_k", 2)
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[typ]
            gate = cls(d_model, self.num_expert, topk=topk)
        self.gate = gate
        self.l_aux = None

    def _capacity(self, n_tokens):
        k = self.gate.top_k
        return max(
            1, int(math.ceil(self.capacity_factor * k * n_tokens
                             / self.num_expert))
        )

    def forward(self, x):
        shape = x.shape
        m = shape[-1]
        flat = x.reshape([-1, m])
        n = flat.shape[0]
        cap = self._capacity(int(n))

        val, idx = self.gate(flat)
        self.l_aux = self.gate.get_loss(clear=True)

        ep_axis = self.ep_axis

        def _dispatch(xa, idxa, vala):
            ei, comb = _dense_dispatch_combine(
                xa, idxa, vala, self.num_expert, cap
            )
            if ep_axis is not None:
                from paddle_tpu.distributed.auto_parallel import get_mesh
                from paddle_tpu.distributed.spmd_rules import (
                    DistTensorSpec,
                    constrain,
                    constraints_enabled,
                )

                mesh = get_mesh()
                if (
                    mesh is not None
                    and ep_axis in mesh.dim_names
                    and constraints_enabled()
                ):
                    # spmd rule `moe_dispatch`: expert dim over ep, tokens
                    # contributed via all_to_all (spmd_rules.py)
                    ei = constrain(
                        "moe_dispatch",
                        mesh,
                        ei,
                        DistTensorSpec(list(xa.shape), [-1] * xa.ndim),
                        ep_mesh_dim=mesh.dim_names.index(ep_axis),
                    )
            return ei, comb

        expert_inputs, combine = apply_op(
            _dispatch, flat, idx, val, _op_name="moe_dispatch"
        )

        if isinstance(self.experts, StackedExperts):
            stacked = self.experts(expert_inputs)  # [E, C, M']
        else:
            outs = []
            for e in range(self.num_expert):
                outs.append(self.experts[e](expert_inputs[e]))
            stacked = paddle.stack(outs, axis=0)  # [E, C, M']

        def _combine(comb, ys):
            return jnp.einsum("nec,ecm->nm", comb.astype(jnp.float32),
                              ys.astype(jnp.float32)).astype(ys.dtype)

        out = apply_op(_combine, combine, stacked, _op_name="moe_combine")
        return out.reshape(list(shape[:-1]) + [stacked.shape[-1]])


class StackedExperts(nn.Layer):
    """All expert FFNs as leading-axis-stacked parameters [E, ...].

    The expert-parallel form: every expert weight is one tensor whose
    leading axis shards over the ep mesh axis, the per-expert FFN is a
    batched einsum on the MXU, and GSPMD turns the dispatch buffer's
    sharding mismatch into the all-to-all. Equivalent capability to
    fastmoe's per-rank expert placement — without MPMD.
    """

    def __init__(self, num_expert, d_model, d_hidden, act="gelu"):
        super().__init__()
        from paddle_tpu.nn.initializer import Constant, Normal

        w = lambda *s: self.create_parameter(
            list(s), default_initializer=Normal(std=0.02))
        zero = Constant(0.0)
        self.num_expert = num_expert
        self.act = act
        self.w1 = w(num_expert, d_model, d_hidden)
        self.b1 = self.create_parameter([num_expert, 1, d_hidden],
                                        default_initializer=zero)
        self.w2 = w(num_expert, d_hidden, d_model)
        self.b2 = self.create_parameter([num_expert, 1, d_model],
                                        default_initializer=zero)

    def __len__(self):
        return self.num_expert

    def forward(self, expert_inputs):  # [E, C, M] -> [E, C, M]
        act = self.act

        def _ffn(x, w1, b1, w2, b2):
            h = jnp.einsum("ecm,emh->ech", x, w1) + b1
            h = jax.nn.gelu(h) if act == "gelu" else jax.nn.relu(h)
            return jnp.einsum("ech,ehm->ecm", h, w2) + b2

        return apply_op(_ffn, expert_inputs, self.w1, self.b1, self.w2,
                        self.b2, _op_name="stacked_experts")

    def apply_ep_placements(self, mesh, axis="dp"):
        """Shard the expert axis over `axis` (expert parallelism)."""
        from paddle_tpu.distributed.auto_parallel import (
            Replicate, Shard, TensorDistAttr)

        ax_idx = mesh.dim_names.index(axis)
        for _, p in self.named_parameters():
            placements = [Replicate() for _ in mesh.dim_names]
            placements[ax_idx] = Shard(0)
            p._dist_attr = TensorDistAttr(mesh, placements)
        return self


def shard_expert_parameters(moe_layer: MoELayer, mesh, axis="dp"):
    """Enable expert parallelism on a MoELayer built over StackedExperts."""
    if not isinstance(moe_layer.experts, StackedExperts):
        raise ValueError(
            "expert parallelism needs StackedExperts (per-expert LayerLists "
            "cannot be placement-sharded under SPMD); replicated execution "
            "is still correct without it"
        )
    if moe_layer.num_expert % mesh.get_dim_size(axis) != 0:
        raise ValueError("num_expert must divide the ep axis size")
    moe_layer.experts.apply_ep_placements(mesh, axis)
    moe_layer.ep_axis = axis
    return moe_layer
