"""namespace package"""
