"""paddle.incubate.distributed.fleet — recompute wrappers."""
from ....distributed.fleet.utils import recompute as _recompute

__all__ = ["recompute_sequential", "recompute_hybrid"]


def recompute_sequential(ctx, functions, *args, **kwargs):
    """RUN `functions` (a Sequential or list of layers) over args with
    per-segment recompute; returns the output (incubate recompute.py:649
    contract)."""
    segments = int((ctx or {}).get("segments", 1))
    layers = list(functions)
    if segments <= 1:
        chunks = [layers]
    else:
        k = max(1, len(layers) // segments)
        chunks = [layers[i:i + k] for i in range(0, len(layers), k)]
    out = args[0] if len(args) == 1 else args
    for chunk in chunks:
        def seg(h, _chunk=chunk):
            for lay in _chunk:
                h = lay(h)
            return h

        out = _recompute(seg, out, **kwargs)
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Recompute under hybrid parallel (mp-aware rng is handled by the
    fleet recompute already)."""
    return _recompute(function, *args, **kwargs)
