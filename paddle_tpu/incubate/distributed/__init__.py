"""namespace package"""
