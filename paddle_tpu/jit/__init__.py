"""paddle.jit — trace-to-XLA compilation (parity: python/paddle/jit).

The reference captures python bytecode (SOT eval-frame hook, §3.6 of the
survey) and compiles the captured graph through CINN.  The TPU-native design
replaces that whole pipeline with jax tracing: because every eager op is a
pure jax function over the Tensor's payload, running a Layer's forward with
tracer payloads *is* the capture.  ``to_static`` wraps a Layer as a pure
function of (parameters, buffers, inputs) and hands it to ``jax.jit``;
``TrainStep`` compiles forward+backward+optimizer into one donated-buffer XLA
program — the analogue of the reference's whole-graph `pir_partial_program`
plus CINN, with XLA doing fusion/scheduling.
"""
from __future__ import annotations

import functools
import time as _time

import jax
import jax.numpy as jnp
from jax import tree_util

from .. import framework
from .. import telemetry as _telemetry
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

_TRAIN_STEP_SECONDS = _telemetry.histogram(
    "train_step_seconds",
    "TrainStep dispatch wall time (async under jit: device sync excluded)",
    labelnames=("model",))
_TRAIN_STEPS = _telemetry.counter(
    "train_steps_total", "TrainStep invocations", labelnames=("model",))

# -- compile-phase telemetry (docs/TELEMETRY.md, docs/SCAN.md) --------------
# Wall seconds of the newest program build, split by phase, plus the
# serialized HLO module size — the measurement behind the scan-over-layers
# "compile time and program size flat in depth" claim (bench.py "compile"
# block; tools/bench_gate.py gates regressions).
_TRACE_SECONDS = _telemetry.gauge(
    "trace_seconds", "jax tracing wall seconds of the newest program "
    "build for this function", labelnames=("function",))
_LOWER_SECONDS = _telemetry.gauge(
    "lower_seconds", "StableHLO lowering wall seconds of the newest "
    "program build for this function", labelnames=("function",))
_COMPILE_SECONDS = _telemetry.gauge(
    "compile_seconds", "XLA backend-compile wall seconds of the newest "
    "program build for this function", labelnames=("function",))
_HLO_PROGRAM_BYTES = _telemetry.gauge(
    "hlo_program_bytes", "serialized HLO module size (bytes) of the "
    "newest compiled program for this function", labelnames=("function",))

#: newest per-function phase record: {label: {"trace_seconds": ..,
#: "lower_seconds": .., "compile_seconds": .., "hlo_program_bytes": ..}}
_LAST_COMPILE = {}


def _device_peaks():
    """(peak_flops, peak_bytes_per_sec, placeholder?) for device 0 —
    the roofline denominators behind the dispatch-span cost attrs and
    the bench anatomy's cost-analysis MFU. bf16 peak per chip / HBM
    bandwidth from the public chip tables; unknown kinds and CPU dev
    runs get placeholder numbers flagged as such (the host-overhead
    bench gate only engages on non-placeholder estimates)."""
    try:
        d = jax.devices()[0]
        kind = d.device_kind.lower()
        platform = d.platform
    except Exception:
        return 1e12, 100e9, True
    if platform == "cpu":
        return 1e12, 100e9, True
    if "v5p" in kind:
        return 459e12, 2765e9, False
    if "v5e" in kind or "v5 lite" in kind or "v5" == kind:
        return 197e12, 819e9, False
    if "v4" in kind:
        return 275e12, 1228e9, False
    if "v6" in kind or "trillium" in kind:
        return 918e12, 1640e9, False
    return 197e12, 819e9, True


def compiled_cost_summary(compiled):
    """``compiled.cost_analysis()`` distilled to the anatomy contract:
    {"flops", "bytes_accessed", "device_seconds_est" (roofline:
    max(flops/peak_flops, bytes/peak_bw)), "peak_flops",
    "peak_bytes_per_sec", "peak_model_placeholder"} — or None when the
    executable exposes no cost analysis (plain jit dispatch
    fallback)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    pf, pb, placeholder = _device_peaks()
    return {
        "flops": flops,
        "bytes_accessed": nbytes,
        "device_seconds_est": max(flops / pf, nbytes / pb),
        "peak_flops": pf,
        "peak_bytes_per_sec": pb,
        "peak_model_placeholder": bool(placeholder),
    }


def _traced_dispatch(ex, label, cost, op_args):
    """Run one compiled dispatch, recording a ``dispatch`` span with the
    program's cost-analysis attrs when tracing is on: flops, bytes, the
    roofline device-seconds estimate, the per-call MFU estimate
    (flops / wall / peak) and host_gap = wall − device estimate (async
    dispatch can legitimately clamp it to 0). Plain call when the
    tracer is disabled — the hot path pays one attribute check."""
    tr = _telemetry.trace
    if not tr.enabled():
        return ex(*op_args)
    t0 = _time.perf_counter()
    out = ex(*op_args)
    dt = _time.perf_counter() - t0
    attrs = {"function": label}
    if cost:
        dev = cost["device_seconds_est"]
        attrs.update(
            flops=cost["flops"], bytes_accessed=cost["bytes_accessed"],
            device_seconds_est=round(dev, 6),
            host_gap_seconds=round(max(0.0, dt - dev), 6))
        # per-call MFU only when the wall time plausibly COVERED the
        # device work (dt >= roofline estimate): under async dispatch
        # the call returns in enqueue time and flops/wall would
        # overstate MFU by orders of magnitude — exactly on the TPU
        # runs the attr targets. Those runs read the per-STEP cost_mfu
        # in the bench anatomy block instead.
        if dt >= dev > 0.0 and not cost["peak_model_placeholder"]:
            attrs["mfu_est"] = round(
                cost["flops"] / (dt * cost["peak_flops"]), 4)
    tr.complete("dispatch", t0, dt, attrs, cat="jit")
    return out


def _serialized_hlo_bytes(lowered):
    """Size of the lowered program: serialized HLO proto when this
    jax/jaxlib exposes it, StableHLO text length otherwise (both are
    monotone in program size, which is what the depth-sweep asserts)."""
    try:
        return len(lowered.compiler_ir(
            dialect="hlo").as_serialized_hlo_module_proto())
    except Exception:
        try:
            return len(lowered.as_text())
        except Exception:
            return 0


def _record_compile_phases(label, trace_s, lower_s, compile_s, hlo_bytes):
    labels = (label,)
    _TRACE_SECONDS.set(trace_s, labels=labels)
    _LOWER_SECONDS.set(lower_s, labels=labels)
    _COMPILE_SECONDS.set(compile_s, labels=labels)
    _HLO_PROGRAM_BYTES.set(hlo_bytes, labels=labels)
    _LAST_COMPILE[label] = {
        "trace_seconds": trace_s, "lower_seconds": lower_s,
        "compile_seconds": compile_s, "hlo_program_bytes": hlo_bytes}


def compile_summary(label=None):
    """Newest compile-phase record for ``label`` (None = all labels):
    the bench "compile" block's data source. Returns None for an
    unknown label."""
    if label is None:
        return {k: dict(v) for k, v in _LAST_COMPILE.items()}
    rec = _LAST_COMPILE.get(label)
    return dict(rec) if rec is not None else None


def timed_lower_compile(jitfn, label, *args, **kwargs):
    """AOT trace -> lower -> compile of a ``jax.jit`` function, feeding
    the per-phase gauges. Returns the Compiled executable (same program
    jit dispatch would build — donation and shardings preserved)."""
    t0 = _time.perf_counter()
    traced = None
    if hasattr(jitfn, "trace"):
        try:
            traced = jitfn.trace(*args, **kwargs)
        except TypeError as e:
            # only a .trace() CALLING-convention mismatch falls back to
            # .lower(); genuine trace-time errors (TracerBoolConversion
            # et al. subclass TypeError via JAXTypeError) must propagate
            # — re-tracing through .lower() just to re-raise them would
            # double the trace cost of every graph-breaking call
            if isinstance(e, jax.errors.JAXTypeError):
                raise
            traced = None
    if traced is not None:
        t1 = _time.perf_counter()
        lowered = traced.lower()
    else:  # older jax: .lower() fuses trace+lower; report it as lower
        t1 = t0
        lowered = jitfn.lower(*args, **kwargs)
    t2 = _time.perf_counter()
    compiled = lowered.compile()
    t3 = _time.perf_counter()
    hlo_bytes = _serialized_hlo_bytes(lowered)
    _record_compile_phases(label, t1 - t0, t2 - t1, t3 - t2, hlo_bytes)
    tr = _telemetry.trace
    if tr.enabled():
        # the three build phases as spans so a trace shows WHERE a cold
        # start went (compile churn shows as repeated jit:* triplets)
        attrs = {"function": label}
        tr.complete("jit:trace", t0, t1 - t0, dict(attrs), cat="jit")
        tr.complete("jit:lower", t1, t2 - t1, dict(attrs), cat="jit")
        tr.complete("jit:compile", t2, t3 - t2,
                    dict(attrs, hlo_program_bytes=hlo_bytes), cat="jit")
    return compiled


def _wrap_arrays(tree):
    return tree_util.tree_map(lambda a: Tensor(a), tree)


def _unwrap_tensors(tree):
    return tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t,
        tree,
        is_leaf=lambda x: isinstance(x, Tensor),
    )


def functional_call(layer: Layer, state: dict, *args, **kwargs):
    """Run `layer` as a pure function of `state` (name -> array).

    Returns (outputs_pytree_of_arrays, mutated_state_dict)."""
    with layer._swap_state(state) as mutated:
        with framework.no_grad():
            wrapped_args = _wrap_arrays(args)
            wrapped_kwargs = _wrap_arrays(kwargs)
            out = layer(*wrapped_args, **wrapped_kwargs)
    return _unwrap_tensors(out), mutated


class StaticFunction:
    """Compiled wrapper around a Layer or a pure tensor function.

    Guards (reference: jit/sot guard.py semantics): the compiled-program
    cache is keyed on (training, input shapes, input dtypes) — a shape or
    dtype change triggers a retrace instead of running a stale program.
    Graph breaks (reference: SOT graph-break fallback): data-dependent
    Python control flow raises a jax concretization error during tracing;
    the call falls back to eager for that invocation with a one-time
    warning instead of a hard failure.
    """

    def __init__(self, function, input_spec=None,
                 bucket_dynamic_shapes=False, **kwargs):
        if isinstance(function, Layer):
            self._layer = function
            self._fn = None
        else:
            self._layer = getattr(function, "__self__", None)
            self._fn = function
        self._input_spec = input_spec
        target = self._fn if self._fn is not None else self._layer
        # invariant per StaticFunction: computed once, not per dispatch
        self._dispatch_label = (getattr(target, "__qualname__", None)
                                or type(target).__name__)
        # LRU-bounded program cache: value guards key on python scalars
        # (below), so a Layer that mutates a fresh scalar every call
        # (self.calls += 1 in forward) would otherwise grow this dict
        # without bound while retracing per call — correct (the old
        # behavior silently reused a stale program) but it must not
        # leak. 32 programs covers shape buckets x a few guard states.
        import collections

        self._compiled = collections.OrderedDict()
        self._compiled_cap = 32
        self._fallback_warned = False
        # dynamic-dim bucketing (SURVEY hard-part 6): dims declared
        # None/-1 in input_spec are padded up to the next power of two, so
        # a stream of varying lengths costs O(log) compilations instead of
        # one per shape. Opt-in: padding changes values for ops that
        # reduce over the padded region — the caller owns masking, exactly
        # like the reference's dynamic-shape dy2st deployments pad inputs.
        self._bucket_axes = None
        self._bucket_kw = None
        if bucket_dynamic_shapes and input_spec is not None:
            from ..static import InputSpec

            axes, kw = [], {}
            for spec in (input_spec if isinstance(input_spec, (list, tuple))
                         else [input_spec]):
                if isinstance(spec, InputSpec):
                    dyn = tuple(i for i, d in enumerate(spec.shape)
                                if d is None or d == -1)
                    axes.append(dyn)
                    # NAMED specs additionally bucket same-named kwargs
                    if getattr(spec, "name", None):
                        kw[spec.name] = dyn
                else:
                    axes.append(())
            self._bucket_axes = axes
            self._bucket_kw = kw

    @staticmethod
    def _next_bucket(n):
        b = 8
        while b < n:
            b *= 2
        return b

    def _bucketize(self, raw_args):
        if self._bucket_axes is None:
            return raw_args
        import numpy as _np

        out = []
        for i, a in enumerate(raw_args):
            axes = (self._bucket_axes[i]
                    if i < len(self._bucket_axes) else ())
            if axes and hasattr(a, "shape"):
                a = self._pad_to_buckets(a, axes)
            out.append(a)
        return tuple(out)

    def _pad_to_buckets(self, a, axes):
        import numpy as _np

        pad = [(0, 0)] * a.ndim
        needs = False
        for ax in axes:
            tgt = self._next_bucket(a.shape[ax])
            if tgt != a.shape[ax]:
                pad[ax] = (0, tgt - a.shape[ax])
                needs = True
        if not needs:
            return a
        return (_np.pad(a, pad) if isinstance(a, _np.ndarray)
                else jnp.pad(a, pad))

    def _bucketize_kwargs(self, raw_kwargs):
        """Bucket keyword tensors through their NAMED InputSpecs."""
        if self._bucket_axes is None or not raw_kwargs:
            return raw_kwargs
        out = {}
        for k, v in raw_kwargs.items():
            axes = (self._bucket_kw or {}).get(k, ())
            if hasattr(v, "shape") and v.ndim >= 1:
                if axes:
                    v = self._pad_to_buckets(v, axes)
                elif k not in (self._bucket_kw or {}):
                    raise ValueError(
                        "bucket_dynamic_shapes: tensor keyword argument "
                        f"{k!r} has no matching NAMED InputSpec — name the "
                        "spec (InputSpec(shape, name=...)) or pass the "
                        "tensor positionally")
            elif k not in (self._bucket_kw or {}) and any(
                    hasattr(leaf, "shape")
                    for leaf in tree_util.tree_leaves(v)):
                # tensors hidden in containers can't be bucketed — raise
                # loudly rather than silently recompiling per shape
                raise ValueError(
                    "bucket_dynamic_shapes: keyword argument "
                    f"{k!r} contains tensors inside a container — pass "
                    "them as named top-level arguments so they can be "
                    "padded to their bucket")
            out[k] = v
        return out

    _GUARD_SCALARS = (bool, int, float, str, bytes, type(None))

    def _value_guard_sig(self):
        """Python-state value guards (reference: jit/sot guard.py —
        guards on object attributes and closure cells read by the traced
        frame). A trace bakes python scalars into the program
        (`if self.use_cache:`, a closed-over scale float), so the cache
        key must carry them: the cheap 90% is every scalar attribute on
        the Layer tree plus the function's scalar closure cells —
        mutating one maps to a NEW key (retrace); restoring it reuses
        the old compiled program."""
        parts = []
        if self._layer is not None:
            # per-call tree walk, deliberately uncached: a sublayer
            # attached AFTER the first call must still be guarded on its
            # scalar mutations (a snapshot would silently reuse stale
            # programs). The generator walk is cheap next to jit dispatch.
            for path, layer in self._layer.named_sublayers(
                    include_self=True):
                for k, v in layer.__dict__.items():
                    if k.startswith("_") or k == "training":
                        continue
                    if isinstance(v, self._GUARD_SCALARS):
                        parts.append((path, k, v))
        fn = self._fn
        if fn is not None:
            try:
                closure = fn.__closure__ or ()
            except AttributeError:
                closure = ()
            for i, cell in enumerate(closure):
                try:
                    v = cell.cell_contents
                except ValueError:
                    continue
                if isinstance(v, self._GUARD_SCALARS):
                    parts.append(("<closure>", i, v))
        return tuple(parts)

    def _trace_key(self, raw_args, raw_kwargs):
        training = self._layer.training if self._layer is not None else False

        def leaf_sig(a):
            if hasattr(a, "shape"):
                return (tuple(a.shape), str(a.dtype))
            if isinstance(a, float):
                # floats trace as values inside the program — keying by
                # value would recompile per lr/scale; key by type only
                return ("<float>",)
            return a  # bools/ints/strings: small value sets, key by value

        sig = tuple(leaf_sig(a)
                    for a in tree_util.tree_leaves((raw_args, raw_kwargs)))
        return (training, sig, self._value_guard_sig())

    def _get_compiled(self, key):
        if key in self._compiled:
            self._compiled.move_to_end(key)
        else:
            while len(self._compiled) >= self._compiled_cap:
                self._compiled.popitem(last=False)
        if key not in self._compiled:
            layer = self._layer
            fn = self._fn
            # jit-cache miss: every new (training, shapes, guards) key is
            # a fresh trace+compile — feed the recompile watchdog with the
            # function identity and the signature it missed on
            target = fn if fn is not None else layer
            _telemetry.record_compile(
                getattr(target, "__qualname__", None)
                or type(target).__name__, key)

            if layer is not None:
                def pure(state, key_arr, args, kwargs):
                    with layer._swap_state(state) as mutated:
                        with framework.no_grad(), framework.rng_key_scope(key_arr):
                            wa = _wrap_arrays(args)
                            wk = _wrap_arrays(kwargs)
                            if fn is not None:
                                out = fn(*wa, **wk)
                            else:
                                out = layer(*wa, **wk)
                    return _unwrap_tensors(out), dict(mutated)

                self._compiled[key] = [jax.jit(pure), None, None]
            else:
                def pure_fn(key_arr, args, kwargs):
                    with framework.no_grad(), framework.rng_key_scope(key_arr):
                        out = fn(*_wrap_arrays(args), **_wrap_arrays(kwargs))
                    return _unwrap_tensors(out)

                self._compiled[key] = [jax.jit(pure_fn), None, None]
        return self._compiled[key]

    def _run_slot(self, slot, *args):
        """Run a compiled-program slot ([jit fn, executable|None, cost]):
        the first call builds the executable through timed_lower_compile
        so the compile-phase gauges (trace/lower/compile seconds +
        hlo_program_bytes, labeled by function) cover to_static programs
        too, and caches the program's cost_analysis summary for the
        dispatch trace span. Graph-break tracer errors propagate to
        __call__'s eager fallback; any other AOT surprise degrades to
        plain jit dispatch."""
        jitfn, ex = slot[0], slot[1]
        label = self._dispatch_label
        if ex is None:
            try:
                ex = timed_lower_compile(jitfn, label, *args)
                slot[2] = compiled_cost_summary(ex)
            except self._GRAPH_BREAK_ERRORS:
                raise
            except Exception:
                ex = jitfn
            slot[1] = ex
        try:
            return _traced_dispatch(ex, label, slot[2], args)
        except (TypeError, ValueError):
            if ex is jitfn:
                raise
            slot[1] = jitfn
            slot[2] = None
            return jitfn(*args)

    _GRAPH_BREAK_ERRORS = (
        jax.errors.TracerBoolConversionError,
        jax.errors.TracerIntegerConversionError,
        jax.errors.TracerArrayConversionError,
        jax.errors.ConcretizationTypeError,
    )

    def _eager_call(self, args, kwargs):
        fn = self._fn if self._fn is not None else self._layer
        import os

        if os.environ.get("PTPU_NO_SEGMENTS"):
            return fn(*args, **kwargs)
        # Partial-graph capture around graph breaks — ops compile as
        # segments (prefix up to the .item()/bool(), host branch, suffix),
        # the SOT-granularity answer (function_graph.py) without bytecode
        # rewriting. Memoized per op-sequence, so steady-state calls reuse
        # the compiled programs. Under grad (training fallback), each
        # flushed segment lands on the tape as ONE GradNode whose vjp runs
        # through the cached jitted program — staged autograd, so a
        # one-.item() training model keeps its FLOPs compiled.
        from .lazy import materialize_tree, segment_capture

        with segment_capture(
                grad_mode=framework.is_grad_enabled()) as trace:
            out = fn(*args, **kwargs)
        self._segment_stats = {"segments": trace.segments,
                               "ops": trace.recorded_ops}
        return materialize_tree(out)

    def __call__(self, *args, **kwargs):
        raw_args = self._bucketize(_unwrap_tensors(args))
        raw_kwargs = self._bucketize_kwargs(_unwrap_tensors(kwargs))
        key = self._trace_key(raw_args, raw_kwargs)
        if self._compiled.get(key, False) is None:  # known graph break
            return self._eager_call(args, kwargs)
        slot = self._get_compiled(key)
        key_arr = framework.next_rng_key()
        try:
            if self._layer is not None:
                state = {k: v._data
                         for k, v in self._layer.state_dict().items()}
                out_arrays, mutated = self._run_slot(slot, state, key_arr,
                                                     raw_args, raw_kwargs)
                # write back mutated buffers (e.g. batchnorm stats)
                entries = self._layer.state_dict()
                for name, arr in mutated.items():
                    if name in entries:
                        entries[name]._data = arr
                return _wrap_arrays(out_arrays)
            return _wrap_arrays(self._run_slot(slot, key_arr, raw_args,
                                               raw_kwargs))
        except self._GRAPH_BREAK_ERRORS as e:
            # graph break: data-dependent Python control flow cannot trace;
            # run this call eagerly (SOT fallback semantics) and remember so
            # later same-signature calls skip the doomed trace
            self._compiled[key] = None
            if not self._fallback_warned:
                self._fallback_warned = True
                import warnings

                target = self._fn or self._layer
                warnings.warn(
                    f"to_static: graph break in "
                    f"{getattr(target, '__name__', type(target).__name__)} "
                    f"({type(e).__name__}); falling back to eager for such "
                    "calls — hoist data-dependent Python branching out of "
                    "forward (or use paddle.where / lax.cond) to stay "
                    "compiled")
            return self._eager_call(args, kwargs)

    @property
    def dygraph_function(self):
        return self._fn or self._layer

    def concrete_program(self):  # compat stub
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """paddle.jit.to_static — decorator or direct call."""

    def decorate(fn):
        if isinstance(fn, Layer):
            static = StaticFunction(fn, input_spec, **kwargs)
            # wrap the layer: calling the proxy runs the compiled path while
            # attribute access (parameters, state_dict...) hits the layer
            return _StaticLayerProxy(fn, static)
        return functools.wraps(fn)(StaticFunction(fn, input_spec, **kwargs))

    if function is not None:
        return decorate(function)
    return decorate


class _StaticLayerProxy:
    """Layer wrapper whose __call__ runs the compiled program."""

    def __init__(self, layer, static):
        object.__setattr__(self, "_layer", layer)
        object.__setattr__(self, "_static", static)

    def __call__(self, *args, **kwargs):
        return self._static(*args, **kwargs)

    def __getattr__(self, name):
        if name == "_segment_stats":  # capture observability lives on the
            return self._static._segment_stats  # StaticFunction, not the layer
        return getattr(self._layer, name)

    def __setattr__(self, name, value):
        setattr(self._layer, name, value)


def not_to_static(fn):
    return fn


def enable_to_static(flag=True):
    pass


def ignore_module(modules):
    pass


# ---------------------------------------------------------------------------
# TrainStep: compiled forward+backward+update (the perf path)
# ---------------------------------------------------------------------------
def _global_grad_sumsq(grads):
    """One fused reduction: sum of squares over the flattened grad tree
    (float32). Shared by the in-graph StepHealth bundle and global-norm
    clipping — the norm is computed once per step, never twice."""
    leaves = [g for g in tree_util.tree_leaves(grads) if g is not None]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)


def _functional_clip_global_norm(grads, clip_norm, gnorm=None):
    leaves = [g for g in tree_util.tree_leaves(grads) if g is not None]
    if not leaves:
        return grads
    if gnorm is None:
        gnorm = jnp.sqrt(_global_grad_sumsq(grads))
    clip = jnp.asarray(clip_norm, jnp.float32)
    scale = clip / jnp.maximum(gnorm, clip)
    return tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


def _step_update_tail(opt, clip, reg, params, grads, loss, new_buffers,
                      buffers, opt_state, lr, guard, *,
                      gsumsq_fn=_global_grad_sumsq):
    """The post-gradient step tail — chaos injection, regularizer,
    StepHealth bundle, grad clip, optimizer update, guard keep-select —
    shared by ``TrainStep._build`` and the ZeRO
    ``ShardedTrainStep._build_zero`` so the PR 5 guard semantics live in
    ONE place (the zero step passes param/grad SHARD views and a
    ``gsumsq_fn`` that psums the sharded leaves; everything here is
    elementwise or scale-broadcast, so it is layout-agnostic).

    Returns ``(loss, new_params, new_buffers, new_opt_state, health)``
    with ``new_params`` in the same layout as ``params``."""
    # chaos anomaly seam: a zero injection selects the original bytes —
    # the select with a false predicate is the identity, so clean runs
    # are bit-identical with or without a hook installed
    ginj, linj = guard[1], guard[2]
    do_g = ginj != 0.0  # nan != 0 and inf != 0 are both True
    grads = tree_util.tree_map(
        lambda g: jnp.where(do_g, jnp.full_like(g, ginj.astype(g.dtype)),
                            g),
        grads)
    loss = jnp.where(linj != 0.0, linj.astype(loss.dtype), loss)
    if reg is not None:
        grads = {
            n: reg._apply_arr(params[n], g) for n, g in grads.items()
        }
    # StepHealth: ONE reduction over the flattened grad tree, shared
    # with global-norm clipping below — no second pass, no extra HBM
    # arrays (4 scalars ride out with the step)
    gsumsq = gsumsq_fn(grads)
    gnorm = jnp.sqrt(gsumsq)
    loss32 = loss.astype(jnp.float32)
    finite = jnp.isfinite(loss32) & jnp.isfinite(gsumsq)
    from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

    # trace-phase anatomy: this function runs under jax tracing (once
    # per program build), so these spans decompose the jit:trace phase
    # of a build — they never fire per executed step
    _tr = _telemetry.trace
    _tr_on = _tr.enabled()
    _t_clip = _time.perf_counter() if _tr_on else 0.0
    if isinstance(clip, ClipGradByGlobalNorm):
        grads = _functional_clip_global_norm(grads, clip.clip_norm,
                                             gnorm=gnorm)
    elif isinstance(clip, ClipGradByValue):
        grads = tree_util.tree_map(
            lambda g: jnp.clip(g, clip.min, clip.max), grads
        )
    elif isinstance(clip, ClipGradByNorm):
        # (the zero plan declines ClipGradByNorm at build — per-tensor
        # norms need the full grad tensor — so this branch only runs on
        # full layouts)
        def _clip_one(g):
            n = jnp.linalg.norm(g.astype(jnp.float32).reshape(-1))
            c = jnp.asarray(clip.clip_norm, jnp.float32)
            return (g * jnp.minimum(c / jnp.maximum(n, c), 1.0)).astype(g.dtype)

        grads = tree_util.tree_map(_clip_one, grads)
    if _tr_on:
        _t_upd = _time.perf_counter()
        _tr.complete("trace:grad_clip", _t_clip, _t_upd - _t_clip,
                     cat="jit")
    new_params, new_opt_state = opt.functional_update(params, grads,
                                                      opt_state, lr)
    if _tr_on:
        _t_guard = _time.perf_counter()
        _tr.complete("trace:opt_update", _t_upd, _t_guard - _t_upd,
                     cat="jit")
    # in-graph skip (StepGuard): a nonfinite or above-threshold step
    # keeps the pre-step param/slot/buffer trees. select on a true
    # predicate returns the update bytes unchanged, and the pre-step
    # operands are already live inside the step, so this costs no extra
    # HBM and composes with buffer donation.
    ok = (guard[3] == 0.0) | (finite & (loss32 <= guard[0]))

    def _keep(new, old):
        return jnp.where(ok, new, old)

    new_params = tree_util.tree_map(_keep, new_params, params)
    new_opt_state = tree_util.tree_map(_keep, new_opt_state, opt_state)
    new_buffers = {n: _keep(new_buffers[n], buffers[n])
                   for n in new_buffers}
    health = jnp.stack([finite.astype(jnp.float32), gnorm, loss32,
                        ok.astype(jnp.float32)])
    if _tr_on:
        _tr.complete("trace:guard_select", _t_guard,
                     _time.perf_counter() - _t_guard, cat="jit")
    return loss, new_params, new_buffers, new_opt_state, health


class TrainStep:
    """Compile (forward, loss, backward, optimizer update) into one XLA program.

    train_fn(*batch_tensors) -> scalar loss Tensor, closing over `model`.
    Parameters and optimizer slots are donated — updates happen in-place in
    HBM with zero copies, like the reference's fused optimizer kernels.
    """

    def __init__(self, model: Layer, train_fn, optimizer, scaler=None):
        self.model = model
        self.train_fn = train_fn
        self.optimizer = optimizer
        self._compiled = None
        self._execs = {}  # input-signature -> AOT executable (or jit fn)
        self._exec_costs = {}  # input-signature -> cost_analysis summary
        self._last_cost = None  # newest executable's cost summary
        self._param_names = None
        self._buffer_names = None
        self._opt_state = None
        # resilience guard inputs (docs/RESILIENCE.md): the spike
        # threshold rides into the compiled step as an OPERAND, so the
        # guard never causes a recompile. None = +inf = never skip.
        self._guard_threshold = None
        self._call_index = 0      # 1-based invocation count (chaos seam)
        self._last_health = None  # device f32[4], fetched lazily

    def _build(self):
        model, train_fn, opt = self.model, self.train_fn, self.optimizer
        from ..utils.flags import get_flags as _gf

        # planner-driven AOT builds are labeled apart from real training
        # compiles: a cold-cache plan lowers up to a full candidate grid,
        # which would false-positive the watchdog's ">1 recompile per
        # function means shape churn" triage rule (docs/TELEMETRY.md)
        _telemetry.record_compile(
            f"TrainStep[{type(self.model).__name__}]"
            + ("[plan]" if getattr(self, "_planning", False) else ""),
            ("build", bool(_gf("check_nan_inf")["check_nan_inf"])))
        entries = model.state_dict()
        from ..core.tensor import Parameter

        self._param_names = [
            n for n, t in entries.items()
            if isinstance(t, Parameter) and t.trainable
        ]
        self._buffer_names = [n for n in entries if n not in self._param_names]
        clip = opt._grad_clip
        reg = opt.regularization

        def make_loss_of(buffers, key_arr, batch):
            # the (buffers, rng key, batch) closure is built through this
            # factory so subclasses can re-close it over PER-SHARD values
            # (ShardedTrainStep's quantized dp-grad reduce rebuilds it
            # inside a manual shard_map region with the batch split over
            # the data axes — distributed/collectives)
            def loss_of(params):
                state = dict(params)
                state.update(buffers)
                with model._swap_state(state) as mutated:
                    with framework.no_grad(), framework.rng_key_scope(key_arr):
                        loss_t = train_fn(*_wrap_arrays(batch))
                new_buffers = {n: mutated[n] for n in self._buffer_names}
                return loss_t._data, new_buffers

            return loss_of

        def step(params, buffers, opt_state, lr, guard, key_arr, batch):
            # guard: f32[4] operand = [spike_threshold, grad_inject,
            # loss_inject, armed]. Thresholds/injections are VALUES, not
            # shapes — guarded and unguarded runs execute this same
            # program. `armed` gates the skip select: only an attached
            # StepGuard discards anomalous updates; an unguarded step
            # adopts them exactly as it always did (a silent drop would
            # hide real divergence from users who never opted in).
            (loss, new_buffers), grads = self._value_and_grads(
                make_loss_of, params, buffers, key_arr, batch)
            return _step_update_tail(opt, clip, reg, params, grads, loss,
                                     new_buffers, buffers, opt_state, lr,
                                     guard)

        from ..utils.flags import get_flags

        self._execs = {}
        if get_flags("check_nan_inf")["check_nan_inf"]:
            # FLAGS_check_nan_inf inside the COMPILED step: checkify
            # instruments every float op so the raised error names the
            # first NaN-producing primitive and its traceback — the
            # compiled-mode analogue of the reference's per-kernel
            # CheckNumerics pass (paddle/fluid/framework/details/
            # nan_inf_utils_detail). Costs extra compute; debug-only.
            from jax.experimental import checkify

            self._checkified = True
            # NO buffer donation in debug mode: on a nan error the step's
            # outputs are discarded and the caller must still be able to
            # inspect the pre-step params/opt-state
            self._compiled = jax.jit(
                checkify.checkify(step, errors=checkify.float_checks))
        else:
            self._checkified = False
            self._compiled = jax.jit(step, donate_argnums=(0, 2))

    def _compile_label(self):
        return (f"TrainStep[{type(self.model).__name__}]"
                + ("[plan]" if getattr(self, "_planning", False) else ""))

    @staticmethod
    def _exec_sig(tree):
        def leaf_sig(a):
            if hasattr(a, "shape"):
                return (tuple(a.shape), str(a.dtype))
            # python scalars are traced as weak-typed OPERANDS (jit
            # reuses one program across values) — key them by class,
            # never by value, or a per-step int in the batch would force
            # a full recompile per distinct value
            if isinstance(a, bool):
                return "<b>"
            if isinstance(a, int):
                return "<i>"
            if isinstance(a, float):
                return "<f>"
            return repr(a)

        return tuple(leaf_sig(l) for l in tree_util.tree_leaves(tree))

    def _dispatch_compiled(self, *op_args):
        """Run the step program through an explicitly built executable so
        the build splits into measured trace/lower/compile phases
        (compile-phase gauges + the bench "compile" block). Signature
        miss -> timed AOT build; any AOT surprise falls back to plain
        ``jax.jit`` dispatch — never worse than the pre-telemetry path."""
        key = self._exec_sig(op_args)
        ex = self._execs.get(key)
        if ex is None:
            try:
                ex = timed_lower_compile(self._compiled,
                                         self._compile_label(), *op_args)
                cost = compiled_cost_summary(ex)
                self._exec_costs[key] = cost
                if cost is not None:
                    self._last_cost = cost
            except Exception:
                ex = self._compiled
            self._execs[key] = ex
        try:
            return _traced_dispatch(ex, self._compile_label(),
                                    self._exec_costs.get(key), op_args)
        except (TypeError, ValueError):
            # AOT argument check rejected the operands BEFORE execution
            # (an aval/layout property the signature key didn't capture):
            # jit dispatch is authoritative for this signature from now
            # on. Execution-time errors re-raise unchanged.
            if ex is self._compiled:
                raise
            self._execs[key] = self._compiled
            self._exec_costs.pop(key, None)
            return self._compiled(*op_args)

    def _value_and_grads(self, make_loss_of, params, buffers, key_arr,
                         batch):
        """Differentiation seam inside the compiled step: returns
        ``((loss, new_buffers), grads)``. The base implementation is the
        pre-PR program verbatim; ShardedTrainStep overrides it to run
        the backward inside a manual data-axis region with a bucketed /
        quantized gradient reduce (distributed/collectives) when its
        plan engages — and delegates HERE when it doesn't, which is what
        makes ``PTPU_QUANT_COLLECTIVES=0`` byte-identical."""
        loss_of = make_loss_of(buffers, key_arr, batch)
        return jax.value_and_grad(loss_of, has_aux=True)(params)

    def last_dispatch_cost(self):
        """cost_analysis summary of the newest compiled step executable
        (compiled_cost_summary shape), or None before the first build /
        when the program exposes no cost analysis — the bench anatomy
        block's device-side estimate."""
        return self._last_cost

    def __call__(self, *batch):
        model_label = (type(self.model).__name__,)
        _TRAIN_STEPS.inc(labels=model_label)
        with _telemetry.timer(_TRAIN_STEP_SECONDS, labels=model_label):
            tr = _telemetry.trace
            if tr.enabled():
                with tr.span("train_step",
                             attrs={"model": model_label[0]}, cat="step"):
                    return self._call_impl(*batch)
            return self._call_impl(*batch)

    def _call_impl(self, *batch):
        from ..utils.flags import get_flags

        want_check = bool(get_flags("check_nan_inf")["check_nan_inf"])
        if self._compiled is None or want_check != getattr(
                self, "_checkified", False):
            self._build()  # flag flipped since last compile: rebuild
        entries = self.model.state_dict()
        params = {n: entries[n]._data for n in self._param_names}
        buffers = {n: entries[n]._data for n in self._buffer_names}
        if self._opt_state is None:
            self._opt_state = self._init_opt_state(params)
        lr = self.optimizer.get_lr()
        guard_arr = self._guard_operand()
        key_arr = framework.next_rng_key()
        raw_batch = _unwrap_tensors(batch)
        if self._checkified:
            err, out = self._dispatch_compiled(params, buffers,
                                               self._opt_state, lr,
                                               guard_arr, key_arr, raw_batch)
            # raise BEFORE adopting any of the step's outputs: params,
            # buffers, and opt state all stay at their pre-step values so
            # the user can inspect or skip the batch
            err.throw()
            loss, new_params, new_buffers, self._opt_state, health = out
        else:
            loss, new_params, new_buffers, self._opt_state, health = \
                self._dispatch_compiled(
                    params, buffers, self._opt_state, lr, guard_arr,
                    key_arr, raw_batch
                )
        self._last_health = health
        for n, arr in new_params.items():
            entries[n]._data = arr
        for n, arr in new_buffers.items():
            entries[n]._data = arr
        if self.optimizer._lr_scheduler is not None:
            pass  # stepped by the caller per paddle convention
        self.optimizer._step_count += 1
        # quant-compute flops accounting (docs/QUANT.md): one counter tick
        # per executed step, rate recorded by the last engaged trace
        from ..quant import note_step_tokens

        shape = getattr(raw_batch[0], "shape", ()) if raw_batch else ()
        note_step_tokens(int(shape[0]) * int(shape[1])
                         if len(shape) >= 2 else 0)
        return Tensor(loss)

    def _guard_operand(self):
        """f32[4] guard operand: [spike_threshold, grad_inject,
        loss_inject, armed]. `armed` is 1 only while a StepGuard drives
        the step (``_guard_threshold`` set) — unguarded steps keep their
        legacy adopt-everything semantics. Also advances the chaos
        anomaly seam (resilience._ANOMALY_FAULT_HOOK) by one invocation.
        The device array is cached per value tuple: unguarded runs and
        a guard still inside its warmup (+inf threshold) re-upload
        nothing; once the rolling spike threshold is live it changes
        per accepted step, costing one f32[4] (16-byte) upload."""
        self._call_index += 1
        thr = self._guard_threshold
        armed = 0.0 if thr is None else 1.0
        thr = float("inf") if thr is None else float(thr)
        ginj = linj = 0.0
        from .. import resilience as _resilience

        hook = _resilience._ANOMALY_FAULT_HOOK
        if hook is not None:
            res = hook(self._call_index)
            if res is not None:
                site, val = res
                if site == "grads":
                    ginj = float(val)
                elif site == "loss":
                    linj = float(val)
                else:
                    raise ValueError(
                        f"anomaly hook site {site!r} not in "
                        "('grads', 'loss')")
        key = (thr, ginj, linj, armed)
        cached = getattr(self, "_guard_arr_cache", None)
        if cached is None or cached[0] != key:
            cached = (key, jnp.asarray(key, jnp.float32))
            self._guard_arr_cache = cached
        return cached[1]

    @property
    def last_health(self):
        """`resilience.StepHealth` of the most recent step (None before
        the first). This is the guard's ONE extra device fetch per step:
        the fused 4-scalar bundle computed inside the compiled program."""
        if self._last_health is None:
            return None
        import numpy as _np

        from ..resilience.guard import StepHealth

        v = _np.asarray(self._last_health)
        return StepHealth(finite=bool(v[0]), grad_norm=float(v[1]),
                          loss=float(v[2]), ok=bool(v[3]))

    def aot_compile(self, *batch):
        """Lower + compile this step WITHOUT executing it (the memory
        planner's entry point, paddle_tpu.memory.plan_train_step):
        returns the jax Compiled object, whose ``memory_analysis()``
        prices the program's HBM before anything runs.

        Every operand is passed as an aval (ShapeDtypeStruct) — params
        and buffers from the live model's shapes, optimizer state via
        ``eval_shape`` over ``functional_state`` — so candidate configs
        can be compiled back to back without allocating a single device
        buffer. ``batch`` may be Tensors, arrays, or ShapeDtypeStructs.
        (ShardedTrainStep's ``_prepare_batch`` hook still places model +
        opt state on the mesh so the lowered program matches a real
        step's shardings — the zero-allocation guarantee is for the
        single-program TrainStep the planner drives.)"""
        if self._compiled is None:
            self._build()
        raw_batch = self._prepare_batch(_unwrap_tensors(batch))

        def aval(a):
            # keep the array's sharding (ShardedTrainStep places batch/
            # state with NamedShardings via _prepare_batch — the lowered
            # program must see the same placements a real step would)
            sh = getattr(a, "sharding", None)
            if sh is not None:
                return jax.ShapeDtypeStruct(tuple(a.shape),
                                            jnp.dtype(a.dtype), sharding=sh)
            return jax.ShapeDtypeStruct(tuple(a.shape), jnp.dtype(a.dtype))

        entries = self.model.state_dict()
        params = {n: aval(entries[n]._data) for n in self._param_names}
        buffers = {n: aval(entries[n]._data) for n in self._buffer_names}
        if self._opt_state is not None:
            opt_state = tree_util.tree_map(aval, self._opt_state)
        else:
            opt_state = jax.eval_shape(self._functional_state, params)
        lr = self.optimizer.get_lr()
        guard_aval = jax.ShapeDtypeStruct((4,), jnp.float32)
        key_arr = aval(framework.next_rng_key())
        batch_avals = tree_util.tree_map(aval, raw_batch)
        return timed_lower_compile(
            self._compiled, self._compile_label(), params, buffers,
            opt_state, lr, guard_aval, key_arr, batch_avals)

    def memory_stats(self, *batch):
        """XLA buffer-assignment stats for this step's program: dict of
        argument/output/temp bytes (CompiledMemoryStats). Lowers and
        compiles ahead-of-time without executing (aot_compile) — meant
        for small trial programs (the auto_tuner's measure mode) and the
        memory planner, not the training hot path."""
        ma = self.aot_compile(*batch).memory_analysis()
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "peak_bytes": int(ma.argument_size_in_bytes
                              + ma.temp_size_in_bytes),
        }

    def aot_report(self, *batch):
        """One AOT compile, both pricing surfaces: ``(memory, cost)``
        where ``memory`` is the :meth:`memory_stats` dict and ``cost``
        the :func:`compiled_cost_summary` roofline record (or None when
        the executable exposes no cost analysis). The layout autotuner
        (memory/autotune.py) scores every candidate from this — calling
        memory_stats and a separate cost pass would pay the
        lower+compile twice per candidate."""
        compiled = self.aot_compile(*batch)
        ma = compiled.memory_analysis()
        mem = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "peak_bytes": int(ma.argument_size_in_bytes
                              + ma.temp_size_in_bytes),
        }
        return mem, compiled_cost_summary(compiled)

    def _prepare_batch(self, raw_batch):
        """Hook: sharded subclasses place batch arrays on the mesh so the
        lowered program sees the same input shardings as a real step."""
        return raw_batch

    def _functional_state(self, params):
        """Layout hook: fresh functional slots for this step. The ZeRO
        ShardedTrainStep overrides it to create flat dp-sharded slots
        for chunk-updated params (distributed/collectives/zero)."""
        return self.optimizer.functional_state(params)

    def _init_opt_state(self, params):
        """Fresh functional slots, seeded from any eager slots already on
        the optimizer — the checkpoint-restore path: set_state_dict fills
        optimizer._slots, and a resumed compiled step must continue from
        those moments, not from zeros (reference resume semantics:
        opt.set_state_dict before the next train_batch)."""
        state = self._functional_state(params)
        entries = self.model.state_dict()
        for n in self._param_names:
            slots = self.optimizer._slots.get(id(entries[n]))
            if slots:
                pshape = tuple(entries[n]._data.shape)
                st = dict(state[n])
                for k, v in slots.items():
                    if k not in st:
                        continue
                    arr = jnp.asarray(v._data if isinstance(v, Tensor)
                                      else v)
                    adapted = self._adapt_restored_slot(arr, st[k], n,
                                                        pshape)
                    if adapted is None:
                        continue  # incompatible layout: keep fresh slots
                    # COPY: the compiled step donates opt state
                    # (donate_argnums) — seeding by reference would let
                    # the first step delete the eager slot buffers and
                    # the checkpoint arrays they share
                    st[k] = jnp.array(adapted, copy=True)
                state[n] = st
        return state

    def _adapt_restored_slot(self, arr, tgt, pname, pshape):
        """Shape-adapt one restored eager slot ``arr`` to the functional
        target ``tgt``, or None to keep the fresh slot. The ONE place
        the slot-layout conversion rules live (the ZeRO
        ShardedTrainStep overrides it for the flat dp-sharded layout,
        docs/ZERO.md checkpoint contract). Base rules: identical shapes
        pass through; a ZeRO flat ``[padded]`` slot un-pads losslessly
        into a param-shaped target (the flat layout is exactly
        flatten + zero-pad)."""
        import numpy as _np

        if tuple(arr.shape) == tuple(tgt.shape):
            return arr
        pnumel = int(_np.prod(pshape)) if pshape else 1
        if (arr.ndim == 1 and arr.size >= pnumel
                and tuple(tgt.shape) == pshape):
            return arr[:pnumel].reshape(pshape)
        return None

    def sync_optimizer_state(self):
        """Push functional opt state back into the eager optimizer slots."""
        if self._opt_state is None:
            return
        entries = self.model.state_dict()
        for n in self._param_names:
            p = entries[n]
            self.optimizer._slots[id(p)] = self._opt_state[n]


# ---------------------------------------------------------------------------
# jit.save / jit.load: serialized-program inference artifact
# (capability slot: fluid/jit + inference AnalysisPredictor program files —
#  analysis_predictor.h:101. NO pickled Python objects: the artifact is a
#  serialized StableHLO program + raw weight bytes, loadable in a process
#  that has never seen the model's class.)
# ---------------------------------------------------------------------------
_ARTIFACT_VERSION = 1


def _pack_weights(weights, names):
    """Shared artifact weight packing (used by jit.save and
    inference.convert_to_mixed_precision — one format, one writer)."""
    import numpy as np

    packed, params_meta = {}, []
    for i, (n, w) in enumerate(zip(names, weights)):
        a = np.asarray(w)
        packed[f"w{i}"] = np.frombuffer(a.tobytes(), np.uint8)
        # self-describing sidecar keys: the npz alone decodes without the
        # meta json (static.deserialize_persistables relies on this)
        packed[f"w{i}_name"] = np.asarray(n)
        packed[f"w{i}_dtype"] = np.asarray(str(a.dtype))
        packed[f"w{i}_shape"] = np.asarray(list(a.shape), np.int64)
        params_meta.append({"name": n, "dtype": str(a.dtype),
                            "shape": list(a.shape)})
    return packed, params_meta


def _encode_struct(tree, counter):
    """JSON-able description of an output pytree; leaves become indices."""
    if isinstance(tree, (list, tuple)):
        return {"kind": "tuple" if isinstance(tree, tuple) else "list",
                "items": [_encode_struct(t, counter) for t in tree]}
    if isinstance(tree, dict):
        return {"kind": "dict",
                "keys": sorted(tree),
                "items": [_encode_struct(tree[k], counter) for k in sorted(tree)]}
    if tree is None:
        return {"kind": "none"}
    i = counter[0]
    counter[0] += 1
    return {"kind": "leaf", "index": i}


def _decode_struct(desc, leaves):
    k = desc["kind"]
    if k == "leaf":
        return leaves[desc["index"]]
    if k == "none":
        return None
    if k == "dict":
        return {key: _decode_struct(d, leaves)
                for key, d in zip(desc["keys"], desc["items"])}
    items = [_decode_struct(d, leaves) for d in desc["items"]]
    return tuple(items) if k == "tuple" else items


def _input_avals(input_spec, layer):
    import numpy as np

    from ..static import InputSpec

    specs = input_spec
    if specs is None:
        specs = getattr(layer, "_last_call_spec", None)
        if specs is None:
            raise ValueError(
                "jit.save needs input_spec (or call the layer once first so "
                "its input signature is recorded)")
    if isinstance(specs, (InputSpec, Tensor)):
        specs = [specs]
    avals = []
    scope = None
    sym_count = [0]

    def _sym_shape(dims):
        """InputSpec None/-1 dims become jax.export symbolic dims, so the
        artifact serves any batch size (reference: dynamic-axis InputSpec)."""
        nonlocal scope
        from jax import export as jax_export

        names = []
        for d in dims:
            if d is None or (isinstance(d, int) and d < 0):
                names.append(f"_dyn{sym_count[0]}")
                sym_count[0] += 1
            else:
                names.append(str(int(d)))
        spec_str = ", ".join(names) if names else ""
        if scope is None:
            scope = jax_export.SymbolicScope()
        return jax_export.symbolic_shape(spec_str, scope=scope)

    for s in specs:
        if isinstance(s, InputSpec):
            dims = list(s.shape)
            if any(d is None or (isinstance(d, int) and d < 0) for d in dims):
                shape = _sym_shape(dims)
            else:
                shape = tuple(int(d) for d in dims)
            avals.append(jax.ShapeDtypeStruct(tuple(shape),
                                              jnp.dtype(_np_dtype(s.dtype))))
        elif isinstance(s, Tensor):
            avals.append(jax.ShapeDtypeStruct(tuple(s.shape), s._data.dtype))
        elif isinstance(s, tuple) and len(s) == 2:  # recorded (shape, dtype)
            avals.append(jax.ShapeDtypeStruct(tuple(s[0]), jnp.dtype(s[1])))
        else:
            a = np.asarray(s)
            avals.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
    return avals


def _np_dtype(d):
    from .. import dtypes as _dt

    return _dt.to_np(d)


def save(layer, path, input_spec=None, **configs):
    """Serialize `layer` into a class-free inference artifact.

    Writes {path}.pdmodel (StableHLO program over (weights, *inputs)),
    {path}.pdiparams (raw weight bytes), {path}.pdmeta.json (names, input
    avals, output structure).
    """
    import json
    import os

    import numpy as np
    from jax import export as jax_export

    if isinstance(layer, _StaticLayerProxy):
        layer = layer._layer
    if isinstance(layer, StaticFunction):
        layer = layer._layer
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer (or to_static Layer)")

    avals = _input_avals(input_spec, layer)
    entries = layer.state_dict()
    names = sorted(entries)
    weights = [entries[n]._data for n in names]

    was_training = layer.training
    layer.eval()
    try:
        # discover the output structure, then export a flat-output program
        def run(state_list, *inputs):
            state = dict(zip(names, state_list))
            out, _ = functional_call(layer, state, *inputs)
            return out

        out_shape = jax.eval_shape(run, weights, *avals)
        counter = [0]
        struct = _encode_struct(out_shape, counter)

        def pure(state_list, *inputs):
            out = run(state_list, *inputs)
            return tuple(tree_util.tree_leaves(out))

        try:  # platform-polymorphic artifact when supported (cpu dev / tpu)
            exported = jax_export.export(
                jax.jit(pure), platforms=("cpu", "tpu"))(weights, *avals)
        except Exception:
            exported = jax_export.export(jax.jit(pure))(weights, *avals)
        blob = exported.serialize()
    finally:
        if was_training:
            layer.train()

    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    packed, params_meta = _pack_weights(weights, names)
    with open(path + ".pdiparams", "wb") as f:
        np.savez(f, **packed)
    meta = {
        "version": _ARTIFACT_VERSION,
        "params": params_meta,
        "inputs": [{"shape": [d if isinstance(d, int) else -1
                              for d in a.shape],
                    "dtype": str(a.dtype)}
                   for a in avals],
        "input_names": [getattr(s, "name", None) or f"input_{i}"
                        for i, s in enumerate(input_spec or avals)],
        "outputs": struct,
    }
    with open(path + ".pdmeta.json", "w") as f:
        json.dump(meta, f)


def load_artifact(path, params_file=None):
    """(exported_program, weights[list of jax arrays], meta) from jit.save files.

    `path` is the save prefix; `params_file` overrides the default
    `{path}.pdiparams` (the reference Config takes them separately)."""
    import json

    import numpy as np
    from jax import export as jax_export

    import os

    if not os.path.exists(path + ".pdmeta.json"):
        raise FileNotFoundError(
            f"{path}.pdmeta.json not found — not a paddle_tpu jit.save "
            "artifact (models saved before the serialized-program format "
            "must be re-saved with jit.save)")
    with open(path + ".pdmeta.json") as f:
        meta = json.load(f)
    if meta.get("version") != _ARTIFACT_VERSION:
        raise ValueError(
            f"artifact version {meta.get('version')} != supported "
            f"{_ARTIFACT_VERSION}; re-save the model with this release")
    with open(path + ".pdmodel", "rb") as f:
        blob = f.read()
    if blob[:1] == b"\x80":  # pickle protocol header = legacy jit.save file
        raise ValueError(
            f"{path}.pdmodel is a legacy pickled model; re-save with the "
            "current jit.save (serialized-program artifact)")
    exported = jax_export.deserialize(bytearray(blob))
    import ml_dtypes  # noqa: F401  (registers bfloat16 et al with numpy)

    weights = []
    with np.load(params_file or path + ".pdiparams",
                 allow_pickle=False) as z:
        for i, pm in enumerate(meta["params"]):
            raw = z[f"w{i}"].tobytes()
            a = np.frombuffer(raw, dtype=np.dtype(pm["dtype"])).reshape(
                pm["shape"])
            weights.append(jnp.asarray(a))
    return exported, weights, meta


def load(path, **configs):
    return TranslatedLayer._construct(path)


class TranslatedLayer(Layer):
    """A loaded jit.save artifact (parity: jit/translated_layer.py) — runs the
    serialized program; the original Python class is not needed."""

    def __init__(self, exported, weights, meta):
        super().__init__()
        self._exported = exported
        self._weights = list(weights)
        self._meta = meta
        self._run = jax.jit(exported.call)

    @staticmethod
    def _construct(model_path, configs=None):
        return TranslatedLayer(*load_artifact(model_path))

    def forward(self, *args):
        raw = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
               for a in args]
        flat = self._run(self._weights, *raw)
        out = _decode_struct(self._meta["outputs"],
                             [Tensor(l) for l in flat])
        return out

    # weights live outside Layer's parameter machinery; expose the standard
    # state-dict surface directly
    def state_dict(self, *a, **kw):
        return {pm["name"]: Tensor(w)
                for pm, w in zip(self._meta["params"], self._weights)}

    def set_state_dict(self, state_dict, *a, **kw):
        for i, pm in enumerate(self._meta["params"]):
            v = state_dict.get(pm["name"])
            if v is not None:
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                self._weights[i] = arr.astype(self._weights[i].dtype)

    def program(self):  # compat: the loaded "program" is the exported module
        return self._exported


def set_code_level(level=100, also_to_stdout=False):
    pass  # SOT bytecode logging has no analogue: tracing is the capture


def set_verbosity(level=0, also_to_stdout=False):
    pass
