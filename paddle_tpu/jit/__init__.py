"""paddle.jit — trace-to-XLA compilation (parity: python/paddle/jit).

The reference captures python bytecode (SOT eval-frame hook, §3.6 of the
survey) and compiles the captured graph through CINN.  The TPU-native design
replaces that whole pipeline with jax tracing: because every eager op is a
pure jax function over the Tensor's payload, running a Layer's forward with
tracer payloads *is* the capture.  ``to_static`` wraps a Layer as a pure
function of (parameters, buffers, inputs) and hands it to ``jax.jit``;
``TrainStep`` compiles forward+backward+optimizer into one donated-buffer XLA
program — the analogue of the reference's whole-graph `pir_partial_program`
plus CINN, with XLA doing fusion/scheduling.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import tree_util

from .. import framework
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def _wrap_arrays(tree):
    return tree_util.tree_map(lambda a: Tensor(a), tree)


def _unwrap_tensors(tree):
    return tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t,
        tree,
        is_leaf=lambda x: isinstance(x, Tensor),
    )


def functional_call(layer: Layer, state: dict, *args, **kwargs):
    """Run `layer` as a pure function of `state` (name -> array).

    Returns (outputs_pytree_of_arrays, mutated_state_dict)."""
    with layer._swap_state(state) as mutated:
        with framework.no_grad():
            wrapped_args = _wrap_arrays(args)
            wrapped_kwargs = _wrap_arrays(kwargs)
            out = layer(*wrapped_args, **wrapped_kwargs)
    return _unwrap_tensors(out), mutated


class StaticFunction:
    """Compiled wrapper around a Layer or a pure tensor function."""

    def __init__(self, function, input_spec=None, **kwargs):
        if isinstance(function, Layer):
            self._layer = function
            self._fn = None
        else:
            self._layer = getattr(function, "__self__", None)
            self._fn = function
        self._input_spec = input_spec
        self._compiled = {}

    def _trace_key(self):
        training = self._layer.training if self._layer is not None else False
        return (training,)

    def _get_compiled(self):
        key = self._trace_key()
        if key not in self._compiled:
            layer = self._layer
            fn = self._fn

            if layer is not None:
                def pure(state, key_arr, args, kwargs):
                    with layer._swap_state(state) as mutated:
                        with framework.no_grad(), framework.rng_key_scope(key_arr):
                            wa = _wrap_arrays(args)
                            wk = _wrap_arrays(kwargs)
                            if fn is not None:
                                out = fn(*wa, **wk)
                            else:
                                out = layer(*wa, **wk)
                    return _unwrap_tensors(out), dict(mutated)

                self._compiled[key] = jax.jit(pure)
            else:
                def pure_fn(key_arr, args, kwargs):
                    with framework.no_grad(), framework.rng_key_scope(key_arr):
                        out = fn(*_wrap_arrays(args), **_wrap_arrays(kwargs))
                    return _unwrap_tensors(out)

                self._compiled[key] = jax.jit(pure_fn)
        return self._compiled[key]

    def __call__(self, *args, **kwargs):
        compiled = self._get_compiled()
        raw_args = _unwrap_tensors(args)
        raw_kwargs = _unwrap_tensors(kwargs)
        key_arr = framework.next_rng_key()
        if self._layer is not None:
            state = {k: v._data for k, v in self._layer.state_dict().items()}
            out_arrays, mutated = compiled(state, key_arr, raw_args, raw_kwargs)
            # write back mutated buffers (e.g. batchnorm stats)
            entries = self._layer.state_dict()
            for name, arr in mutated.items():
                if name in entries:
                    entries[name]._data = arr
            return _wrap_arrays(out_arrays)
        return _wrap_arrays(compiled(key_arr, raw_args, raw_kwargs))

    @property
    def dygraph_function(self):
        return self._fn or self._layer

    def concrete_program(self):  # compat stub
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """paddle.jit.to_static — decorator or direct call."""

    def decorate(fn):
        if isinstance(fn, Layer):
            static = StaticFunction(fn, input_spec)
            # wrap the layer: calling the proxy runs the compiled path while
            # attribute access (parameters, state_dict...) hits the layer
            return _StaticLayerProxy(fn, static)
        return functools.wraps(fn)(StaticFunction(fn, input_spec))

    if function is not None:
        return decorate(function)
    return decorate


class _StaticLayerProxy:
    """Layer wrapper whose __call__ runs the compiled program."""

    def __init__(self, layer, static):
        object.__setattr__(self, "_layer", layer)
        object.__setattr__(self, "_static", static)

    def __call__(self, *args, **kwargs):
        return self._static(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._layer, name)

    def __setattr__(self, name, value):
        setattr(self._layer, name, value)


def not_to_static(fn):
    return fn


def enable_to_static(flag=True):
    pass


def ignore_module(modules):
    pass


# ---------------------------------------------------------------------------
# TrainStep: compiled forward+backward+update (the perf path)
# ---------------------------------------------------------------------------
def _functional_clip_global_norm(grads, clip_norm):
    leaves = [g for g in tree_util.tree_leaves(grads) if g is not None]
    if not leaves:
        return grads
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    gnorm = jnp.sqrt(sq)
    clip = jnp.asarray(clip_norm, jnp.float32)
    scale = clip / jnp.maximum(gnorm, clip)
    return tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


class TrainStep:
    """Compile (forward, loss, backward, optimizer update) into one XLA program.

    train_fn(*batch_tensors) -> scalar loss Tensor, closing over `model`.
    Parameters and optimizer slots are donated — updates happen in-place in
    HBM with zero copies, like the reference's fused optimizer kernels.
    """

    def __init__(self, model: Layer, train_fn, optimizer, scaler=None):
        self.model = model
        self.train_fn = train_fn
        self.optimizer = optimizer
        self._compiled = None
        self._param_names = None
        self._buffer_names = None
        self._opt_state = None

    def _build(self):
        model, train_fn, opt = self.model, self.train_fn, self.optimizer
        entries = model.state_dict()
        from ..core.tensor import Parameter

        self._param_names = [
            n for n, t in entries.items()
            if isinstance(t, Parameter) and t.trainable
        ]
        self._buffer_names = [n for n in entries if n not in self._param_names]
        clip = opt._grad_clip
        reg = opt.regularization

        def step(params, buffers, opt_state, lr, key_arr, batch):
            def loss_of(params):
                state = dict(params)
                state.update(buffers)
                with model._swap_state(state) as mutated:
                    with framework.no_grad(), framework.rng_key_scope(key_arr):
                        loss_t = train_fn(*_wrap_arrays(batch))
                new_buffers = {n: mutated[n] for n in self._buffer_names}
                return loss_t._data, new_buffers

            (loss, new_buffers), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
            if reg is not None:
                grads = {
                    n: reg._apply_arr(params[n], g) for n, g in grads.items()
                }
            from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

            if isinstance(clip, ClipGradByGlobalNorm):
                grads = _functional_clip_global_norm(grads, clip.clip_norm)
            elif isinstance(clip, ClipGradByValue):
                grads = tree_util.tree_map(
                    lambda g: jnp.clip(g, clip.min, clip.max), grads
                )
            elif isinstance(clip, ClipGradByNorm):
                def _clip_one(g):
                    n = jnp.linalg.norm(g.astype(jnp.float32).reshape(-1))
                    c = jnp.asarray(clip.clip_norm, jnp.float32)
                    return (g * jnp.minimum(c / jnp.maximum(n, c), 1.0)).astype(g.dtype)

                grads = tree_util.tree_map(_clip_one, grads)
            new_params, new_opt_state = opt.functional_update(params, grads, opt_state, lr)
            return loss, new_params, new_buffers, new_opt_state

        self._compiled = jax.jit(step, donate_argnums=(0, 2))

    def __call__(self, *batch):
        if self._compiled is None:
            self._build()
        entries = self.model.state_dict()
        params = {n: entries[n]._data for n in self._param_names}
        buffers = {n: entries[n]._data for n in self._buffer_names}
        if self._opt_state is None:
            self._opt_state = self.optimizer.functional_state(params)
        lr = self.optimizer.get_lr()
        key_arr = framework.next_rng_key()
        raw_batch = _unwrap_tensors(batch)
        loss, new_params, new_buffers, self._opt_state = self._compiled(
            params, buffers, self._opt_state, lr, key_arr, raw_batch
        )
        for n, arr in new_params.items():
            entries[n]._data = arr
        for n, arr in new_buffers.items():
            entries[n]._data = arr
        if self.optimizer._lr_scheduler is not None:
            pass  # stepped by the caller per paddle convention
        self.optimizer._step_count += 1
        return Tensor(loss)

    def sync_optimizer_state(self):
        """Push functional opt state back into the eager optimizer slots."""
        if self._opt_state is None:
            return
        entries = self.model.state_dict()
        for n in self._param_names:
            p = entries[n]
            self.optimizer._slots[id(p)] = self._opt_state[n]


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — persists state_dict (+ pickled layer when possible)."""
    from .. import framework_io

    state = layer.state_dict() if isinstance(layer, Layer) else {}
    framework_io.save(state, path + ".pdparams")
    try:
        import pickle

        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(layer, f)
    except Exception:
        pass


def load(path, **configs):
    import os
    import pickle

    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            layer = pickle.load(f)
        from .. import framework_io

        if os.path.exists(path + ".pdparams"):
            layer.set_state_dict(framework_io.load(path + ".pdparams"))
        return layer
    raise FileNotFoundError(path)


class TranslatedLayer(Layer):
    """parity: jit/translated_layer.py — a loaded jit.save model."""

    def __init__(self, programs=None, persistable_vars=None):
        super().__init__()
        self._inner = None

    @staticmethod
    def _construct(model_path, configs=None):
        return load(model_path)

    def forward(self, *args, **kwargs):
        if self._inner is None:
            raise RuntimeError("TranslatedLayer: load via paddle.jit.load")
        return self._inner(*args, **kwargs)


def set_code_level(level=100, also_to_stdout=False):
    pass  # SOT bytecode logging has no analogue: tracing is the capture


def set_verbosity(level=0, also_to_stdout=False):
    pass
