"""Segment capture: partial-graph compilation around graph breaks.

Capability slot: the reference's SOT compiles the subgraphs AROUND a
data-dependent break and stitches them (jit/sot/opcode_translator/executor/
function_graph.py) — one stray ``if tensor.item():`` costs one host sync,
not the whole function's compilation.

TPU-native design (LazyTensor-style, no bytecode rewriting): when a
``to_static`` call site is known to graph-break, the fallback no longer
dispatches op-by-op. Ops accumulate into a SEGMENT — a recorded graph of
apply_op calls whose outputs are placeholder `LazyValue`s (shape/dtype via
``jax.eval_shape``, no device work). The first *value* access (``.item()``,
``bool()``, ``.numpy()`` — the break itself) flushes the segment: the
recorded graph compiles to ONE jitted program (memoized per op-sequence +
input avals), runs, and fills every placeholder. Execution then continues
eagerly through the Python branch, and the ops after it accumulate into a
new segment — prefix compiled, break on host, suffix compiled.

Training mode (staged autograd, VERDICT r3 item 3): each flushed segment
becomes ONE GradNode on the eager tape whose pure_fn is the cached jitted
segment program — ``jax.vjp`` through the jit boundary keeps both the
recompute and the cotangent pull compiled, and the autograd engine
stitches cotangents across the host break exactly as it stitches any
other node edge. A training loop with one ``.item()`` branch thus keeps
its FLOPs in two compiled programs instead of falling back to per-op
eager (reference parity: SOT compiles train-mode subgraphs around breaks,
jit/sot/opcode_translator/executor/function_graph.py).
"""
from __future__ import annotations

import logging
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import tree_util

logger = logging.getLogger("paddle_tpu.jit.lazy")

_state = threading.local()


class LazyValue:
    """Placeholder for a not-yet-computed array. Knows its aval; forcing
    it flushes the owning segment. Any consumer outside apply_op (numpy
    conversion, a raw jnp op via __jax_array__) transparently forces."""

    __slots__ = ("trace", "shape", "dtype", "_concrete")

    def __init__(self, trace, shape, dtype):
        self.trace = trace
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self._concrete = None

    @property
    def ndim(self):
        return len(self.shape)

    def force(self):
        if self._concrete is None:
            self.trace.flush()
        return self._concrete

    # numpy / jax interop: any direct consumption materialises
    def __array__(self, dtype=None):
        a = np.asarray(self.force())
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self.force()


class _Op:
    __slots__ = ("fn", "arg_plan", "treedef", "out_lazy", "key",
                 "out_tensors", "nograd")

    def __init__(self, fn, arg_plan, treedef, out_lazy, key, nograd=False):
        self.fn = fn
        self.arg_plan = arg_plan      # per leaf: ("lazy", LazyValue) |
        self.treedef = treedef        #           ("in", input_index)
        self.out_lazy = out_lazy      # flat list of LazyValue outputs
        self.key = key                # hashable op identity for memoizing
        self.out_tensors = None       # grad mode: Tensor wrappers (or None)
        self.nograd = nograd          # recorded under no_grad: outputs
                                      # are constants for the segment vjp


def _op_key(fn, statics):
    """Op identity for segment memoization: code object + hashable
    closure constants (unhashable cells — typically captured arrays —
    key by id; stable for long-lived weights)."""
    cells = []
    try:
        closure = fn.__closure__ or ()
    except AttributeError:   # custom_vjp wrappers forward getattr oddly
        closure = ()
    for cell in closure:
        v = cell.cell_contents
        try:
            hash(v)
            cells.append(v)
        except TypeError:
            cells.append(("#id", id(v)))
    try:
        code = fn.__code__
    except AttributeError:
        code = id(fn)
    return (code, tuple(cells), statics)


class SegmentTrace:
    """One capture session (one to_static fallback call)."""

    _cache: dict = {}

    def __init__(self, grad_mode=False):
        self.ops: list[_Op] = []
        self.inputs: list = []        # concrete arrays, in encounter order
        self.input_tensors: list = []  # parallel: Tensor wrapper | None
        self.segments = 0             # flush count (observability)
        self.recorded_ops = 0
        self.grad_mode = grad_mode    # staged autograd: node per segment

    # -- recording ----------------------------------------------------------
    def record(self, fn, leaf_arrays, treedef, op_name, amp_target=None,
               leaves=None):
        orig_fn = fn
        nograd_in_train = False
        if self.grad_mode:
            from .. import framework

            if not framework.is_grad_enabled():
                # a no_grad section inside a training capture: the op
                # joins the segment program but must be a CONSTANT to the
                # segment vjp (eager parity: no node recorded)
                nograd_in_train = True
                fn = _stop_gradient_wrap(fn)
                leaves = None
        if amp_target is not None:
            # fold the AMP cast into the recorded op: the cast then runs
            # both under eval_shape and in the compiled segment, matching
            # the per-op eager fallback's autocast dtypes. Memo key stays
            # derived from the ORIGINAL fn (+ the target) so wrapper
            # identity doesn't defeat segment caching.
            fn = _amp_cast_wrap(fn, amp_target)
        plan, statics, dyn = [], [], []
        for i, a in enumerate(leaf_arrays):
            leaf = leaves[i] if leaves is not None else None
            if isinstance(a, LazyValue):
                if a.trace is not self:
                    # foreign (outer-trace) placeholder: force it — this
                    # trace's segment program can't reference another
                    # trace's graph nodes
                    a.force()
                if a._concrete is not None:       # already flushed earlier
                    plan.append(("in", len(self.inputs)))
                    self.inputs.append(a._concrete)
                    self.input_tensors.append(leaf)
                    dyn.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
                else:
                    plan.append(("lazy", a))
                    dyn.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
            elif hasattr(a, "shape") and hasattr(a, "dtype"):
                plan.append(("in", len(self.inputs)))
                self.inputs.append(a)
                self.input_tensors.append(leaf)
                dyn.append(jax.ShapeDtypeStruct(
                    tuple(a.shape), np.dtype(a.dtype)))
            else:
                plan.append(("static", a))
                statics.append(a if _hashable(a) else repr(a))

        def shaped_call(*dyn_leaves):
            it = iter(dyn_leaves)
            leaves = [p[1] if p[0] == "static" else next(it) for p in plan]
            a2, k2 = tree_util.tree_unflatten(treedef, leaves)
            return fn(*a2, **k2)

        out_shape = jax.eval_shape(shaped_call, *dyn)
        out_leaves, out_tree = tree_util.tree_flatten(out_shape)
        out_lazy = [LazyValue(self, o.shape, o.dtype) for o in out_leaves]
        key = _op_key(orig_fn, tuple(statics))
        if amp_target is not None:
            key = key + (("amp", str(amp_target)),)
        if nograd_in_train:
            key = key + (("nograd",),)
        self.ops.append(_Op(fn, plan, treedef, out_lazy, key,
                            nograd=nograd_in_train))
        self.recorded_ops += 1
        return tree_util.tree_unflatten(out_tree, out_lazy)

    def note_out_tensors(self, tensor_leaves):
        """Grad mode: remember the Tensor wrappers of the LAST recorded
        op's outputs so flush can attach the segment GradNode to them."""
        self.ops[-1].out_tensors = list(tensor_leaves)

    # -- flushing -----------------------------------------------------------
    def flush(self):
        if not self.ops:
            return
        ops, inputs = self.ops, self.inputs
        input_tensors = self.input_tensors
        self.ops, self.inputs, self.input_tensors = [], [], []
        self.segments += 1

        sig = (tuple(op.key for op in ops),
               tuple((tuple(a.shape), str(getattr(a, "dtype", type(a))))
                     for a in inputs))
        entry = self._cache.get(_freeze(sig))
        if entry is None:
            def seg_fn(inputs):
                env = {}
                for op, live in zip(ops, entry_ops):
                    leaves = []
                    for kind, ref in live.arg_plan:
                        if kind == "lazy":
                            leaves.append(env[id(ref)])
                        elif kind == "in":
                            leaves.append(inputs[ref])
                        else:
                            leaves.append(ref)
                    a2, k2 = tree_util.tree_unflatten(live.treedef, leaves)
                    outs = live.fn(*a2, **k2)
                    for lz, val in zip(live.out_lazy,
                                       tree_util.tree_leaves(outs)):
                        env[id(lz)] = val
                return [env[id(lz)] for op in entry_ops
                        for lz in op.out_lazy]

            entry_ops = ops
            entry = jax.jit(seg_fn)
            self._cache[_freeze(sig)] = (entry, ops)
            logger.info("segment compiled: %d ops, %d inputs",
                        len(ops), len(inputs))
            results = entry(inputs)
        else:
            entry, cached_ops = entry
            # replay the CACHED program; map results onto THIS call's
            # placeholders positionally (same op sequence by key)
            results = entry(inputs)
        flat_lazy = [lz for op in ops for lz in op.out_lazy]
        for lz, val in zip(flat_lazy, results):
            lz._concrete = val
        if self.grad_mode:
            self._attach_grad(ops, inputs, input_tensors, entry)

    def _attach_grad(self, ops, inputs, input_tensors, entry):
        """Staged autograd: one GradNode for the whole flushed segment.

        pure_fn re-runs the CACHED jitted segment over the differentiable
        inputs (others captured), so run_vjp's jax.vjp stays one compiled
        forward + one compiled cotangent pull. Output tensors of every
        grad-enabled recorded op share the node, indexed by their flat
        position — the eager engine then stitches across host breaks like
        any other edge."""
        from ..core.dispatch import GradNode
        from ..core.tensor import Tensor

        def _inexact(t):
            return jnp.issubdtype(np.dtype(t._data.dtype), jnp.inexact)

        diff_pos = []
        for i, t in enumerate(input_tensors):
            if (isinstance(t, Tensor) and not t.stop_gradient
                    and _inexact(t)):
                diff_pos.append(i)
        if not diff_pos:
            return
        edges = []
        for i in diff_pos:
            t = input_tensors[i]
            if t._grad_node is not None:
                edges.append(("node", t._grad_node, t._out_index))
            else:
                edges.append(("leaf", t))
        flat_lazy = [lz for op in ops for lz in op.out_lazy]
        out_avals = [(lz.shape, lz.dtype) for lz in flat_lazy]
        out_treedef = tree_util.tree_structure([0] * len(flat_lazy))

        def seg_pure(diff_arrays, _inputs=list(inputs),
                     _pos=tuple(diff_pos), _entry=entry):
            buf = list(_inputs)
            for p, a in zip(_pos, diff_arrays):
                buf[p] = a
            return _entry(buf)

        node = GradNode("segment", seg_pure,
                        [inputs[i] for i in diff_pos],
                        [input_tensors[i] for i in diff_pos],
                        edges, out_avals, out_treedef)
        # Per-op differentiable-input reachability: eager dispatch leaves
        # outputs of all-stop_gradient ops at stop_gradient=True; the
        # segment attach must match (ADVICE r4) — attach the node / flip
        # stop_gradient ONLY for outputs downstream of a differentiable
        # input, and never through no_grad-recorded ops.
        diff_in = set(diff_pos)
        reachable: set[int] = set()
        for op in ops:
            if op.nograd:
                continue
            hit = any(
                (p[0] == "in" and p[1] in diff_in)
                or (p[0] == "lazy" and id(p[1]) in reachable)
                for p in op.arg_plan)
            if hit:
                for lz in op.out_lazy:
                    reachable.add(id(lz))
        idx = 0
        for op in ops:
            touts = op.out_tensors or [None] * len(op.out_lazy)
            for t, lz in zip(touts, op.out_lazy):
                if (isinstance(t, Tensor) and _inexact(t)
                        and id(lz) in reachable):
                    t._grad_node = node
                    t._out_index = idx
                    t.stop_gradient = False
                idx += 1


def _stop_gradient_wrap(fn):
    """Record-time guard for no_grad ops inside a training capture: the
    segment vjp must see their outputs as constants (eager parity: no
    GradNode is recorded under no_grad)."""

    def guarded(*a, **k):
        return tree_util.tree_map(jax.lax.stop_gradient, fn(*a, **k))

    return guarded


def _amp_cast_wrap(fn, target):
    """Wrap an op fn so float array args are cast to ``target`` first —
    the in-graph form of dispatch._maybe_autocast (the leaf rule is the
    SHARED dispatch._cast_leaf, so capture-mode numerics track eager)."""
    from ..core.dispatch import _cast_leaf

    target = np.dtype(target)

    def casted(*a2, **k2):
        leaves, td = tree_util.tree_flatten((a2, k2))
        out = [_cast_leaf(a, target) for a in leaves]
        aa, kk = tree_util.tree_unflatten(td, out)
        return fn(*aa, **kk)

    return casted


def _hashable(v):
    try:
        hash(v)
        return True
    except TypeError:
        return False


def _freeze(sig):
    try:
        hash(sig)
        return sig
    except TypeError:
        return repr(sig)


# ---------------------------------------------------------------- context
def lazy_active() -> bool:
    return getattr(_state, "trace", None) is not None


def current_trace() -> SegmentTrace | None:
    return getattr(_state, "trace", None)


class segment_capture:
    """Context manager: run a python function with op-segment capture."""

    def __init__(self, grad_mode=False):
        self.grad_mode = grad_mode

    def __enter__(self):
        self.prev = getattr(_state, "trace", None)
        _state.trace = SegmentTrace(grad_mode=self.grad_mode)
        return _state.trace

    def __exit__(self, *exc):
        trace = _state.trace
        _state.trace = self.prev
        if exc[0] is None:
            trace.flush()        # materialise anything still pending
        return False


def materialize_tree(out):
    """Force every LazyValue left in a result pytree (call on the capture
    result AFTER the context exits — flush() has filled them)."""
    from ..core.tensor import Tensor

    def fix(t):
        if isinstance(t, Tensor) and isinstance(t._data, LazyValue):
            t._data = t._data.force()
        return t

    return tree_util.tree_map(
        fix, out, is_leaf=lambda x: isinstance(x, Tensor))
