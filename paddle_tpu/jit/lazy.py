"""Segment capture: partial-graph compilation around graph breaks.

Capability slot: the reference's SOT compiles the subgraphs AROUND a
data-dependent break and stitches them (jit/sot/opcode_translator/executor/
function_graph.py) — one stray ``if tensor.item():`` costs one host sync,
not the whole function's compilation.

TPU-native design (LazyTensor-style, no bytecode rewriting): when a
``to_static`` call site is known to graph-break, the fallback no longer
dispatches op-by-op. Ops accumulate into a SEGMENT — a recorded graph of
apply_op calls whose outputs are placeholder `LazyValue`s (shape/dtype via
``jax.eval_shape``, no device work). The first *value* access (``.item()``,
``bool()``, ``.numpy()`` — the break itself) flushes the segment: the
recorded graph compiles to ONE jitted program (memoized per op-sequence +
input avals), runs, and fills every placeholder. Execution then continues
eagerly through the Python branch, and the ops after it accumulate into a
new segment — prefix compiled, break on host, suffix compiled.

Grad-recording calls bypass capture (the eager autograd engine needs
concrete arrays per op); ``to_static``'s compiled path is no-grad, so the
fallback matches its semantics.
"""
from __future__ import annotations

import logging
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import tree_util

logger = logging.getLogger("paddle_tpu.jit.lazy")

_state = threading.local()


class LazyValue:
    """Placeholder for a not-yet-computed array. Knows its aval; forcing
    it flushes the owning segment. Any consumer outside apply_op (numpy
    conversion, a raw jnp op via __jax_array__) transparently forces."""

    __slots__ = ("trace", "shape", "dtype", "_concrete")

    def __init__(self, trace, shape, dtype):
        self.trace = trace
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self._concrete = None

    @property
    def ndim(self):
        return len(self.shape)

    def force(self):
        if self._concrete is None:
            self.trace.flush()
        return self._concrete

    # numpy / jax interop: any direct consumption materialises
    def __array__(self, dtype=None):
        a = np.asarray(self.force())
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self.force()


class _Op:
    __slots__ = ("fn", "arg_plan", "treedef", "out_lazy", "key")

    def __init__(self, fn, arg_plan, treedef, out_lazy, key):
        self.fn = fn
        self.arg_plan = arg_plan      # per leaf: ("lazy", LazyValue) |
        self.treedef = treedef        #           ("in", input_index)
        self.out_lazy = out_lazy      # flat list of LazyValue outputs
        self.key = key                # hashable op identity for memoizing


def _op_key(fn, statics):
    """Op identity for segment memoization: code object + hashable
    closure constants (unhashable cells — typically captured arrays —
    key by id; stable for long-lived weights)."""
    cells = []
    try:
        closure = fn.__closure__ or ()
    except AttributeError:   # custom_vjp wrappers forward getattr oddly
        closure = ()
    for cell in closure:
        v = cell.cell_contents
        try:
            hash(v)
            cells.append(v)
        except TypeError:
            cells.append(("#id", id(v)))
    try:
        code = fn.__code__
    except AttributeError:
        code = id(fn)
    return (code, tuple(cells), statics)


class SegmentTrace:
    """One capture session (one to_static fallback call)."""

    _cache: dict = {}

    def __init__(self):
        self.ops: list[_Op] = []
        self.inputs: list = []        # concrete arrays, in encounter order
        self.segments = 0             # flush count (observability)
        self.recorded_ops = 0

    # -- recording ----------------------------------------------------------
    def record(self, fn, leaf_arrays, treedef, op_name, amp_target=None):
        orig_fn = fn
        if amp_target is not None:
            # fold the AMP cast into the recorded op: the cast then runs
            # both under eval_shape and in the compiled segment, matching
            # the per-op eager fallback's autocast dtypes. Memo key stays
            # derived from the ORIGINAL fn (+ the target) so wrapper
            # identity doesn't defeat segment caching.
            fn = _amp_cast_wrap(fn, amp_target)
        plan, statics, dyn = [], [], []
        for a in leaf_arrays:
            if isinstance(a, LazyValue):
                if a.trace is not self:
                    # foreign (outer-trace) placeholder: force it — this
                    # trace's segment program can't reference another
                    # trace's graph nodes
                    a.force()
                if a._concrete is not None:       # already flushed earlier
                    plan.append(("in", len(self.inputs)))
                    self.inputs.append(a._concrete)
                    dyn.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
                else:
                    plan.append(("lazy", a))
                    dyn.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
            elif hasattr(a, "shape") and hasattr(a, "dtype"):
                plan.append(("in", len(self.inputs)))
                self.inputs.append(a)
                dyn.append(jax.ShapeDtypeStruct(
                    tuple(a.shape), np.dtype(a.dtype)))
            else:
                plan.append(("static", a))
                statics.append(a if _hashable(a) else repr(a))

        def shaped_call(*dyn_leaves):
            it = iter(dyn_leaves)
            leaves = [p[1] if p[0] == "static" else next(it) for p in plan]
            a2, k2 = tree_util.tree_unflatten(treedef, leaves)
            return fn(*a2, **k2)

        out_shape = jax.eval_shape(shaped_call, *dyn)
        out_leaves, out_tree = tree_util.tree_flatten(out_shape)
        out_lazy = [LazyValue(self, o.shape, o.dtype) for o in out_leaves]
        key = _op_key(orig_fn, tuple(statics))
        if amp_target is not None:
            key = key + (("amp", str(amp_target)),)
        self.ops.append(_Op(fn, plan, treedef, out_lazy, key))
        self.recorded_ops += 1
        return tree_util.tree_unflatten(out_tree, out_lazy)

    # -- flushing -----------------------------------------------------------
    def flush(self):
        if not self.ops:
            return
        ops, inputs = self.ops, self.inputs
        self.ops, self.inputs = [], []
        self.segments += 1

        sig = (tuple(op.key for op in ops),
               tuple((tuple(a.shape), str(getattr(a, "dtype", type(a))))
                     for a in inputs))
        entry = self._cache.get(_freeze(sig))
        if entry is None:
            def seg_fn(inputs):
                env = {}
                for op, live in zip(ops, entry_ops):
                    leaves = []
                    for kind, ref in live.arg_plan:
                        if kind == "lazy":
                            leaves.append(env[id(ref)])
                        elif kind == "in":
                            leaves.append(inputs[ref])
                        else:
                            leaves.append(ref)
                    a2, k2 = tree_util.tree_unflatten(live.treedef, leaves)
                    outs = live.fn(*a2, **k2)
                    for lz, val in zip(live.out_lazy,
                                       tree_util.tree_leaves(outs)):
                        env[id(lz)] = val
                return [env[id(lz)] for op in entry_ops
                        for lz in op.out_lazy]

            entry_ops = ops
            entry = jax.jit(seg_fn)
            self._cache[_freeze(sig)] = (entry, ops)
            logger.info("segment compiled: %d ops, %d inputs",
                        len(ops), len(inputs))
            results = entry(inputs)
        else:
            entry, cached_ops = entry
            # replay the CACHED program; map results onto THIS call's
            # placeholders positionally (same op sequence by key)
            results = entry(inputs)
        flat_lazy = [lz for op in ops for lz in op.out_lazy]
        for lz, val in zip(flat_lazy, results):
            lz._concrete = val


def _amp_cast_wrap(fn, target):
    """Wrap an op fn so float array args are cast to ``target`` first —
    the in-graph form of dispatch._maybe_autocast (the leaf rule is the
    SHARED dispatch._cast_leaf, so capture-mode numerics track eager)."""
    from ..core.dispatch import _cast_leaf

    target = np.dtype(target)

    def casted(*a2, **k2):
        leaves, td = tree_util.tree_flatten((a2, k2))
        out = [_cast_leaf(a, target) for a in leaves]
        aa, kk = tree_util.tree_unflatten(td, out)
        return fn(*aa, **kk)

    return casted


def _hashable(v):
    try:
        hash(v)
        return True
    except TypeError:
        return False


def _freeze(sig):
    try:
        hash(sig)
        return sig
    except TypeError:
        return repr(sig)


# ---------------------------------------------------------------- context
def lazy_active() -> bool:
    return getattr(_state, "trace", None) is not None


def current_trace() -> SegmentTrace | None:
    return getattr(_state, "trace", None)


class segment_capture:
    """Context manager: run a python function with op-segment capture."""

    def __enter__(self):
        self.prev = getattr(_state, "trace", None)
        _state.trace = SegmentTrace()
        return _state.trace

    def __exit__(self, *exc):
        trace = _state.trace
        _state.trace = self.prev
        if exc[0] is None:
            trace.flush()        # materialise anything still pending
        return False


def materialize_tree(out):
    """Force every LazyValue left in a result pytree (call on the capture
    result AFTER the context exits — flush() has filled them)."""
    from ..core.tensor import Tensor

    def fix(t):
        if isinstance(t, Tensor) and isinstance(t._data, LazyValue):
            t._data = t._data.force()
        return t

    return tree_util.tree_map(
        fix, out, is_leaf=lambda x: isinstance(x, Tensor))
