"""Context parallelism: ring attention + Ulysses (DeepSpeed-style) all-to-all.

The reference has NO ring attention / Ulysses in core (SURVEY §5 long-context:
verified gap — building blocks only: the "sep" topology axis, reshard engine,
p2p groups). Here long-context is first-class, built the TPU way:

- :func:`ring_attention` — blockwise attention with online-softmax state,
  rotating k/v shards around the "sep" mesh axis with ``lax.ppermute`` so
  the transfers ride adjacent-chip ICI links and overlap with the block
  matmuls. Memory per chip stays O(S_local); no device ever holds full kv.
- :func:`ulysses_attention` — ``lax.all_to_all`` exchanges the seq shard for
  a head shard (seq-sharded -> head-sharded), runs dense local attention
  over the full sequence, and exchanges back. Cheaper than the ring when
  heads >= cp degree and ICI all-to-all bandwidth is plentiful.

Both are shard_map-level functions: inputs are the LOCAL [B, S_local, H, D]
blocks, called inside ``shard_map`` / jit over a mesh carrying the given
axis. Gradients flow through ``ppermute``/``all_to_all`` via jax AD (their
transposes are the reverse rotation / inverse exchange).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """One kv-block contribution in online-softmax form.

    q: [B, Sq, H, D], k/v: [B, Sk, H, D], mask: broadcastable [Sq, Sk] bool
    or None. Returns (acc [B,H,Sq,D] f32 unnormalised, m [B,H,Sq,1], l).
    """
    s = jnp.einsum(
        "bshd,bthd->bhst", q.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # all-masked rows stay finite
    p = jnp.exp(s - m)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhst,bthd->bhsd", p, v.astype(jnp.float32))
    return acc, m, l


# ---------------------------------------------------------------------------
# flash-kernel ring attention (the TPU long-context training path)
# ---------------------------------------------------------------------------
def _flash_with_lse(q, k, v, causal, scale, interpret=None):
    """[B, S, H, D] flash forward returning (o, lse [B, H, S]) — the
    per-ring-step building block (lse merges across steps)."""
    from ..ops.pallas import use_interpret
    from ..ops.pallas.flash_attention import _fwd, from_bh, to_bh

    if interpret is None:
        interpret = use_interpret()
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hq % hk != 0:
        raise ValueError(
            f"ring flash attention: q heads ({hq}) must be a multiple of "
            f"kv heads ({hk})")
    o, lse = _fwd(to_bh(q, hq), to_bh(k, hk), to_bh(v, hk), float(scale),
                  bool(causal), bool(interpret), hq, hk)
    return from_bh(o, b, hq), lse.reshape(b, hq, sq)


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, interpret):
    """At step t the device holds kv block src = (idx - t) % n; under the
    global causal mask the step is 'full' (src < idx), 'diag' (src == idx)
    or fully-masked 'skip' (src > idx)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    acc = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full((b, q.shape[2], s_loc), NEG_INF, jnp.float32)

    kt, vt = k, v
    for t in range(n):
        src = (idx - t) % n

        def full_case(q, kt, vt):
            return _flash_with_lse(q, kt, vt, False, scale, interpret)

        def diag_case(q, kt, vt):
            return _flash_with_lse(q, kt, vt, True, scale, interpret)

        def skip_case(q, kt, vt):
            return (jnp.zeros(q.shape, q.dtype),
                    jnp.full((b, q.shape[2], s_loc), NEG_INF, jnp.float32))

        if causal:
            o_blk, lse_blk = jax.lax.cond(
                src == idx,
                diag_case,
                lambda q, kt, vt: jax.lax.cond(
                    src < idx, full_case, skip_case, q, kt, vt),
                q, kt, vt)
        else:
            o_blk, lse_blk = full_case(q, kt, vt)

        # merge via lse (numerically the online-softmax combine)
        lse_new = jnp.logaddexp(lse, lse_blk)
        a = jnp.exp(lse - lse_new)[..., None]          # [B, H, S, 1]
        bta = jnp.exp(lse_blk - lse_new)[..., None]
        a = jnp.transpose(a, (0, 2, 1, 3))             # -> [B, S, H, 1]
        bta = jnp.transpose(bta, (0, 2, 1, 3))
        acc = acc * a + o_blk.astype(jnp.float32) * bta
        lse = lse_new
        if t != n - 1:
            kt = jax.lax.ppermute(kt, axis_name, perm)
            vt = jax.lax.ppermute(vt, axis_name, perm)
    return acc.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_flash_attention(q, k, v, axis_name="sep", causal=False, scale=None,
                         interpret=None):
    """Ring attention whose per-block math runs the pallas flash kernels —
    O(S_local) memory AND no materialised score matrices. Call inside
    shard_map with seq-sharded [B, S_loc, H, D] blocks."""
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                  interpret)
    return out


def _ring_flash_fwd_rule(q, k, v, axis_name, causal, scale, interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                    interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd_rule(axis_name, causal, scale, interpret, res, dout):
    """Ring backward: replay the kv rotation; per step run the flash bwd
    kernels against the GLOBAL lse (p = exp(s - lse) is exact for the
    full softmax, so per-block dq/dk/dv sum to the true grads). dk/dv
    accumulators travel WITH their kv block and come home after a final
    rotation."""
    from ..ops.pallas import use_interpret
    from ..ops.pallas.flash_attention import _bwd, from_bh as _from_bh, to_bh as _to_bh

    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    interp = use_interpret() if interpret is None else interpret
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    hk = k.shape[2]
    perm = [(j, (j + 1) % n) for j in range(n)]

    def to_bh(x, hh):
        return _to_bh(x, hh)

    def from_bh(x, hh):
        return _from_bh(x, b, hh)

    q_bh, o_bh, do_bh = to_bh(q, h), to_bh(out, h), to_bh(dout, h)
    lse_bh = lse.reshape(b * h, s_loc)

    dq = jnp.zeros(q.shape, jnp.float32)
    dk_acc = jnp.zeros(k.shape, jnp.float32)   # travels with kt/vt
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    kt, vt = k, v
    for t in range(n):
        src = (idx - t) % n

        def run(causal_flag, q_bh=q_bh, o_bh=o_bh, do_bh=do_bh,
                lse_bh=lse_bh):
            def f(kt, vt):
                dq_b, dk_b, dv_b = _bwd(
                    q_bh, to_bh(kt, hk), to_bh(vt, hk), o_bh, lse_bh,
                    do_bh, float(scale), causal_flag, bool(interp), h, hk)
                return (from_bh(dq_b, h).astype(jnp.float32),
                        from_bh(dk_b, hk).astype(jnp.float32),
                        from_bh(dv_b, hk).astype(jnp.float32))
            return f

        def skip(kt, vt):
            return (jnp.zeros(q.shape, jnp.float32),
                    jnp.zeros(k.shape, jnp.float32),
                    jnp.zeros(v.shape, jnp.float32))

        if causal:
            dq_b, dk_b, dv_b = jax.lax.cond(
                src == idx,
                run(True),
                lambda kt, vt: jax.lax.cond(src < idx, run(False), skip,
                                            kt, vt),
                kt, vt)
        else:
            dq_b, dk_b, dv_b = run(False)(kt, vt)

        dq = dq + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        # rotate kv AND its grad accumulators together; after the loop one
        # more rotation brings every block's grads back to its owner
        kt = jax.lax.ppermute(kt, axis_name, perm)
        vt = jax.lax.ppermute(vt, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


ring_flash_attention.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def ring_attention(q, k, v, axis_name="sep", causal=False, scale=None):
    """Ring attention over seq-sharded q/k/v local blocks [B, S_loc, H, D].

    Must be called inside shard_map/jit with ``axis_name`` bound in the mesh.
    Dispatches the per-block math to the pallas flash kernels when the
    local shape is eligible (TPU); the jnp online-softmax path otherwise.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _default_local_attn(q.shape) is not None:
        return ring_flash_attention(q, k, v, axis_name, causal, scale, None)
    return _ring_attention_jnp(q, k, v, axis_name=axis_name, causal=causal,
                               scale=scale)


def _ring_attention_jnp(q, k, v, axis_name="sep", causal=False, scale=None):
    """jnp online-softmax ring (fallback path)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    rows = jnp.arange(s_loc)[:, None]
    cols = jnp.arange(s_loc)[None, :]
    perm = [(j, (j + 1) % n) for j in range(n)]

    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m = jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc, 1), jnp.float32)

    kt, vt = k, v
    for t in range(n):
        src = (idx - t) % n  # which shard's kv we hold this step
        if causal:
            # global causal mask between my q rows and the src kv cols
            q_off = idx * s_loc
            k_off = src * s_loc
            mask = (rows + q_off) >= (cols + k_off)
        else:
            mask = None
        a_blk, m_blk, l_blk = _block_attn(q, kt, vt, scale, mask)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_blk - m_new)
        acc = acc * alpha + a_blk * beta
        l = l * alpha + l_blk * beta
        m = m_new
        if t != n - 1:
            kt = jax.lax.ppermute(kt, axis_name, perm)
            vt = jax.lax.ppermute(vt, axis_name, perm)

    out = acc / jnp.where(l == 0.0, 1.0, l)
    return jnp.einsum("bhsd->bshd", out).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name="sep", causal=False, scale=None,
                      attn_fn=None):
    """Ulysses: all-to-all seq<->head exchange around dense local attention.

    Local blocks [B, S_loc, H, D] with H divisible by the axis size. After
    the exchange each device holds [B, S_full, H/n, D] and runs ``attn_fn``
    (default: naive sdpa; pass the pallas flash kernel on TPU).
    """
    n = jax.lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    def seq_to_head(x):
        # [B, S_loc, H, D] -> [B, n*S_loc, H/n, D]
        x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                               tiled=True)
        return x

    def head_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    attn_fn = attn_fn or _default_local_attn(qg.shape)
    if attn_fn is None:
        sq = qg.shape[1]
        mask = None
        if causal:
            mask = jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :]
        a, m, l = _block_attn(qg, kg, vg, scale, mask)
        out = (a / jnp.where(l == 0.0, 1.0, l)).astype(q.dtype)
        og = jnp.einsum("bhsd->bshd", out)
    else:
        og = attn_fn(qg, kg, vg, causal=causal, scale=scale)
    return head_to_seq(og)


def _default_local_attn(q_shape):
    """Pick the local-attention kernel for the post-exchange block: the
    differentiable pallas flash kernel on TPU when the shape tiles (it runs
    fine inside shard_map — kernels are per-device), else None for the
    jnp online-softmax fallback. Eligibility is THE shared `_use_pallas`
    predicate so the dispatch never drifts from the kernel's constraints."""
    from ..nn.functional.flash_attention import _use_pallas

    if _use_pallas(q_shape):
        from ..ops.pallas import flash_attention as _flash_kernel

        return _flash_kernel
    return None


# ------------------------------------------------------------------ API level

def context_parallel_attention(query, key, value, mesh=None, causal=True,
                               strategy="ring", axis_name="sep"):
    """Framework-level entry over DistTensor/Tensor values sharded on seq.

    Builds the shard_map over the fleet/global mesh and applies the chosen
    cp strategy. ``strategy``: "ring" | "ulysses".
    """
    from ..core.dispatch import apply_op
    from .fleet import get_fleet_mesh

    if mesh is None:
        mesh = get_fleet_mesh()
    jmesh = mesh.jax_mesh if hasattr(mesh, "jax_mesh") else mesh
    if axis_name not in jmesh.axis_names:
        raise ValueError(f"mesh has no '{axis_name}' axis: {jmesh.axis_names}")
    if strategy not in ("ring", "ulysses"):
        raise ValueError(f"unknown cp strategy {strategy!r} "
                         "(expected 'ring' or 'ulysses')")

    mapped = _mapped_cp(jmesh, strategy, bool(causal), axis_name)
    spec = PartitionSpec(None, axis_name, None, None)

    def _cp(q, k, v):
        q = jax.device_put(q, NamedSharding(jmesh, spec))
        k = jax.device_put(k, NamedSharding(jmesh, spec))
        v = jax.device_put(v, NamedSharding(jmesh, spec))
        return mapped(q, k, v)

    return apply_op(_cp, query, key, value, _op_name="context_parallel_attention")


@functools.lru_cache(maxsize=64)
def _mapped_cp(jmesh, strategy, causal, axis_name):
    """Memoised shard_map wrapper so repeated eager calls hit jax's
    compilation cache instead of retracing."""
    fn = ring_attention if strategy == "ring" else ulysses_attention
    spec = PartitionSpec(None, axis_name, None, None)
    # check_vma=False: BOTH strategies can dispatch to the pallas flash
    # kernels (ring via ring_flash_attention, ulysses as local attention),
    # and pallas out_shapes can't annotate varying mesh axes
    return jax.shard_map(
        functools.partial(fn, axis_name=axis_name, causal=causal),
        mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
