"""Per-op cost model + sharding-placement planner over jaxprs.

Capability slot: the reference's auto-parallel static cost stack —
per-op cost classes (``python/paddle/distributed/auto_parallel/static/
cost/``) and the planner/tuner that scores reshard placements
(``static/tuner/``). The round-2 auto_tuner models whole-config
memory/roofline only; this module sees INDIVIDUAL operations:

- `jaxpr_op_costs(fn, *args)`: per-equation FLOPs / bytes (dot_general
  and conv get exact formulas, elementwise/reduce get byte counts;
  control-flow bodies are walked recursively with trip-count
  multipliers).
- `OpCostModel`: eqn -> seconds on a device roofline (MXU peak vs HBM
  bandwidth).
- `plan_matmul_shardings(...)`: for every dot_general, score the
  classical placements — split M (data-parallel-like), split N
  (column-parallel), split K (row-parallel + psum), replicate — with
  compute/degree + reshard + collective costs over the ICI, and return
  the argmin per op. This is the per-op reshard-placement decision the
  whole-config roofline is blind to (VERDICT r2 Missing #5).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
from jax import tree_util

__all__ = ["jaxpr_op_costs", "OpCostModel", "plan_matmul_shardings",
           "MatmulPlan"]


def _aval_bytes(v):
    aval = v.aval
    if not hasattr(aval, "shape"):
        return 0
    return int(np.prod(aval.shape or (1,))) * np.dtype(aval.dtype).itemsize


def _dot_flops(eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    m = int(np.prod([d for i, d in enumerate(lhs.shape)
                     if i not in lc and i not in lb] or [1]))
    n = int(np.prod([d for i, d in enumerate(rhs.shape)
                     if i not in rc and i not in rb] or [1]))
    k = int(np.prod([lhs.shape[i] for i in lc] or [1]))
    b = int(np.prod([lhs.shape[i] for i in lb] or [1]))
    return 2 * b * m * n * k


def _conv_flops(eqn):
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    # flops per output element = 2 * prod(kernel spatial) * C_in/groups
    groups = eqn.params.get("feature_group_count", 1)
    dn = eqn.params["dimension_numbers"]
    k_spatial = [rhs.shape[i] for i in dn.rhs_spec[2:]]
    cin = rhs.shape[dn.rhs_spec[1]]
    return (2 * int(np.prod(out.shape)) * int(np.prod(k_spatial or [1]))
            * cin // max(groups, 1))


def _eqn_cost(eqn, mult=1):
    """(flops, bytes) of one equation; recurses into call-like prims."""
    name = eqn.primitive.name
    sub = []
    if name in ("pjit", "jit", "closed_call", "custom_jvp_call",
                "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                "remat2", "checkpoint"):
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if inner is not None:
            sub = [(inner, mult)]
    elif name == "scan":
        sub = [(eqn.params["jaxpr"], mult * int(eqn.params["length"]))]
    elif name == "while":
        # unknowable trip count: count ONE iteration (documented)
        sub = [(eqn.params["body_jaxpr"], mult)]
    elif name == "cond":
        # worst-case branch
        sub = [(b, mult) for b in eqn.params["branches"]]

    if sub:
        flops = bytes_ = 0
        best = 0
        for inner, m in sub:
            f, by = _jaxpr_cost(getattr(inner, "jaxpr", inner), m)
            if name == "cond":
                best = max(best, f)
                bytes_ = max(bytes_, by)
            else:
                flops += f
                bytes_ += by
        if name == "cond":
            flops = best
        return flops, bytes_

    io_bytes = mult * (sum(_aval_bytes(v) for v in eqn.invars
                           if hasattr(v, "aval"))
                       + sum(_aval_bytes(v) for v in eqn.outvars))
    if name == "dot_general":
        return mult * _dot_flops(eqn), io_bytes
    if name == "conv_general_dilated":
        return mult * _conv_flops(eqn), io_bytes
    # elementwise / reduce / data movement: bandwidth-bound, ~1 flop/elt
    out_elems = sum(int(np.prod(v.aval.shape or (1,)))
                    for v in eqn.outvars if hasattr(v.aval, "shape"))
    return mult * out_elems, io_bytes


def _jaxpr_cost(jaxpr, mult=1):
    flops = bytes_ = 0
    for eqn in jaxpr.eqns:
        f, b = _eqn_cost(eqn, mult)
        flops += f
        bytes_ += b
    return flops, bytes_


def jaxpr_op_costs(fn, *example_args):
    """Trace `fn` and return (rows, totals): one row per top-level
    equation with {prim, flops, bytes}, plus {"flops", "bytes"} totals
    (control-flow bodies folded into their owning row)."""
    flat = tree_util.tree_leaves(example_args)
    closed = jax.make_jaxpr(
        lambda *a: tree_util.tree_leaves(
            fn(*tree_util.tree_unflatten(
                tree_util.tree_structure(example_args), a))))(*flat)
    rows = []
    for i, eqn in enumerate(closed.jaxpr.eqns):
        f, b = _eqn_cost(eqn)
        rows.append({"index": i, "prim": eqn.primitive.name,
                     "flops": int(f), "bytes": int(b)})
    totals = {"flops": sum(r["flops"] for r in rows),
              "bytes": sum(r["bytes"] for r in rows)}
    return rows, totals


@dataclass
class OpCostModel:
    """Roofline per op: time = max(flops/peak, bytes/hbm)."""

    peak_tflops: float = 197.0      # v5e bf16
    hbm_gbps: float = 819.0
    ici_gbps: float = 90.0

    def eqn_seconds(self, flops, bytes_):
        return max(flops / (self.peak_tflops * 1e12),
                   bytes_ / (self.hbm_gbps * 1e9))

    def comm_seconds(self, bytes_, degree):
        """Ring collective over `degree` devices on ICI."""
        if degree <= 1 or bytes_ == 0:
            return 0.0
        return bytes_ * 2 * (degree - 1) / degree / (self.ici_gbps * 1e9)


@dataclass
class MatmulPlan:
    index: int            # top-level eqn index
    m: int
    n: int
    k: int
    choice: str           # "split_m" | "split_n" | "split_k" | "replicate"
    est_ms: dict          # choice -> estimated milliseconds


def plan_matmul_shardings(fn, *example_args, axis_size=8,
                          in_sharded="replicated", model=None,
                          out_mappings=None):
    """Score the classical per-matmul placements and pick the cheapest.

    in_sharded: how operands currently live — "replicated" (both full on
    every device) or "rows" (lhs already split on M, the data-parallel
    ambient). Costs per choice:
      split_m:   compute/d; reshard lhs only if not already row-split.
      split_n:   compute/d; rhs col-shard free (weights placed once);
                 output col-sharded — no collective.
      split_k:   compute/d; + psum of the [M, N] partial output.
      replicate: full compute, no comm.
    Returns [MatmulPlan] for every top-level dot_general, mirroring the
    reference planner's per-op dist_attr decisions
    (auto_parallel/static/cost + tuner).
    """
    model = model or OpCostModel()
    # reverse completion: an output-side annotation (the loss, a
    # col-sharded downstream consumer) flows backward through
    # reshape/transpose/elementwise chains and FORCES the reached
    # matmuls' output placements (split_m / split_n) before costing
    flat = tree_util.tree_leaves(example_args)
    closed = jax.make_jaxpr(
        lambda *a: tree_util.tree_leaves(
            fn(*tree_util.tree_unflatten(
                tree_util.tree_structure(example_args), a))))(*flat)
    # one trace: the completion pass walks the SAME jaxpr the costing
    # loop enumerates, so forced eqn indices can never misalign
    forced = {}
    if out_mappings is not None:
        forced = complete_output_annotation(
            fn, *example_args, out_mappings=out_mappings,
            axis_size=axis_size, _closed=closed)
    plans = []
    d = axis_size
    # chain propagation: a split_n matmul leaves its output COLUMN-sharded
    # — a downstream matmul contracting that value gets split_k for free
    # (Megatron's colwise->rowwise pair), while any other choice must pay
    # an all-gather of the sharded operand first. Elementwise eqns pass
    # the annotation through (same shape in->out).
    col_sharded: set = set()
    for i, eqn in enumerate(closed.jaxpr.eqns):
        if eqn.primitive.name != "dot_general":
            ins = [v for v in eqn.invars
                   if hasattr(v, "aval") and id(v) in col_sharded]
            if ins and eqn.outvars:
                for ov in eqn.outvars:
                    if (hasattr(ov.aval, "shape")
                            and ov.aval.shape == ins[0].aval.shape):
                        col_sharded.add(id(ov))
            continue
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = (v.aval for v in eqn.invars[:2])
        itemsize = np.dtype(lhs.dtype).itemsize
        m = int(np.prod([dd for j, dd in enumerate(lhs.shape)
                         if j not in lc and j not in lb] or [1]))
        n = int(np.prod([dd for j, dd in enumerate(rhs.shape)
                         if j not in rc and j not in rb] or [1]))
        k = int(np.prod([lhs.shape[j] for j in lc] or [1]))
        b = int(np.prod([lhs.shape[j] for j in lb] or [1]))
        # batch dims scale EVERYTHING: flops, operand/output bytes, and
        # the split_k psum payload (attention-style matmuls are exactly
        # where mis-costing flips the placement decision)
        flops = 2 * b * m * n * k
        io_bytes = b * (m * k + k * n + m * n) * itemsize
        compute = model.eqn_seconds(flops / d, io_bytes / d)
        lhs_col = id(eqn.invars[0]) in col_sharded
        # operand already k-sharded: gathering it back costs one
        # all_gather of the full lhs; split_k skips that entirely
        gather_lhs = (model.comm_seconds(
            b * m * k * itemsize * (d - 1) / d, d) if lhs_col else 0.0)
        est = {
            "split_m": compute + gather_lhs + (
                0.0 if in_sharded == "rows"
                else model.comm_seconds(
                    b * m * k * itemsize * (d - 1) / d, d)),
            "split_n": compute + gather_lhs + (model.comm_seconds(
                b * m * k * itemsize, d) if in_sharded == "rows" else 0.0),
            "split_k": compute + model.comm_seconds(b * m * n * 4, d),
            "replicate": model.eqn_seconds(flops, io_bytes) + gather_lhs,
        }
        est_ms = {c: t * 1e3 for c, t in est.items()}
        choice = min(est_ms, key=est_ms.get)
        dm = forced.get(i)
        if dm is not None:
            # the annotation binds: n-dim sharded -> split_n, m-dim
            # sharded -> split_m; a fully-replicated output does NOT
            # exclude split_k (its psum result is replicated). Output
            # dims are [batch..., m?, n?] — matvec (no n) must map the
            # trailing dim to m, not n.
            has_m = len(lhs.shape) - len(lc) - len(lb) > 0
            has_n = len(rhs.shape) - len(rc) - len(rb) > 0
            if has_n and dm[-1] >= 0:
                choice = "split_n"
            elif has_m and len(dm) >= (2 if has_n else 1) and \
                    dm[-2 if has_n else -1] >= 0:
                choice = "split_m"
        if choice == "split_n":
            for ov in eqn.outvars:
                col_sharded.add(id(ov))
        plans.append(MatmulPlan(i, m, n, k, choice, est_ms))
    return plans


# ---------------------------------------------------------------------------
# Reverse completion: flow an OUTPUT-side annotation backward to the
# producing matmuls (parity: the reference planner's InferSpmdReverse
# completion pass — phi/infermeta/spmd_rules/matmul.h:30 registers
# forward AND reverse per op; completion uses reverse so an annotation
# on the loss / a downstream value reaches producers through
# reshape/transpose/elementwise chains).
# ---------------------------------------------------------------------------
_ELTWISE_PRIMS = frozenset((
    "add", "sub", "mul", "div", "max", "min", "pow", "integer_pow",
    "exp", "log", "tanh", "logistic", "neg", "abs", "sqrt", "rsqrt",
    "convert_element_type", "stop_gradient", "select_n", "sign",
    "erf", "floor", "ceil", "round", "clamp", "custom_jvp_call",
))


def complete_output_annotation(fn, *example_args, out_mappings,
                               axis_size=8, _closed=None):
    """Backward pass over the traced jaxpr: seed the function outputs
    with `out_mappings` (one dims_mapping per output leaf, or one list
    for a single output) and run the registered infer_reverse rules
    through transpose/reshape/elementwise/reduction eqns. Returns
    {top_level_eqn_index: output_dims_mapping} for every equation the
    annotation reached. Unknown primitives stop the flow (conservative,
    same as the reference's fallback)."""
    from .spmd_rules import DistTensorSpec, get_spmd_rule

    if _closed is None:
        flat = tree_util.tree_leaves(example_args)
        _closed = jax.make_jaxpr(
            lambda *a: tree_util.tree_leaves(
                fn(*tree_util.tree_unflatten(
                    tree_util.tree_structure(example_args), a))))(*flat)
    jx = _closed.jaxpr
    if out_mappings and not isinstance(out_mappings[0], (list, tuple)):
        out_mappings = [out_mappings]
    if len(out_mappings) != len(jx.outvars):
        raise ValueError(
            f"{len(out_mappings)} out_mappings for {len(jx.outvars)} "
            "output leaves — one dims_mapping per flattened output")
    known = {}
    for v, dm in zip(jx.outvars, out_mappings):
        if not hasattr(v, "aval"):
            continue
        if len(dm) != len(v.aval.shape):
            raise ValueError(
                f"out_mappings entry {dm} has rank {len(dm)} but the "
                f"output leaf has shape {tuple(v.aval.shape)} — one "
                "dims_mapping per output leaf, matching its rank")
        known[id(v)] = list(dm)
    reached = {}
    for i in reversed(range(len(jx.eqns))):
        eqn = jx.eqns[i]
        dm = None
        for ov in eqn.outvars:
            if id(ov) in known:
                dm = known[id(ov)]
                break
        if dm is None:
            continue
        reached[i] = list(dm)
        name = eqn.primitive.name
        out_spec = DistTensorSpec(list(eqn.outvars[0].aval.shape), dm)
        ivars = [v for v in eqn.invars if hasattr(v, "aval")]
        in_shapes = [list(v.aval.shape) for v in ivars]
        try:
            if name == "transpose":
                ins, _ = get_spmd_rule("transpose").infer_reverse(
                    [in_shapes[0]], [out_spec],
                    perm=list(eqn.params["permutation"]))
                known[id(ivars[0])] = ins[0].dims_mapping
            elif name == "reshape":
                ins, _ = get_spmd_rule("reshape").infer_reverse(
                    [in_shapes[0]], [out_spec])
                known[id(ivars[0])] = ins[0].dims_mapping
            elif name in ("reduce_sum", "reduce_max", "reduce_min",
                          "reduce_prod"):
                ins, _ = get_spmd_rule("reduction").infer_reverse(
                    [in_shapes[0]], [out_spec],
                    axis=list(eqn.params["axes"]))
                known[id(ivars[0])] = ins[0].dims_mapping
            elif name in _ELTWISE_PRIMS:
                ins, _ = get_spmd_rule("elementwise").infer_reverse(
                    in_shapes, [out_spec])
                for v, spec in zip(ivars, ins):
                    known.setdefault(id(v), spec.dims_mapping)
            elif name == "concatenate":
                ins, _ = get_spmd_rule("concat").infer_reverse(
                    in_shapes, [out_spec],
                    axis=int(eqn.params["dimension"]))
                for v, spec in zip(ivars, ins):
                    known.setdefault(id(v), spec.dims_mapping)
            elif name == "rev":
                ins, _ = get_spmd_rule("flip").infer_reverse(
                    [in_shapes[0]], [out_spec],
                    axis=list(eqn.params["dimensions"]))
                known[id(ivars[0])] = ins[0].dims_mapping
            elif name == "pad":
                cfg = eqn.params["padding_config"]
                padded = [i for i, (lo, hi, it) in enumerate(cfg)
                          if lo or hi or it]
                in_dm = [(-1 if i in padded else m)
                         for i, m in enumerate(dm)]
                known[id(ivars[0])] = in_dm
            elif name == "squeeze":
                dims = set(eqn.params["dimensions"])
                in_dm, j = [], 0
                for i in range(len(in_shapes[0])):
                    if i in dims:
                        in_dm.append(-1)
                    else:
                        in_dm.append(dm[j])
                        j += 1
                known[id(ivars[0])] = in_dm
            elif name == "broadcast_in_dim":
                bd = eqn.params["broadcast_dimensions"]
                in_shape = in_shapes[0]
                in_dm = [(dm[od] if in_shape[j] ==
                          eqn.outvars[0].aval.shape[od] else -1)
                         for j, od in enumerate(bd)]
                known[id(ivars[0])] = in_dm
            # dot_general: record (done above) but don't flow through —
            # the contracted dim is undetermined by the output and the
            # planner owns the operand-side decision
        except Exception:
            continue
    return reached
