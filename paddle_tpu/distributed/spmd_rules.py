"""Per-op SPMD sharding rules.

Parity slot: `paddle/phi/infermeta/spmd_rules/` (121 rule files, e.g.
`matmul.cc:42-80`) and the rule tests under
`test/auto_parallel/spmd_rules/test_matmul_rule.py`.

GSPMD propagation is the framework default (the compiler propagates
shardings through the whole jaxpr), but propagation alone mis-shards a
handful of ops whose optimal placement is a *semantic* decision, not a
dataflow one: vocab-parallel embedding (masked-lookup + allreduce beats
gathering the sharded table), attention (shard heads, never head_dim),
softmax/norm reduction axes, and MoE dispatch (expert dim over "ep").
This module supplies:

1. ``DistTensorSpec`` + an einsum-notation inference engine that, given
   input dims_mappings, produces merged input mappings and output
   mappings with partial (pending-reduction) mesh dims — the same
   contract as the reference's ``infer_forward``.
2. A registry of per-op rules (``get_spmd_rule(name).infer_forward``)
   covering matmul/elementwise/embedding/reduction/softmax/layer_norm/
   flash_attention/cross_entropy/reshape/transpose/concat/split/moe
   and friends.
3. ``constrain(op, mesh, out, *input_placement_lists)`` — applies the
   rule's inferred output placement as a ``lax.with_sharding_constraint``
   so the decision binds inside jit (the analogue of the reference
   inserting a reshard op from the inferred dist_attr).

dims_mapping convention matches the reference: ``dims_mapping[i]`` is
the mesh-dim *index* sharding tensor dim ``i``, or ``-1`` for
replicated. Partial state is a set of mesh-dim indices carrying an
unreduced sum (phi ``TensorDistAttr::_partial_dims()``).
"""
from __future__ import annotations

from typing import List, Sequence

from jax.sharding import PartitionSpec

__all__ = [
    "DistTensorSpec",
    "get_spmd_rule",
    "register_spmd_rule",
    "constrain",
    "constraints_enabled",
]


def constraints_enabled() -> bool:
    """Master switch for rule-driven constraint insertion
    (``FLAGS_spmd_rule_constraints``) — gates the embedding, attention,
    and MoE-dispatch sites."""
    from ..utils.flags import get_flags

    return bool(get_flags("spmd_rule_constraints")["spmd_rule_constraints"])


class DistTensorSpec:
    """Shape + dims_mapping (+ partial dims) — phi ``DistTensorSpec``."""

    def __init__(self, shape, dims_mapping=None, partial_dims=()):
        self.shape = list(shape)
        if dims_mapping is None:
            dims_mapping = [-1] * len(self.shape)
        if len(dims_mapping) != len(self.shape):
            raise ValueError(
                f"dims_mapping rank {len(dims_mapping)} != tensor rank {len(self.shape)}"
            )
        self.dims_mapping = list(dims_mapping)
        self.partial_dims = set(partial_dims)

    # reference-test API
    def set_dims_mapping(self, dm):
        self.dims_mapping = list(dm)

    def _is_partial(self):
        return bool(self.partial_dims)

    def _partial_dims(self):
        return set(self.partial_dims)

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):
        p = f", partial={sorted(self.partial_dims)}" if self.partial_dims else ""
        return f"DistTensorSpec({self.shape}, {self.dims_mapping}{p})"

    def partition_spec(self, mesh_dim_names: Sequence[str]) -> PartitionSpec:
        entries = [
            None if m < 0 else mesh_dim_names[m] for m in self.dims_mapping
        ]
        while entries and entries[-1] is None:
            entries.pop()
        return PartitionSpec(*entries)


# ---------------------------------------------------------------------------
# einsum-notation inference engine
# ---------------------------------------------------------------------------
def _merge_axis(candidates: List[int]) -> int:
    """Merge per-letter mesh dims from multiple inputs.

    Reference semantics (`ShardingMergeForAxis`): sharded beats
    replicated; two different shardings of the same letter keep the
    first (the later input is inferred resharded to match).
    """
    for c in candidates:
        if c >= 0:
            return c
    return -1


def einsum_infer(notation: str, specs: Sequence[DistTensorSpec]):
    """Infer shardings through an einsum-style notation.

    ``notation`` e.g. ``"mk,kn->mn"``. Returns
    ``(inferred_inputs, inferred_outputs)`` where contracted letters
    that remain sharded surface as partial dims on the outputs —
    exactly the reference matmul rule's contract
    (`matmul.cc:42-80`: mk[1,0] x kn[0,-1] -> mn[1,-1] partial{0}).

    A ``1`` in the notation marks a broadcast dim (size-1), always
    replicated. A ``*`` marks a dim forced replicated (e.g. a softmax
    or norm axis).
    """
    lhs, rhs = notation.split("->")
    in_subs = lhs.split(",")
    out_subs = rhs.split(",") if rhs else []
    if len(in_subs) != len(specs):
        raise ValueError(f"notation {notation!r} has {len(in_subs)} operands, got {len(specs)} specs")

    # 1. merge each letter's sharding across inputs
    letter_map = {}
    order = []
    for sub, spec in zip(in_subs, specs):
        if len(sub) != spec.ndim:
            raise ValueError(f"operand {sub!r} rank != spec rank {spec.ndim}")
        for letter, m in zip(sub, spec.dims_mapping):
            if letter in "1*":
                continue
            if letter not in letter_map:
                letter_map[letter] = []
                order.append(letter)
            letter_map[letter].append(m)
    merged = {lt: _merge_axis(ms) for lt, ms in letter_map.items()}

    # 2. a mesh dim may shard at most one letter: first letter wins
    used = {}
    for lt in order:
        m = merged[lt]
        if m < 0:
            continue
        if m in used:
            merged[lt] = -1
        else:
            used[m] = lt

    # 3. inferred (corrected) input specs
    inferred_inputs = []
    for sub, spec in zip(in_subs, specs):
        dm = [
            -1 if letter in "1*" else merged[letter]
            for letter in sub
        ]
        inferred_inputs.append(DistTensorSpec(spec.shape, dm))

    # 4. outputs: contracted sharded letters become partial dims
    out_letters = set("".join(out_subs))
    pending = {
        merged[lt]
        for lt in order
        if merged[lt] >= 0 and lt not in out_letters
    }
    inferred_outputs = []
    for sub in out_subs:
        dm = [-1 if letter in "1*" else merged.get(letter, -1) for letter in sub]
        # output shape is unknown to the engine; synthesize rank-only shape
        inferred_outputs.append(DistTensorSpec([0] * len(sub), dm, partial_dims=pending))
    return inferred_inputs, inferred_outputs


def _letters(n, skip=""):
    pool = [c for c in "abcdefghijklmnopqrstuvwxyz" if c not in skip]
    return "".join(pool[:n])


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------
_RULES = {}


def register_spmd_rule(name):
    def deco(fn):
        _RULES[name] = SpmdRule(name, fn)
        return fn

    return deco


class SpmdRule:
    def __init__(self, name, fn):
        self.name = name
        self._fn = fn

    def infer_forward(self, *specs, **attrs):
        """Returns ([inferred input specs], [inferred output specs])."""
        return self._fn(*specs, **attrs)

    def infer_reverse(self, in_shapes, out_specs, **attrs):
        """Completion in the reverse direction (parity:
        MatmulInferSpmdReverse, phi/infermeta/spmd_rules/matmul.h:30):
        given the OUTPUT dist specs and the input shapes, infer input
        specs consistent with them. Dims not determined by any output
        (e.g. a matmul's contracted k) stay replicated. Returns
        ([in specs], [corrected out specs])."""
        fn = _REVERSE_RULES.get(self.name)
        if fn is None:
            raise NotImplementedError(
                f"no reverse SPMD rule for {self.name!r}")
        outs = (list(out_specs) if isinstance(out_specs, (list, tuple))
                else [out_specs])
        return fn(list(in_shapes), outs, **attrs)


_REVERSE_RULES = {}


def register_spmd_reverse(name):
    def deco(fn):
        _REVERSE_RULES[name] = fn
        return fn

    return deco


def einsum_infer_reverse(notation, in_shapes, out_specs):
    """Reverse of einsum_infer: letters take their mapping from the
    outputs; letters absent from every output (contracted) replicate."""
    lhs, rhs = notation.split("->")
    in_subs = lhs.split(",")
    out_subs = rhs.split(",") if rhs else []
    letter_map = {}
    order = []
    for sub, spec in zip(out_subs, out_specs):
        if len(sub) != spec.ndim:
            raise ValueError(
                f"output {sub!r} rank != spec rank {spec.ndim}")
        for letter, m in zip(sub, spec.dims_mapping):
            if letter in "1*":
                continue
            if letter not in letter_map:
                letter_map[letter] = []
                order.append(letter)
            letter_map[letter].append(m)
    merged = {lt: _merge_axis(ms) for lt, ms in letter_map.items()}
    used = {}
    for lt in order:
        m = merged[lt]
        if m < 0:
            continue
        if m in used:
            merged[lt] = -1
        else:
            used[m] = lt
    in_specs = [
        DistTensorSpec(shape, [merged.get(letter, -1)
                               if letter not in "1*" else -1
                               for letter in sub])
        for sub, shape in zip(in_subs, in_shapes)
    ]
    new_outs = [
        DistTensorSpec(spec.shape, [merged.get(letter, -1)
                                    if letter not in "1*" else -1
                                    for letter in sub])
        for sub, spec in zip(out_subs, out_specs)
    ]
    return in_specs, new_outs


@register_spmd_reverse("matmul")
def _matmul_reverse(in_shapes, out_specs, trans_x=False, trans_y=False):
    xd, yd = len(in_shapes[0]), len(in_shapes[1])
    return einsum_infer_reverse(
        _matmul_notation(xd, yd, trans_x, trans_y), in_shapes, out_specs)


@register_spmd_reverse("elementwise")
def _elementwise_reverse(in_shapes, out_specs):
    fake = [DistTensorSpec(sh) for sh in in_shapes]
    return einsum_infer_reverse(_broadcast_subs(fake), in_shapes, out_specs)


@register_spmd_reverse("transpose")
def _transpose_reverse(in_shapes, out_specs, perm=None):
    nd = len(in_shapes[0])
    perm = list(range(nd))[::-1] if perm is None else [p % nd for p in perm]
    letters = _letters(nd)
    out = "".join(letters[p] for p in perm)
    return einsum_infer_reverse(f"{letters}->{out}", in_shapes, out_specs)


@register_spmd_reverse("reshape")
def _reshape_reverse(in_shapes, out_specs, shape=None):
    # reshape reverse IS the forward rule applied out-shape -> in-shape
    out = out_specs[0]
    ins, outs = _reshape_rule(
        DistTensorSpec(out.shape, out.dims_mapping), shape=in_shapes[0])
    return [outs[0]], [ins[0]]


@register_spmd_reverse("reduction")
def _reduction_reverse(in_shapes, out_specs, axis=None, keepdim=False,
                       reduce_type="sum"):
    nd = len(in_shapes[0])
    if axis is None:
        axes = list(range(nd))
    else:
        axes = [axis] if isinstance(axis, int) else list(axis)
        axes = [a % nd for a in axes]
    letters = _letters(nd)
    if keepdim:
        out = "".join("*" if i in axes else c
                      for i, c in enumerate(letters))
    else:
        out = "".join(c for i, c in enumerate(letters) if i not in axes)
    return einsum_infer_reverse(f"{letters}->{out}", in_shapes, out_specs)


@register_spmd_reverse("embedding")
def _embedding_reverse(in_shapes, out_specs, padding_idx=-1, sparse=False):
    ids_nd = len(in_shapes[0])
    ids = _letters(ids_nd, skip="vh")
    return einsum_infer_reverse(f"{ids},vh->{ids}h", in_shapes, out_specs)


def get_spmd_rule(name) -> SpmdRule:
    try:
        return _RULES[name]
    except KeyError:
        raise KeyError(
            f"no SPMD rule registered for {name!r}; GSPMD propagation is the default"
        ) from None


# -- matmul ------------------------------------------------------------------
def _matmul_notation(xd, yd, trans_x, trans_y):
    x_mat = "mk" if not trans_x else "km"
    y_mat = "kn" if not trans_y else "nk"
    if xd == 1:
        x_mat = "k"
    if yd == 1:
        y_mat = "k"
    xb, yb = max(xd - len(x_mat), 0), max(yd - len(y_mat), 0)
    nb = max(xb, yb)
    batch = _letters(nb, skip="mnk")
    x_sub = batch[nb - xb:] + x_mat
    y_sub = batch[nb - yb:] + y_mat
    out = batch + ("m" if "m" in x_mat else "") + ("n" if "n" in y_mat else "")
    return f"{x_sub},{y_sub}->{out}"


@register_spmd_rule("matmul")
def _matmul_rule(x: DistTensorSpec, y: DistTensorSpec, trans_x=False, trans_y=False):
    """`matmul.cc:42-80`. Batched, broadcast-aware."""
    return einsum_infer(
        _matmul_notation(x.ndim, y.ndim, trans_x, trans_y), [x, y])


@register_spmd_rule("einsum")
def _einsum_rule(*specs, equation):
    return einsum_infer(equation, list(specs))


# -- elementwise -------------------------------------------------------------
def _broadcast_subs(specs):
    nd = max(s.ndim for s in specs)
    letters = _letters(nd)
    subs = []
    for s in specs:
        sub = letters[nd - s.ndim:]
        # size-1 dims broadcast: force replicated
        sub = "".join(
            "1" if s.shape[i] == 1 else c for i, c in enumerate(sub)
        )
        subs.append(sub)
    return ",".join(subs) + "->" + letters


@register_spmd_rule("elementwise")
def _elementwise_rule(*specs):
    """`elementwise.cc` — broadcast-aware letter merge."""
    return einsum_infer(_broadcast_subs(specs), list(specs))


@register_spmd_rule("where")
def _where_rule(cond, x, y):
    return einsum_infer(_broadcast_subs([cond, x, y]), [cond, x, y])


@register_spmd_rule("cast")
def _cast_rule(x):
    return einsum_infer(f"{_letters(x.ndim)}->{_letters(x.ndim)}", [x])


# -- embedding ---------------------------------------------------------------
@register_spmd_rule("embedding")
def _embedding_rule(x: DistTensorSpec, w: DistTensorSpec, padding_idx=-1, sparse=False):
    """`embedding.cc:30`. ids [...], weight [V, H] -> out [..., H].

    Row-sharded weight (vocab over mp) keeps the sharding and the
    output becomes *partial* over that mesh dim — the c_embedding
    masked-lookup + allreduce pattern. The ids must not be sharded on
    the same mesh dim as the vocab axis.
    """
    ids = _letters(x.ndim, skip="vh")
    notation = f"{ids},vh->{ids}h"
    return einsum_infer(notation, [x, w])


@register_spmd_rule("c_embedding")
def _c_embedding_rule(w: DistTensorSpec, x: DistTensorSpec, start_index=0):
    ins, outs = _embedding_rule(x, w)
    return [ins[1], ins[0]], outs


# -- reductions --------------------------------------------------------------
@register_spmd_rule("reduction")
def _reduction_rule(x: DistTensorSpec, axis=None, keepdim=False, reduce_type="sum"):
    """`reduction.cc`. Sharded reduced axes -> partial output (sum/mean)
    or forced-replicated input (max/min, where partial isn't linear)."""
    nd = x.ndim
    if axis is None:
        axes = list(range(nd))
    else:
        axes = [axis] if isinstance(axis, int) else list(axis)
        axes = [a % nd for a in axes]
    letters = _letters(nd)
    linear = reduce_type in ("sum", "mean", "avg")
    x_sub = letters
    if not linear:
        x_sub = "".join("*" if i in axes else c for i, c in enumerate(letters))
    if keepdim:
        out = "".join("*" if i in axes else c for i, c in enumerate(letters))
    else:
        out = "".join(c for i, c in enumerate(letters) if i not in axes)
    return einsum_infer(f"{x_sub}->{out}", [x])


@register_spmd_rule("softmax")
def _softmax_rule(x: DistTensorSpec, axis=-1):
    """`softmax.cc:28` — the softmax axis must be replicated."""
    nd = x.ndim
    axis %= nd
    letters = _letters(nd)
    sub = "".join("*" if i == axis else c for i, c in enumerate(letters))
    return einsum_infer(f"{sub}->{sub}", [x])


@register_spmd_rule("layer_norm")
def _layer_norm_rule(x: DistTensorSpec, scale=None, bias=None, begin_norm_axis=-1):
    """`layer_norm.cc` — normalized trailing dims replicated; leading
    (batch/seq) dims keep their sharding. Returns out, mean, variance."""
    nd = x.ndim
    begin_norm_axis %= nd
    letters = _letters(nd)
    sub = "".join(
        "*" if i >= begin_norm_axis else c for i, c in enumerate(letters)
    )
    lead = sub[:begin_norm_axis]
    specs = [x]
    subs = [sub]
    for extra in (scale, bias):
        if extra is not None:
            specs.append(extra)
            subs.append("*" * extra.ndim)
    ins, outs = einsum_infer(
        ",".join(subs) + f"->{sub},{lead},{lead}", specs
    )
    return ins, outs


@register_spmd_rule("rms_norm")
def _rms_norm_rule(x: DistTensorSpec, scale=None, begin_norm_axis=-1):
    ins, outs = _layer_norm_rule(x, scale, None, begin_norm_axis)
    return ins, outs[:1]


# -- shape manipulation ------------------------------------------------------
@register_spmd_rule("transpose")
def _transpose_rule(x: DistTensorSpec, perm=None):
    nd = x.ndim
    perm = list(range(nd))[::-1] if perm is None else [p % nd for p in perm]
    letters = _letters(nd)
    out = "".join(letters[p] for p in perm)
    return einsum_infer(f"{letters}->{out}", [x])


@register_spmd_rule("reshape")
def _reshape_rule(x: DistTensorSpec, shape=None):
    """`reshape.cc` — map shardings through merged/split dim groups.

    Supports the common cases: dims preserved 1:1, a group of input
    dims merged into one output dim (sharding of the *leading* input
    dim survives), one input dim split into several output dims
    (sharding moves to the leading output dim, which must divide).
    Anything more exotic degrades to replicated — a correct (if
    conservative) placement, same as the reference's fallback.
    """
    in_shape = list(x.shape)
    out_shape = list(shape)
    # resolve a single -1
    if -1 in out_shape:
        known = 1
        for d in out_shape:
            if d != -1:
                known *= d
        total = 1
        for d in in_shape:
            total *= d
        out_shape[out_shape.index(-1)] = total // max(known, 1)

    out_dm = [-1] * len(out_shape)
    i = j = 0
    while i < len(in_shape) and j < len(out_shape):
        isz, osz = in_shape[i], out_shape[j]
        if isz == osz:
            out_dm[j] = x.dims_mapping[i]
            i += 1
            j += 1
            continue
        if isz < osz:
            # merge group of input dims -> out dim j; leading in-dim sharding survives
            lead = x.dims_mapping[i]
            prod = isz
            i += 1
            while prod < osz and i < len(in_shape):
                prod *= in_shape[i]
                i += 1
            if prod != osz:
                return _replicated_fallback(x, out_shape)
            out_dm[j] = lead
            j += 1
        else:
            # split input dim i -> group of out dims; sharding moves to leading out dim
            lead_out = j
            prod = osz
            j += 1
            while prod < isz and j < len(out_shape):
                prod *= out_shape[j]
                j += 1
            if prod != isz:
                return _replicated_fallback(x, out_shape)
            out_dm[lead_out] = x.dims_mapping[i]
            i += 1
    return [DistTensorSpec(x.shape, x.dims_mapping)], [
        DistTensorSpec(out_shape, out_dm)
    ]


def _replicated_fallback(x, out_shape):
    return [DistTensorSpec(x.shape, x.dims_mapping)], [
        DistTensorSpec(out_shape, [-1] * len(out_shape))
    ]


@register_spmd_rule("squeeze")
def _squeeze_rule(x: DistTensorSpec, axis=None):
    nd = x.ndim
    if axis is None:
        axes = [i for i, s in enumerate(x.shape) if s == 1]
    else:
        axes = [a % nd for a in (axis if isinstance(axis, (list, tuple)) else [axis])]
    letters = _letters(nd)
    sub = "".join("1" if i in axes else c for i, c in enumerate(letters))
    out = "".join(c for i, c in enumerate(letters) if i not in axes)
    return einsum_infer(f"{sub}->{out}", [x])


@register_spmd_rule("unsqueeze")
def _unsqueeze_rule(x: DistTensorSpec, axis=0):
    axes = sorted(
        a % (x.ndim + 1)
        for a in (axis if isinstance(axis, (list, tuple)) else [axis])
    )
    out_dm = []
    out_shape = []
    i = 0
    nd_out = x.ndim + len(axes)
    for d in range(nd_out):
        if d in axes:
            out_dm.append(-1)
            out_shape.append(1)
        else:
            out_dm.append(x.dims_mapping[i])
            out_shape.append(x.shape[i])
            i += 1
    return [DistTensorSpec(x.shape, x.dims_mapping)], [DistTensorSpec(out_shape, out_dm)]


@register_spmd_rule("concat")
def _concat_rule(*specs, axis=0):
    """`concat.cc` — the concat axis must be replicated (ragged shards
    otherwise); other dims merge across inputs."""
    nd = specs[0].ndim
    axis %= nd
    letters = _letters(nd)
    sub = "".join("*" if i == axis else c for i, c in enumerate(letters))
    notation = ",".join([sub] * len(specs)) + f"->{sub}"
    return einsum_infer(notation, list(specs))


@register_spmd_rule("split")
def _split_rule(x: DistTensorSpec, num_or_sections=2, axis=0):
    nd = x.ndim
    axis %= nd
    letters = _letters(nd)
    sub = "".join("*" if i == axis else c for i, c in enumerate(letters))
    n = (
        num_or_sections
        if isinstance(num_or_sections, int)
        else len(num_or_sections)
    )
    notation = sub + "->" + ",".join([sub] * n)
    return einsum_infer(notation, [x])


@register_spmd_rule("slice")
def _slice_rule(x: DistTensorSpec, axes=()):
    """Sliced axes must be replicated (a shard boundary may bisect the
    slice); others pass through."""
    nd = x.ndim
    ax = {a % nd for a in axes}
    letters = _letters(nd)
    sub = "".join("*" if i in ax else c for i, c in enumerate(letters))
    return einsum_infer(f"{sub}->{sub}", [x])


@register_spmd_rule("stack")
def _stack_rule(*specs, axis=0):
    nd = specs[0].ndim
    axis %= nd + 1
    letters = _letters(nd)
    notation = ",".join([letters] * len(specs)) + "->" + letters[:axis] + "1" + letters[axis:]
    ins, outs = einsum_infer(notation, list(specs))
    return ins, outs


@register_spmd_rule("tile")
def _tile_rule(x: DistTensorSpec, repeat_times=()):
    """Tiled axes must be replicated."""
    nd = x.ndim
    rep = list(repeat_times)
    rep = [1] * (nd - len(rep)) + rep[-nd:] if len(rep) <= nd else rep[-nd:]
    letters = _letters(nd)
    sub = "".join("*" if rep[i] != 1 else c for i, c in enumerate(letters))
    return einsum_infer(f"{sub}->{sub}", [x])


# -- indexing ----------------------------------------------------------------
@register_spmd_rule("gather")
def _gather_rule(x: DistTensorSpec, index: DistTensorSpec, axis=0):
    """Gather along ``axis``: that axis of x must be replicated (the
    lookup crosses shard boundaries); index dims replace it."""
    nd = x.ndim
    axis %= nd
    letters = _letters(nd)
    idx_letters = _letters(index.ndim, skip=letters)
    x_sub = "".join("*" if i == axis else c for i, c in enumerate(letters))
    out = x_sub[:axis] + idx_letters + x_sub[axis + 1:]
    return einsum_infer(f"{x_sub},{idx_letters}->{out}", [x, index])


def _scatter_notation(in_shapes, axis):
    nd = len(in_shapes[0])
    axis %= nd
    letters = _letters(nd)
    x_sub = "".join("*" if i == axis else c for i, c in enumerate(letters))
    idx_sub = "*" * len(in_shapes[1])
    return f"{x_sub},{idx_sub},{x_sub}->{x_sub}"


@register_spmd_rule("scatter")
def _scatter_rule(x: DistTensorSpec, index: DistTensorSpec, updates: DistTensorSpec, axis=0):
    notation = _scatter_notation([x.shape, index.shape, updates.shape], axis)
    return einsum_infer(notation, [x, index, updates])


# -- losses ------------------------------------------------------------------
@register_spmd_rule("cross_entropy_with_softmax")
def _ce_rule(logits: DistTensorSpec, label: DistTensorSpec, axis=-1):
    """`cross_entropy_with_softmax.cc:36`: a vocab-sharded logit keeps
    its sharding and the loss comes out *partial* over that mesh dim —
    the ParallelCrossEntropy pattern (max/sum over local vocab +
    allreduce). Returns (softmax_out, loss)."""
    nd = logits.ndim
    axis %= nd
    letters = _letters(nd, skip="v")
    lg = letters[:axis] + "v" + letters[axis:nd - 1]
    lead = lg.replace("v", "")
    lbl = lead if label.ndim == nd - 1 else lead + "1"
    v_mesh = logits.dims_mapping[axis]
    ins, outs = einsum_infer(f"{lg},{lbl}->{lg},{lead}", [logits, label])
    if v_mesh >= 0:
        # keep the vocab sharding on the input (einsum_infer already
        # does) and mark the reduced loss partial over it
        ins[0].dims_mapping[axis] = v_mesh
        outs[0].dims_mapping[axis] = v_mesh
        outs[1].partial_dims.add(v_mesh)
    return ins, outs


# -- attention ---------------------------------------------------------------
@register_spmd_rule("flash_attention")
def _flash_attention_rule(
    q: DistTensorSpec,
    k: DistTensorSpec,
    v: DistTensorSpec,
    causal=True,
    context_parallel=False,
):
    """`flash_attention.cc` redesigned for the TPU layouts:

    [b, s, n, d]: batch over dp, heads over mp; head_dim must be
    replicated. The kv sequence dim must be replicated *unless* the
    caller runs ring attention (context_parallel=True), where the
    q-sequence sharding is kept and kv blocks rotate over the sep axis.
    """
    # q: b s n d ; k/v: b t m d (m = kv heads, GQA-merged with n)
    q_sub, k_sub, v_sub = "bsnd", "btnd", "btnd"
    if context_parallel:
        # ring attention: kv seq sharding equals q seq sharding (blocks
        # rotate via ppermute outside this op)
        k_sub = v_sub = "bsnd"
    # head_dim always replicated
    q_sub = q_sub[:3] + "*"
    k_sub = k_sub[:3] + "*"
    v_sub = v_sub[:3] + "*"
    if not context_parallel:
        # kv sequence must be whole for plain softmax
        k_sub = k_sub[0] + "*" + k_sub[2:]
        v_sub = v_sub[0] + "*" + v_sub[2:]
    ins, outs = einsum_infer(f"{q_sub},{k_sub},{v_sub}->{q_sub}", [q, k, v])
    return ins, outs


# -- MoE ---------------------------------------------------------------------
@register_spmd_rule("moe_gate")
def _moe_gate_rule(x: DistTensorSpec, gate_w: DistTensorSpec):
    """Gating logits [s, e]: token dim keeps its (dp) sharding, the
    expert dim replicated (every rank routes against all experts)."""
    return einsum_infer("sd,d*->s*", [x, gate_w])


@register_spmd_rule("moe_dispatch")
def _moe_dispatch_rule(x: DistTensorSpec, ep_mesh_dim=None):
    """Dispatched tokens [e, c, d]: expert dim sharded over the "ep"
    mesh dim (`moe_sublayers` dispatch → all_to_all over ep); capacity
    and feature dims replicated. Token input must be replicated over ep
    (each rank contributes its tokens via the all_to_all)."""
    in_dm = list(x.dims_mapping)
    if ep_mesh_dim is not None:
        in_dm = [-1 if m == ep_mesh_dim else m for m in in_dm]
    out_dm = [ep_mesh_dim if ep_mesh_dim is not None else -1, -1, -1]
    return (
        [DistTensorSpec(x.shape, in_dm)],
        [DistTensorSpec([0, 0, 0], out_dm)],
    )


# -- misc passthroughs -------------------------------------------------------
@register_spmd_rule("dropout")
def _dropout_rule(x: DistTensorSpec, p=0.5):
    sub = _letters(x.ndim)
    return einsum_infer(f"{sub}->{sub}", [x])


@register_spmd_rule("triu")
def _triu_rule(x: DistTensorSpec, diagonal=0):
    sub = _letters(x.ndim)
    return einsum_infer(f"{sub}->{sub}", [x])


@register_spmd_rule("cumsum")
def _cumsum_rule(x: DistTensorSpec, axis=-1):
    nd = x.ndim
    axis %= nd
    letters = _letters(nd)
    sub = "".join("*" if i == axis else c for i, c in enumerate(letters))
    return einsum_infer(f"{sub}->{sub}", [x])


@register_spmd_rule("topk")
def _topk_rule(x: DistTensorSpec, k=1, axis=-1):
    nd = x.ndim
    axis %= nd
    letters = _letters(nd)
    sub = "".join("*" if i == axis else c for i, c in enumerate(letters))
    return einsum_infer(f"{sub}->{sub},{sub}", [x])


@register_spmd_rule("argmax")
def _argmax_rule(x: DistTensorSpec, axis=-1, keepdim=False):
    return _reduction_rule(x, axis=axis, keepdim=keepdim, reduce_type="max")


# ---------------------------------------------------------------------------
# application: bind a rule's decision inside jit
# ---------------------------------------------------------------------------
def constrain(op_name, mesh, out, *specs, **attrs):
    """Apply ``get_spmd_rule(op_name)``'s inferred output placement to
    ``out`` as a sharding constraint on ``mesh`` (a ProcessMesh).

    The partial state cannot be expressed to with_sharding_constraint —
    partial outputs are constrained *resolved* (replicated over the
    pending dim), which makes XLA insert the allreduce exactly where
    the reference inserts its c_allreduce_sum.
    """
    from .auto_parallel import shard_activation

    _, outs = get_spmd_rule(op_name).infer_forward(*specs, **attrs)
    spec = outs[0].partition_spec(mesh.dim_names)
    return shard_activation(out, mesh=mesh, spec=spec)


def spec_for(op_name, mesh, *specs, **attrs) -> PartitionSpec:
    """Rule-inferred PartitionSpec of the first output (resolved)."""
    _, outs = get_spmd_rule(op_name).infer_forward(*specs, **attrs)
    return outs[0].partition_spec(mesh.dim_names)


# -- round-4 breadth: the remaining high-traffic yaml-keyed rules ------------
# (reference: phi/infermeta/spmd_rules/ — 60 yaml-keyed ops; these close
# the most-used gap on top of GSPMD-propagation-by-default)
@register_spmd_rule("bmm")
def _bmm_rule(x: DistTensorSpec, y: DistTensorSpec):
    return einsum_infer("bmk,bkn->bmn", [x, y])


def _identity_rule_factory(name):
    @register_spmd_rule(name)
    def _rule(x: DistTensorSpec, **attrs):
        sub = _letters(x.ndim)
        return einsum_infer(f"{sub}->{sub}", [x])
    _rule.__name__ = f"_{name}_rule"
    return _rule


# layout-preserving unaries: sharding flows straight through
for _n in ("tril", "scale", "clip"):
    _identity_rule_factory(_n)


def _axes_replicated_rule_factory(name, axes_of):
    """Reversing/rotating/padding a sharded axis is not locally
    computable (ADVICE r4): the operated axes must be whole per device —
    mark them replicated so the planner prices the reshard instead of
    GSPMD silently inserting it."""
    @register_spmd_rule(name)
    def _rule(x: DistTensorSpec, **attrs):
        nd = x.ndim
        axes = axes_of(nd, attrs)
        letters = _letters(nd)
        sub = "".join("*" if i in axes else c
                      for i, c in enumerate(letters))
        return einsum_infer(f"{sub}->{sub}", [x])
    _rule.__name__ = f"_{name}_rule"
    return _rule


def _flip_axes(nd, attrs):
    ax = attrs.get("axis", attrs.get("axes"))
    if ax is None:
        return set(range(nd))
    ax = [ax] if isinstance(ax, int) else list(ax)
    return {int(a) % nd for a in ax}


def _roll_axes(nd, attrs):
    ax = attrs.get("axis")
    if ax is None:          # axis=None rolls the flattened array
        return set(range(nd))
    ax = [ax] if isinstance(ax, int) else list(ax)
    return {int(a) % nd for a in ax}


def _pad_axes(nd, attrs):
    # NOTE: must mirror the pad-spec layout in ops/manipulation.py pad()
    # (full-rank leading-first pairs vs torch-style trailing reversed);
    # if that convention changes, change this with it
    pad = attrs.get("pad", attrs.get("paddings"))
    if pad is None:
        return set(range(nd))  # unknown spec: be conservative
    pad = list(pad)
    if len(pad) == 2 * nd:     # per-dim (lo, hi) pairs, leading-dim first
        return {i for i in range(nd)
                if pad[2 * i] or pad[2 * i + 1]}
    # torch-style trailing-dims-first pairs
    n_dims = len(pad) // 2
    return {nd - 1 - i for i in range(n_dims)
            if pad[2 * i] or pad[2 * i + 1]}


_axes_replicated_rule_factory("flip", _flip_axes)
_axes_replicated_rule_factory("roll", _roll_axes)
_axes_replicated_rule_factory("pad", _pad_axes)


@register_spmd_rule("fused_rotary_position_embedding")
def _fused_rope_rule(*specs):
    """Rope is elementwise-per-position over each of q/k/v (sin/cos
    broadcast): every tensor input keeps its own sharding; one output
    per input."""
    subs = _broadcast_subs(specs).split("->")[0].split(",")
    notation = ",".join(subs) + "->" + ",".join(subs)
    return einsum_infer(notation, list(specs))


def _axis_replicated_rule_factory(name, n_out=1):
    @register_spmd_rule(name)
    def _rule(x: DistTensorSpec, axis=-1, **attrs):
        nd = x.ndim
        axis %= nd
        letters = _letters(nd)
        sub = "".join("*" if i == axis else c
                      for i, c in enumerate(letters))
        outs = ",".join([sub] * n_out)
        return einsum_infer(f"{sub}->{outs}", [x])
    _rule.__name__ = f"_{name}_rule"
    return _rule


# ops whose working axis must be whole per device
for _n in ("sort", "argsort", "cummax", "cummin", "logcumsumexp",
           "kthvalue"):
    _axis_replicated_rule_factory(
        _n, n_out=2 if _n in ("cummax", "cummin", "kthvalue",
                              "argsort") else 1)


@register_spmd_rule("index_select")
def _index_select_rule(x: DistTensorSpec, index: DistTensorSpec, axis=0):
    nd = x.ndim
    axis %= nd
    letters = _letters(nd, skip="i")
    x_sub = "".join("*" if i == axis else c
                    for i, c in enumerate(letters))
    out = "".join("i" if i == axis else c for i, c in enumerate(letters))
    return einsum_infer(f"{x_sub},i->{out}", [x, index])


def _along_axis_subs(specs, axis):
    """Shared letters with the working axis replicated AND size-1 dims
    broadcast-marked (the _broadcast_subs contract — a broadcast index
    must not inherit a sharding its size-1 dim cannot carry)."""
    nd = max(s.ndim for s in specs)
    axis %= nd
    letters = _letters(nd)
    base = "".join("*" if i == axis else c for i, c in enumerate(letters))
    subs = []
    for s in specs:
        sub = base[nd - s.ndim:]
        sub = "".join("1" if s.shape[i] == 1 and c not in "*" else c
                      for i, c in enumerate(sub))
        subs.append(sub)
    return subs, base


@register_spmd_rule("take_along_axis")
def _take_along_axis_rule(x: DistTensorSpec, index: DistTensorSpec,
                          axis=0):
    (x_sub, i_sub), out = _along_axis_subs([x, index], axis)
    return einsum_infer(f"{x_sub},{i_sub}->{out}", [x, index])


@register_spmd_rule("put_along_axis")
def _put_along_axis_rule(x: DistTensorSpec, index: DistTensorSpec,
                         value: DistTensorSpec, axis=0):
    (x_sub, i_sub, v_sub), out = _along_axis_subs([x, index, value], axis)
    return einsum_infer(f"{x_sub},{i_sub},{v_sub}->{out}", [x, index, value])


@register_spmd_rule("one_hot")
def _one_hot_rule(x: DistTensorSpec, num_classes=-1):
    sub = _letters(x.ndim, skip="c")
    return einsum_infer(f"{sub}->{sub}c", [x])


@register_spmd_rule("conv")
def _conv_rule(x: DistTensorSpec, w: DistTensorSpec, **attrs):
    """NCHW conv: batch stays sharded, channels/spatial replicated
    per-device (spatial sharding needs halo exchange — not expressed;
    reference conv2d rule keeps the same contract). Weight layout
    [C_out, C_in, *k]."""
    nsp = x.ndim - 2
    sp = "*" * nsp
    return einsum_infer(f"bc{sp},oc{sp}->bo{sp}", [x, w])


@register_spmd_rule("conv_transpose")
def _conv_transpose_rule(x: DistTensorSpec, w: DistTensorSpec, **attrs):
    """Transposed conv: weight layout [C_in, C_out, *k] — the CONTRACTED
    channel comes first."""
    nsp = x.ndim - 2
    sp = "*" * nsp
    return einsum_infer(f"bc{sp},co{sp}->bo{sp}", [x, w])


@register_spmd_rule("pool")
def _pool_rule(x: DistTensorSpec, **attrs):
    nsp = x.ndim - 2
    sub = "bc" + "".join("*" for _ in range(nsp))
    return einsum_infer(f"{sub}->{sub}", [x])


def _batched_linalg_notation(in_shapes, out_ranks):
    nb = max(len(in_shapes[0]) - 2, 0)
    in_subs = []
    for sh in in_shapes:
        b = max(len(sh) - 2, 0)
        in_subs.append(_letters(nb)[nb - b:] + "*" * (len(sh) - b))
    if out_ranks is None:
        out_ranks = [len(in_shapes[0])]
    out_subs = [_letters(nb)[: min(nb, r)] + "*" * (r - min(nb, r))
                for r in out_ranks]
    return ",".join(in_subs) + "->" + ",".join(out_subs)


@register_spmd_rule("batched_linalg")
def _batched_linalg_rule(*specs, out_ranks=None, **attrs):
    """Batched dense linalg (cholesky/inv/solve/qr/svd...): batch dims
    keep their sharding, trailing matrix dims compute whole per device.

    ``out_ranks``: rank per output (default: one output ranked like the
    FIRST input). Multi-output ops (qr/svd/lu/slogdet) and rank-reducing
    ops (det) pass their true output ranks; every output carries the
    merged batch sharding with its non-batch dims replicated."""
    return einsum_infer(
        _batched_linalg_notation([s.shape for s in specs], out_ranks),
        list(specs))


@register_spmd_rule("group_norm")
def _group_norm_rule(x: DistTensorSpec, scale=None, bias=None, **attrs):
    # batch sharded; channel/spatial normalise per device
    sub = "b" + "*" * (x.ndim - 1)
    specs = [x] + [s for s in (scale, bias) if s is not None]
    subs = [sub] + ["*" for s in (scale, bias) if s is not None]
    return einsum_infer(",".join(subs) + f"->{sub}", specs)


# ---------------------------------------------------------------------------
# Reverse-rule breadth (beyond the six structural families the planner
# completion uses): every notation-based rule gets its reverse through
# einsum_infer_reverse — the reference registers Infer...SpmdReverse for
# nearly every rule file (phi/infermeta/spmd_rules/*.h), and completion
# quality degrades wherever a reverse is missing.
# ---------------------------------------------------------------------------
def _register_notation_reverse(name, notation_of):
    """notation_of(in_shapes, attrs) -> einsum notation (same one the
    forward rule would build)."""
    @register_spmd_reverse(name)
    def _rev(in_shapes, out_specs, **attrs):
        return einsum_infer_reverse(
            notation_of(in_shapes, attrs), in_shapes, out_specs)
    _rev.__name__ = f"_{name}_reverse"
    return _rev


def _axis_star_sub(nd, axes):
    letters = _letters(nd)
    return "".join("*" if i in axes else c for i, c in enumerate(letters))


def _ident_notation(shapes, attrs):
    sub = _letters(len(shapes[0]))
    return f"{sub}->{sub}"


for _n in ("cast", "dropout", "clip", "scale", "tril", "triu"):
    _register_notation_reverse(_n, _ident_notation)

_register_notation_reverse(
    "softmax", lambda sh, at: (lambda sub: f"{sub}->{sub}")(
        _axis_star_sub(len(sh[0]), {at.get("axis", -1) % len(sh[0])})))
_register_notation_reverse(
    "cumsum", lambda sh, at: (lambda sub: f"{sub}->{sub}")(
        _axis_star_sub(len(sh[0]), {at.get("axis", -1) % len(sh[0])})))
_register_notation_reverse(
    "slice", lambda sh, at: (lambda sub: f"{sub}->{sub}")(
        _axis_star_sub(len(sh[0]),
                       {a % len(sh[0]) for a in at.get("axes", ())})))
_register_notation_reverse(
    "tile", lambda sh, at: _tile_notation(sh, at))


def _tile_notation(sh, at):
    nd = len(sh[0])
    rep = list(at.get("repeat_times", ()))
    rep = [1] * (nd - len(rep)) + rep[-nd:] if len(rep) <= nd else rep[-nd:]
    return (lambda sub: f"{sub}->{sub}")(
        _axis_star_sub(nd, {i for i in range(nd) if rep[i] != 1}))


_register_notation_reverse(
    "concat", lambda sh, at: (lambda sub: ",".join([sub] * len(sh))
                              + f"->{sub}")(
        _axis_star_sub(len(sh[0]), {at.get("axis", 0) % len(sh[0])})))


@register_spmd_reverse("split")
def _split_reverse(in_shapes, out_specs, num_or_sections=2, axis=0):
    nd = len(in_shapes[0])
    sub = _axis_star_sub(nd, {axis % nd})
    notation = sub + "->" + ",".join([sub] * len(out_specs))
    return einsum_infer_reverse(notation, in_shapes, out_specs)


@register_spmd_reverse("stack")
def _stack_reverse(in_shapes, out_specs, axis=0):
    nd = len(in_shapes[0])
    axis %= nd + 1
    letters = _letters(nd)
    notation = (",".join([letters] * len(in_shapes)) + "->"
                + letters[:axis] + "1" + letters[axis:])
    return einsum_infer_reverse(notation, in_shapes, out_specs)


@register_spmd_reverse("squeeze")
def _squeeze_reverse(in_shapes, out_specs, axis=None):
    shape = in_shapes[0]
    nd = len(shape)
    if axis is None:
        axes = [i for i, s in enumerate(shape) if s == 1]
    else:
        axes = [a % nd
                for a in (axis if isinstance(axis, (list, tuple))
                          else [axis])]
    letters = _letters(nd)
    sub = "".join("1" if i in axes else c for i, c in enumerate(letters))
    out = "".join(c for i, c in enumerate(letters) if i not in axes)
    return einsum_infer_reverse(f"{sub}->{out}", in_shapes, out_specs)


@register_spmd_reverse("unsqueeze")
def _unsqueeze_reverse(in_shapes, out_specs, axis=0):
    shape = in_shapes[0]
    axes = sorted(a % (len(shape) + 1)
                  for a in (axis if isinstance(axis, (list, tuple))
                            else [axis]))
    out = out_specs[0]
    in_dm = [m for d, m in enumerate(out.dims_mapping) if d not in axes]
    return ([DistTensorSpec(shape, in_dm)],
            [DistTensorSpec(out.shape, out.dims_mapping)])


_register_notation_reverse(
    "one_hot", lambda sh, at: (lambda sub: f"{sub}->{sub}c")(
        _letters(len(sh[0]), skip="c")))
_register_notation_reverse(
    "topk", lambda sh, at: (lambda sub: f"{sub}->{sub},{sub}")(
        _axis_star_sub(len(sh[0]), {at.get("axis", -1) % len(sh[0])})))
_register_notation_reverse(
    "where", lambda sh, at: _broadcast_subs(
        [DistTensorSpec(s) for s in sh]))
_register_notation_reverse(
    "bmm", lambda sh, at: "bmk,bkn->bmn")
_register_notation_reverse(
    "einsum", lambda sh, at: at["equation"])
_register_notation_reverse(
    "conv", lambda sh, at: (lambda nsp: f"bc{'*' * nsp},oc{'*' * nsp}"
                            f"->bo{'*' * nsp}")(len(sh[0]) - 2))


@register_spmd_reverse("layer_norm")
def _layer_norm_reverse(in_shapes, out_specs, begin_norm_axis=-1, **_):
    nd = len(in_shapes[0])
    begin_norm_axis %= nd
    letters = _letters(nd)
    sub = "".join("*" if i >= begin_norm_axis else c
                  for i, c in enumerate(letters))
    lead = sub[:begin_norm_axis]
    subs = [sub] + ["*" * len(s) for s in in_shapes[1:]]
    notation = ",".join(subs) + f"->{sub},{lead},{lead}"
    # out_specs may carry only `out` (mean/var letters then stay unseeded
    # — zip truncation is the intended partial-reverse contract)
    return einsum_infer_reverse(notation, in_shapes, out_specs)


@register_spmd_reverse("rms_norm")
def _rms_norm_reverse(in_shapes, out_specs, begin_norm_axis=-1, **_):
    ins, outs = _layer_norm_reverse(
        in_shapes, out_specs, begin_norm_axis=begin_norm_axis)
    return ins, outs[:1]


@register_spmd_reverse("flip")
def _flip_reverse(in_shapes, out_specs, **attrs):
    nd = len(in_shapes[0])
    sub = _axis_star_sub(nd, _flip_axes(nd, attrs))
    return einsum_infer_reverse(f"{sub}->{sub}", in_shapes, out_specs)


@register_spmd_reverse("roll")
def _roll_reverse(in_shapes, out_specs, **attrs):
    nd = len(in_shapes[0])
    sub = _axis_star_sub(nd, _roll_axes(nd, attrs))
    return einsum_infer_reverse(f"{sub}->{sub}", in_shapes, out_specs)


@register_spmd_reverse("pad")
def _pad_reverse(in_shapes, out_specs, **attrs):
    nd = len(in_shapes[0])
    sub = _axis_star_sub(nd, _pad_axes(nd, attrs))
    return einsum_infer_reverse(f"{sub}->{sub}", in_shapes, out_specs)


def _register_axis_replicated_reverse(name, n_out=1):
    @register_spmd_reverse(name)
    def _rev(in_shapes, out_specs, axis=-1, **attrs):
        nd = len(in_shapes[0])
        sub = _axis_star_sub(nd, {axis % nd})
        notation = f"{sub}->" + ",".join([sub] * n_out)
        return einsum_infer_reverse(notation, in_shapes, out_specs)
    _rev.__name__ = f"_{name}_reverse"
    return _rev


for _n in ("sort", "cummax", "cummin", "logcumsumexp", "kthvalue",
           "argsort"):
    _register_axis_replicated_reverse(
        _n, n_out=2 if _n in ("cummax", "cummin", "kthvalue",
                              "argsort") else 1)


@register_spmd_reverse("argmax")
def _argmax_reverse(in_shapes, out_specs, axis=-1, keepdim=False):
    nd = len(in_shapes[0])
    if axis is None:
        axes = set(range(nd))
    else:
        axes = {axis % nd}
    letters = _letters(nd)
    if keepdim:
        out = "".join("*" if i in axes else c
                      for i, c in enumerate(letters))
    else:
        out = "".join(c for i, c in enumerate(letters) if i not in axes)
    sub = "".join("*" if i in axes else c for i, c in enumerate(letters))
    return einsum_infer_reverse(f"{sub}->{out}", in_shapes, out_specs)


@register_spmd_reverse("gather")
def _gather_reverse(in_shapes, out_specs, axis=0):
    # out takes index's shape on the gathered axis; x's axis replicated
    nd = len(in_shapes[0])
    axis %= nd
    letters = _letters(nd, skip="i")
    x_sub = "".join("*" if i == axis else c
                    for i, c in enumerate(letters))
    idx_nd = len(in_shapes[1])
    idx_sub = _letters(idx_nd, skip=letters)  # distinct letters
    out_sub = (x_sub[:axis] + idx_sub + x_sub[axis + 1:])
    return einsum_infer_reverse(f"{x_sub},{idx_sub}->{out_sub}",
                                in_shapes, out_specs)


@register_spmd_reverse("index_select")
def _index_select_reverse(in_shapes, out_specs, axis=0):
    nd = len(in_shapes[0])
    axis %= nd
    letters = _letters(nd, skip="i")
    x_sub = "".join("*" if i == axis else c
                    for i, c in enumerate(letters))
    out_sub = "".join("i" if i == axis else c
                      for i, c in enumerate(letters))
    return einsum_infer_reverse(f"{x_sub},i->{out_sub}",
                                in_shapes, out_specs)


@register_spmd_reverse("take_along_axis")
def _take_along_axis_reverse(in_shapes, out_specs, axis=0):
    nd = len(in_shapes[0])
    sub = _axis_star_sub(nd, {axis % nd})
    return einsum_infer_reverse(f"{sub},{sub}->{sub}",
                                in_shapes, out_specs)


@register_spmd_reverse("c_embedding")
def _c_embedding_reverse(in_shapes, out_specs, start_index=0):
    # arg order (w, x); reuse the embedding reverse and swap back
    ins, outs = _embedding_reverse([in_shapes[1], in_shapes[0]], out_specs)
    return [ins[1], ins[0]], outs


# final reverse batch: the structurally-reversible remainder. moe_gate /
# moe_dispatch stay forward-only (the a2a layout is a semantic decision
# with no output-determined inverse), as in the reference.
def _pool_notation(sh, at):
    sub = "bc" + "*" * (len(sh[0]) - 2)
    return f"{sub}->{sub}"


def _conv_transpose_notation(sh, at):
    sp = "*" * (len(sh[0]) - 2)
    return f"bc{sp},co{sp}->bo{sp}"


_register_notation_reverse("pool", _pool_notation)
_register_notation_reverse("conv_transpose", _conv_transpose_notation)


@register_spmd_reverse("group_norm")
def _group_norm_reverse(in_shapes, out_specs, **attrs):
    sub = "b" + "*" * (len(in_shapes[0]) - 1)
    subs = [sub] + ["*"] * (len(in_shapes) - 1)
    return einsum_infer_reverse(",".join(subs) + f"->{sub}",
                                in_shapes, out_specs)


@register_spmd_reverse("scatter")
def _scatter_reverse(in_shapes, out_specs, axis=0):
    return einsum_infer_reverse(_scatter_notation(in_shapes, axis),
                                in_shapes, out_specs)


@register_spmd_reverse("put_along_axis")
def _put_along_axis_reverse(in_shapes, out_specs, axis=0):
    fake = [DistTensorSpec(s) for s in in_shapes]
    (x_sub, i_sub, v_sub), out = _along_axis_subs(fake, axis)
    return einsum_infer_reverse(f"{x_sub},{i_sub},{v_sub}->{out}",
                                in_shapes, out_specs)


@register_spmd_reverse("fused_rotary_position_embedding")
def _fused_rope_reverse(in_shapes, out_specs, **attrs):
    fake = [DistTensorSpec(s) for s in in_shapes]
    subs = _broadcast_subs(fake).split("->")[0].split(",")
    notation = ",".join(subs) + "->" + ",".join(subs)
    return einsum_infer_reverse(notation, in_shapes, out_specs)


@register_spmd_reverse("flash_attention")
def _flash_attention_reverse(in_shapes, out_specs, causal=True,
                             context_parallel=False):
    """Out [b, s, n, d] -> q gets its batch/seq/head sharding; k/v get
    batch + head (kv-seq whole unless ring attention); head_dim always
    replicated — the forward contract mirrored."""
    q_sub = "bsn*"
    kv_sub = "bsn*" if context_parallel else "b*n*"
    return einsum_infer_reverse(
        f"{q_sub},{kv_sub},{kv_sub}->{q_sub}", in_shapes, out_specs)


@register_spmd_reverse("cross_entropy_with_softmax")
def _ce_reverse(in_shapes, out_specs, axis=-1):
    """Reverse from (softmax_out, loss) or from the LOSS alone (a
    rank-(nd-1) single spec): leading dims flow to logits and labels;
    the vocab axis takes softmax_out's sharding when supplied. A
    vocab-sharded placement re-marks the corrected loss partial over
    that mesh dim — the forward ParallelCrossEntropy contract."""
    nd = len(in_shapes[0])
    axis %= nd
    letters = _letters(nd, skip="v")
    lg = letters[:axis] + "v" + letters[axis:nd - 1]
    lead = lg.replace("v", "")
    lbl = lead if len(in_shapes[1]) == nd - 1 else lead + "1"
    outs = list(out_specs)
    if len(outs) == 1 and outs[0].ndim == nd - 1:
        # loss-only completion: align the lone spec with the loss sub
        notation = f"{lg},{lbl}->{lead}"
        ins, new_outs = einsum_infer_reverse(notation, in_shapes, outs)
        return ins, new_outs
    ins, new_outs = einsum_infer_reverse(f"{lg},{lbl}->{lg},{lead}",
                                         in_shapes, outs)
    v_mesh = ins[0].dims_mapping[axis]
    if v_mesh >= 0 and len(new_outs) > 1:
        new_outs[1].partial_dims.add(v_mesh)
    return ins, new_outs


@register_spmd_reverse("batched_linalg")
def _batched_linalg_reverse(in_shapes, out_specs, out_ranks=None, **attrs):
    return einsum_infer_reverse(
        _batched_linalg_notation(in_shapes, out_ranks),
        in_shapes, out_specs)
