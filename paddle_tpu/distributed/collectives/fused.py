"""Fused compute-collective TP seams: matmul+reduce-scatter and
all-gather+matmul.

The Megatron row/col-parallel seams in ``models/gpt.py::_block_pure``
(attn ``o @ wo``, ffn ``ffn @ wd`` and their column-parallel partners)
are pre-PR whatever GSPMD emits: matmul, then a standalone mp
all-reduce of the full activation. These kernels make the seam explicit
("Optimizing Distributed ML Communication with Fused
Computation-Collective Operations", PAPERS.md):

- :func:`matmul_reduce_scatter` — row-parallel ``x @ w``: each shard
  multiplies its contraction slice, and the partial sums resolve
  DIRECTLY into sequence shards via reduce-scatter. Output is
  seq-sharded over the tp axis — half the wire bytes of the all-reduce,
  and the residual-add/norm between seams runs on 1/tp of the rows
  (Megatron sequence parallelism as an explicit kernel).
- :func:`all_gather_matmul` — column-parallel ``x @ w`` whose input is
  seq-sharded: the gather feeds the matmul inside one shard_map body, so
  XLA can overlap the gather with the first output tiles.

Both are ``custom_vjp``: the backward is hand-written per-shard
(all-gather+matmul backs matmul+reduce-scatter and vice versa; weight
grads psum over the data axes inside the body) — AD never transposes
through a collective, which legacy shard_map gets wrong by 1/tp (the
same discipline as the vocab-sharded CE, nn/functional/
fused_cross_entropy.py).

The islands are FULLY-manual shard_maps over the whole mesh (data axes
partition the batch dim, the tp axis partitions contraction/seq): this
XLA's SPMD partitioner rejects gather/scatter collectives in
partial-auto regions, so the seams cannot nest inside the quantized
dp-grad manual region — ``plan_tp_seams`` returns None there and the
grad reduce wins (docs/COMMS.md documents the precedence).
"""
from __future__ import annotations

import collections
import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _data_spec(data_axes):
    return tuple(data_axes) if data_axes else None


#: (mm_rs, ag_mm, tp) per mesh VALUE (process ids + shape + dim names,
#: not object identity — ProcessMesh defines no __eq__, and fleet
#: re-inits build equal-but-distinct meshes every test/strategy change).
#: Bounded so long-lived processes churning meshes can't grow it forever;
#: stable function identities keep jit from retracing per call.
_SEAM_CACHE = collections.OrderedDict()
_SEAM_CACHE_CAP = 32


def _seam_fns(mesh, tp_axis, data_axes):
    """One (mm_rs, ag_mm) custom_vjp pair per (mesh value, tp_axis,
    data_axes) — cached so jit sees stable function identities."""
    key = (tuple(mesh.process_ids), tuple(mesh.shape),
           tuple(mesh.dim_names), tp_axis, tuple(data_axes))
    fns = _SEAM_CACHE.get(key)
    if fns is not None:
        _SEAM_CACHE.move_to_end(key)
        return fns
    while len(_SEAM_CACHE) >= _SEAM_CACHE_CAP:
        _SEAM_CACHE.popitem(last=False)
    from jax import shard_map

    jmesh = mesh.jax_mesh
    D = _data_spec(data_axes)
    tp = mesh.get_dim_size(tp_axis)

    # ---- row-parallel: y = x @ w, x [b,s,k] k-sharded, w [k,n] ----------
    def _mm_rs_fwd_body(xl, wl):
        part = xl @ wl
        return jax.lax.psum_scatter(part, tp_axis, scatter_dimension=1,
                                    tiled=True)

    _mm_rs_fwd_sm = shard_map(
        _mm_rs_fwd_body, mesh=jmesh,
        in_specs=(P(D, None, tp_axis), P(tp_axis, None)),
        out_specs=P(D, tp_axis, None), check_vma=False)

    def _mm_rs_bwd_body(dyl, xl, wl):
        dyg = jax.lax.all_gather(dyl, tp_axis, axis=1, tiled=True)
        dxl = dyg @ wl.T
        dwl = jnp.einsum("bsk,bsn->kn", xl.astype(jnp.float32),
                         dyg.astype(jnp.float32))
        dwl = jax.lax.psum(dwl, data_axes) if data_axes else dwl
        return dxl.astype(xl.dtype), dwl.astype(wl.dtype)

    _mm_rs_bwd_sm = shard_map(
        _mm_rs_bwd_body, mesh=jmesh,
        in_specs=(P(D, tp_axis, None), P(D, None, tp_axis),
                  P(tp_axis, None)),
        out_specs=(P(D, None, tp_axis), P(tp_axis, None)),
        check_vma=False)

    @jax.custom_vjp
    def mm_rs(x, w):
        return _mm_rs_fwd_sm(x, w)

    def mm_rs_fwd(x, w):
        return _mm_rs_fwd_sm(x, w), (x, w)

    def mm_rs_bwd(res, dy):
        x, w = res
        return _mm_rs_bwd_sm(dy, x, w)

    mm_rs.defvjp(mm_rs_fwd, mm_rs_bwd)

    # ---- column-parallel: y = x @ w, x [b,s,h] seq-sharded, w [h,n] -----
    def _ag_mm_fwd_body(xl, wl):
        xg = jax.lax.all_gather(xl, tp_axis, axis=1, tiled=True)
        return xg @ wl

    _ag_mm_fwd_sm = shard_map(
        _ag_mm_fwd_body, mesh=jmesh,
        in_specs=(P(D, tp_axis, None), P(None, tp_axis)),
        out_specs=P(D, None, tp_axis), check_vma=False)

    def _ag_mm_bwd_body(dyl, xl, wl):
        dxp = dyl @ wl.T                       # partial over tp
        dxl = jax.lax.psum_scatter(dxp, tp_axis, scatter_dimension=1,
                                   tiled=True)
        xg = jax.lax.all_gather(xl, tp_axis, axis=1, tiled=True)
        dwl = jnp.einsum("bsh,bsn->hn", xg.astype(jnp.float32),
                         dyl.astype(jnp.float32))
        dwl = jax.lax.psum(dwl, data_axes) if data_axes else dwl
        return dxl.astype(xl.dtype), dwl.astype(wl.dtype)

    _ag_mm_bwd_sm = shard_map(
        _ag_mm_bwd_body, mesh=jmesh,
        in_specs=(P(D, None, tp_axis), P(D, tp_axis, None),
                  P(None, tp_axis)),
        out_specs=(P(D, tp_axis, None), P(None, tp_axis)),
        check_vma=False)

    @jax.custom_vjp
    def ag_mm(x, w):
        return _ag_mm_fwd_sm(x, w)

    def ag_mm_fwd(x, w):
        # save the SEQ-SHARDED input (1/tp of the rows) and re-gather in
        # backward — the remat-friendly choice
        return _ag_mm_fwd_sm(x, w), (x, w)

    def ag_mm_bwd(res, dy):
        x, w = res
        return _ag_mm_bwd_sm(dy, x, w)

    ag_mm.defvjp(ag_mm_fwd, ag_mm_bwd)
    _SEAM_CACHE[key] = (mm_rs, ag_mm, tp)
    return _SEAM_CACHE[key]


class TPSeamPlan:
    """Static seam context for one (mesh, tp_axis): resolved once per
    traced forward (StackedDecoder.forward) and threaded to every seam
    call in ``_block_pure``."""

    __slots__ = ("mesh", "tp_axis", "data_axes", "tp")

    def __init__(self, mesh, tp_axis, data_axes):
        self.mesh = mesh
        self.tp_axis = tp_axis
        self.data_axes = tuple(data_axes)
        self.tp = mesh.get_dim_size(tp_axis)

    def _applicable(self, x, w):
        """Shapes must split evenly: batch over the data axes, seq and
        the tp-sharded weight dim over tp. Also requires a traced
        context: the islands only lower under jit (legacy shard_map has
        no eager execution path), so concrete eager calls keep the plain
        matmul."""
        if not isinstance(x, jax.core.Tracer):
            return False
        if x.ndim != 3 or w.ndim != 2:
            return False
        b, s, _ = x.shape
        nd = 1
        for a in self.data_axes:
            nd *= self.mesh.get_dim_size(a)
        return b % nd == 0 and s % self.tp == 0

    def matmul_reduce_scatter(self, x, w):
        """Row-parallel seam; returns the seq-sharded product, or the
        plain matmul when shapes don't split."""
        if not (self._applicable(x, w) and x.shape[2] % self.tp == 0):
            return x @ w
        mm_rs, _, _ = _seam_fns(self.mesh, self.tp_axis, self.data_axes)
        return mm_rs(x, w)

    def all_gather_matmul(self, x, w):
        """Column-parallel seam over a (possibly) seq-sharded input."""
        if not (self._applicable(x, w) and w.shape[1] % self.tp == 0):
            return x @ w
        _, ag_mm, _ = _seam_fns(self.mesh, self.tp_axis, self.data_axes)
        return ag_mm(x, w)


def tp_seam_mode():
    """PTPU_TP_SEAM: "auto" (default — fuse when the mesh allows),
    "fused" (force where structurally possible), "0" (off)."""
    return os.environ.get("PTPU_TP_SEAM", "auto").strip().lower()


def plan_tp_seams(mesh, tp_axis="mp"):
    """Resolve the fused-seam plan for this trace, or None.

    Engages when the master knob is on, ``PTPU_TP_SEAM`` is not "0",
    the tp axis is live, no pipeline axis is live (the pipeline keeps
    'pp' manual and the islands cannot nest in it), and the trace is not
    inside the quantized dp-grad manual region (same nesting limit —
    the grad reduce takes precedence; docs/COMMS.md)."""
    from . import in_manual_grad_region, quant_collectives_enabled

    mode = tp_seam_mode()
    if mode in ("0", "off", "false") or not quant_collectives_enabled():
        return None
    if mesh is None or tp_axis not in mesh.dim_names:
        return None
    if mesh.get_dim_size(tp_axis) <= 1:
        return None
    if "pp" in mesh.dim_names and mesh.get_dim_size("pp") > 1:
        return None
    if "sep" in mesh.dim_names and mesh.get_dim_size("sep") > 1:
        return None
    if in_manual_grad_region():
        return None
    data_axes = tuple(
        a for a in ("dp", "sharding")
        if a in mesh.dim_names and mesh.get_dim_size(a) > 1)
    return TPSeamPlan(mesh, tp_axis, data_axes)
