"""Ring attention over the ``sep`` mesh axis: context parallelism as a
build-time plan (docs/ATTENTION.md).

Pre-PR, the ``sep`` axis existed in every ProcessMesh but
``parallel_step._batch_spec`` treated it as one more batch axis — 32k+
contexts were unreachable because every chip still ran attention over
the full sequence it held. This module makes ``sep`` a real context-
parallel axis: the :class:`RingAttnPlan` (duck-typing the
``GradReducePlan``/``ZeroPlan`` engagement discipline — resolved ONCE at
step build, decline matrix, ``PTPU_RING_ATTN=0`` escape hatch) runs the
whole (forward, loss, backward) program inside the manual shard_map
region with the batch's SEQUENCE dim sharded over ``sep``. Attention
executes as a ring: each hop calls the existing Pallas flash kernel
(ops/pallas/flash_attention) on the resident KV block while
``lax.ppermute`` rotates the next KV block around the ring — the
ppermute is issued BEFORE the hop's compute so XLA's scheduler can hide
the rotation under the kernel (FlashFuser / fused computation-collective
grounding, PAPERS.md). Hops merge through online-softmax running
``(max, sumexp, acc)`` state; the backward is a hand-written custom_vjp
that replays the rotation and accumulates dk/dv per hop (AD never
transposes a ppermute — the repo's shard_map discipline).

Causal load balance — the zigzag layout
---------------------------------------
A contiguous seq shard under a causal mask gives rank 0 one hop of work
and rank n-1 n hops. Instead the sequence is split into ``2n`` chunks
and rank ``r`` holds the PAIR ``(chunk r, chunk 2n-1-r)`` — the zigzag
assignment (``zigzag_perm``). Every hop then costs exactly half a local
attention square on every rank:

- hop 0 (``src == r``): the local pair is globally ascending, so the
  kernel's plain causal mask at ``sq == sk`` is exactly the global mask;
- ``src < r``: all local queries attend ONLY the kv pair's first chunk,
  fully — one non-causal ``sq = S_loc, sk = S_loc/2`` kernel call;
- ``src > r``: only the local second-half queries attend, and they
  attend the whole kv pair — one non-causal ``sq = S_loc/2, sk = S_loc``
  call.

Both off-diagonal kinds are end-aligned ``sq != sk`` calls in the flash
kernel's documented convention (query rows align to the END of the key
sequence); because each is FULLY visible the end-alignment offset is
inert, and the diagonal hop is the ``offset = 0`` degenerate — the ring
never needs a mask the kernel does not already implement. The branch
between the two off-diagonal kinds depends on the rank ordinal (a
traced, ``P(sep)``-sharded iota — ``lax.axis_index`` lowers to the
PartitionId op this XLA rejects), so it is a ``lax.cond`` between two
equal-cost, equal-shape branches.

Numerics contract (docs/ATTENTION.md): the ring is float32-hex identical
to :func:`ring_reference` (the single-device replay of the same hop
decomposition — proving the ppermute/shard_map machinery adds zero
numeric noise) and agrees with the one-shot attention path to ~1e-6
relative — NOT bitwise, because online-softmax accumulation order over
kv blocks differs, exactly as the flash kernel itself differs from dense
softmax. ``PTPU_RING_ATTN=0`` restores the pre-PR program byte-for-byte.
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
import os
import threading

import numpy as np

import jax
import jax.numpy as jnp

from .overlap import GradBucket, GradReducePlan, partition_buckets  # noqa: F401

NEG_INF = np.float32(-1e30)


# ---------------------------------------------------------------- knobs

def ring_attn_enabled():
    """Master switch (``PTPU_RING_ATTN``, default ON). ``=0`` is the
    bitwise escape hatch: the plan never builds, ``sep`` stays a plain
    batch axis, and the compiled step is byte-identical to the pre-PR
    program (tested against a force-declined build)."""
    return os.environ.get("PTPU_RING_ATTN", "1") not in ("0", "off")


def ring_kernel_mode():
    """Per-hop compute path (``PTPU_RING_KERNEL``): ``auto`` (default —
    the Pallas flash kernel on TPU, the jnp online-softmax math
    elsewhere), ``interpret`` (force the kernel through the Pallas
    interpreter — the CPU-mesh parity tests drive the real kernel code
    this way), ``xla`` (force the jnp math everywhere)."""
    env = os.environ.get("PTPU_RING_KERNEL", "").strip().lower()
    if env in ("", "auto"):
        return "auto"
    if env in ("interpret", "xla"):
        return env
    raise ValueError(
        f"PTPU_RING_KERNEL={env!r}: expected auto|interpret|xla")


def _hops_use_kernel(s_loc, d):
    """Whether this shape's hops run the Pallas flash kernel (mirrors
    nn.functional.flash_attention._use_pallas, plus the zigzag
    half-chunk tiling constraint)."""
    from ...ops.pallas import on_tpu_device
    from ...ops.pallas.flash_attention import supported_seq

    mode = ring_kernel_mode()
    if mode == "xla":
        return False
    if not (on_tpu_device() or mode == "interpret"):
        return False
    return (d <= 256 and supported_seq(s_loc)
            and s_loc % 2 == 0 and supported_seq(s_loc // 2))


# ---------------------------------------------------------------- zigzag

def zigzag_perm(seq, nranks):
    """Token permutation putting the NATURAL-order sequence into the
    zigzag layout: contiguous shard ``r`` of the permuted sequence holds
    global chunks ``(r, 2n-1-r)``. Returns an int32 numpy index vector
    (``x_zig = x[:, perm]``)."""
    if seq % (2 * nranks):
        raise ValueError(
            f"zigzag_perm: seq {seq} must divide into 2*nranks "
            f"({2 * nranks}) chunks")
    c = seq // (2 * nranks)
    idx = np.arange(seq, dtype=np.int32).reshape(2 * nranks, c)
    order = []
    for r in range(nranks):
        order.append(idx[r])
        order.append(idx[2 * nranks - 1 - r])
    return np.concatenate(order)


def zigzag_inverse_perm(seq, nranks):
    """Inverse of :func:`zigzag_perm` (``x = x_zig[:, inv]``)."""
    perm = zigzag_perm(seq, nranks)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq, dtype=np.int32)
    return inv


def zigzag_positions(ordinal, s_loc, nranks):
    """Global token positions of one shard's local rows, as a traced
    i32 ``[s_loc]`` vector: first half ``ord*C + [0..C)``, second half
    ``(2n-1-ord)*C + [0..C)`` with ``C = s_loc // 2`` — what rope must
    see instead of ``0..s_loc`` (docs/ATTENTION.md)."""
    c = s_loc // 2
    ar = jnp.arange(c, dtype=jnp.int32)
    ordinal = jnp.asarray(ordinal, jnp.int32)
    first = ordinal * c + ar
    second = (2 * nranks - 1 - ordinal) * c + ar
    return jnp.concatenate([first, second])


# ---------------------------------------------------------------- context

class RingContext:
    """Trace-scoped handle the model's attention/rope seams consult
    (models/gpt.py ``_sdpa_pure`` / ``_block_pure``): carries the sep
    ordinal (a traced scalar), the ring geometry, and records what the
    trace routed through the ring so the plan's engagement can be
    verified and its traffic accounted (``note_ring_attn``)."""

    def __init__(self, axis, nranks, ordinal, plan=None):
        self.axis = axis
        self.nranks = int(nranks)
        self.ordinal = ordinal
        self.plan = plan
        self.calls = 0

    def rope_tables(self, s_loc, head_dim, base=10000.0):
        """Zigzag-global-position sin/cos tables, broadcast-ready for
        ``[B, S_loc, H, D]`` activations (shape ``[1, S_loc, 1, d/2]``).
        Delegates to the ONE shared frequency formula
        (``models.gpt._rope_tables_at``) so ring rotation can never
        drift from the single-device rope. Computed fresh per request —
        a cached tracer would leak across ``jax.checkpoint`` retraces."""
        from ...models.gpt import _rope_tables_at

        p = zigzag_positions(self.ordinal, s_loc, self.nranks)
        return _rope_tables_at(p, head_dim, base)


_TLS = threading.local()


@contextlib.contextmanager
def ring_scope(ctx):
    prev = getattr(_TLS, "ring_ctx", None)
    _TLS.ring_ctx = ctx
    try:
        yield ctx
    finally:
        _TLS.ring_ctx = prev


def active_ring_context():
    """The RingContext of the enclosing engaged ring region, or None —
    the dispatch seam models/gpt.py consults."""
    return getattr(_TLS, "ring_ctx", None)


# ---------------------------------------------------------------- hop math

def _hop_flash(q, k, v, causal, scale, interpret, hq, hk):
    """One hop through the Pallas flash forward: ``[B, S, H, D]`` in,
    ``(o, lse [B, Hq, Sq])`` out — lse is the merge currency."""
    from ...ops.pallas.flash_attention import _fwd, from_bh, to_bh

    b, sq = q.shape[0], q.shape[1]
    o, lse = _fwd(to_bh(q, hq), to_bh(k, hk), to_bh(v, hk), float(scale),
                  bool(causal), bool(interpret), hq, hk)
    return from_bh(o, b, hq), lse.reshape(b, hq, sq)


def _hop_xla(q, k, v, causal, scale):
    """jnp online-softmax hop with the same ``(o, lse)`` contract — the
    CPU / untileable-shape path. Identical formulas to the kernel: f32
    scores, row max, ``exp``, per-hop normalized output."""
    hq, hk = q.shape[2], k.shape[2]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * np.float32(scale)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                          # [B, H, Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhst,bthd->bshd", p, v.astype(jnp.float32))
    l_safe = jnp.where(l == 0.0, np.float32(1.0), l)
    o = (o / jnp.transpose(l_safe, (0, 2, 1))[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return o, lse


def _hop_fwd(q, k, v, causal, scale, use_kernel, interpret, hq, hk):
    if use_kernel:
        return _hop_flash(q, k, v, causal, scale, interpret, hq, hk)
    return _hop_xla(q, k, v, causal, scale)


def _hop_bwd_flash(q, k, v, o, lse, do, causal, scale, interpret, hq, hk):
    """One hop through the Pallas flash backward against the GLOBAL lse
    (``p = exp(s - lse)`` is exact for the full softmax, so per-hop
    dq/dk/dv sum to the true grads)."""
    from ...ops.pallas.flash_attention import _bwd, from_bh, to_bh

    b = q.shape[0]
    dq, dk, dv = _bwd(to_bh(q, hq), to_bh(k, hk), to_bh(v, hk),
                      to_bh(o, hq), lse.reshape(b * hq, q.shape[1]),
                      to_bh(do, hq), float(scale), bool(causal),
                      bool(interpret), hq, hk)
    return (from_bh(dq, b, hq).astype(jnp.float32),
            from_bh(dk, b, hk).astype(jnp.float32),
            from_bh(dv, b, hk).astype(jnp.float32))


def _hop_bwd_xla(q, k, v, o, lse, do, causal, scale):
    """jnp hop backward with the flash-backward formulas: p from the
    global lse, ``delta = sum(do * o)``, ``ds = p * (dp - delta)``.
    GQA folds the repeated-head dk/dv back onto the kv heads."""
    hq, hk = q.shape[2], k.shape[2]
    rep = hq // hk
    kf = jnp.repeat(k, rep, axis=2) if rep > 1 else k
    vf = jnp.repeat(v, rep, axis=2) if rep > 1 else v
    s = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                   kf.astype(jnp.float32)) * np.float32(scale)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse[..., None])                            # [B,H,Sq,Sk]
    dof = do.astype(jnp.float32)
    of = o.astype(jnp.float32)
    delta = jnp.einsum("bshd,bshd->bhs", dof, of)              # [B,H,Sq]
    dp = jnp.einsum("bshd,bthd->bhst", dof, vf.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * np.float32(scale)
    dq = jnp.einsum("bhst,bthd->bshd", ds, kf.astype(jnp.float32))
    dk = jnp.einsum("bhst,bshd->bthd", ds, q.astype(jnp.float32))
    dv = jnp.einsum("bhst,bshd->bthd", p, dof)
    if rep > 1:
        b, sk = k.shape[0], k.shape[1]
        dk = dk.reshape(b, sk, hk, rep, -1).sum(axis=3)
        dv = dv.reshape(b, sk, hk, rep, -1).sum(axis=3)
    return dq, dk, dv


def _hop_bwd(q, k, v, o, lse, do, causal, scale, use_kernel, interpret,
             hq, hk):
    if use_kernel:
        return _hop_bwd_flash(q, k, v, o, lse, do, causal, scale,
                              interpret, hq, hk)
    return _hop_bwd_xla(q, k, v, o, lse, do, causal, scale)


# ---------------------------------------------------------------- forward

def _merge_state(m, l, acc, o_blk, lse_blk):
    """Online-softmax running-(max, sumexp, acc) merge of one hop's
    normalized ``(o, lse)`` block: the hop contributes one mega-column
    with score ``lse_blk`` and value ``o_blk`` (``o * exp(lse)`` IS the
    hop's unnormalized accumulator). Skip rows ride in as
    ``lse = NEG_INF`` and contribute an exact 0."""
    m_new = jnp.maximum(m, lse_blk)                   # [B, H, S]
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(lse_blk - m_new)
    bs = jnp.transpose(beta, (0, 2, 1))[..., None]    # [B, S, H, 1]
    as_ = jnp.transpose(alpha, (0, 2, 1))[..., None]
    acc = acc * as_ + o_blk.astype(jnp.float32) * bs
    l = l * alpha + beta
    return m_new, l, acc


def _ring_fwd_impl(q, k, v, ordinal, *, axis, nranks, causal, scale,
                   use_kernel, interpret, hq, hk):
    """Zigzag ring forward. Returns (out [B,S,H,D] in q.dtype,
    lse [B,Hq,S] f32 — the global log-sum-exp, the backward's anchor)."""
    b, s_loc, h, d = q.shape
    c = s_loc // 2
    perm = [(j, (j + 1) % nranks) for j in range(nranks)]
    ordinal = jnp.asarray(ordinal, jnp.int32)

    m = jnp.full((b, hq, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hq, s_loc), jnp.float32)
    acc = jnp.zeros((b, s_loc, hq, d), jnp.float32)

    kt, vt = k, v
    for t in range(nranks):
        # issue the NEXT hop's rotation before this hop's compute: the
        # ppermute has no data dependence on the kernel, so XLA's
        # scheduler can run the DMA under the flash compute
        if t != nranks - 1:
            kn = jax.lax.ppermute(kt, axis, perm)
            vn = jax.lax.ppermute(vt, axis, perm)
        if t == 0:
            # diagonal hop (src == r on every rank — static): the local
            # zigzag pair is globally ascending, so plain causal at
            # sq == sk is exactly the global mask
            o_blk, lse_blk = _hop_fwd(q, kt, vt, causal, scale,
                                      use_kernel, interpret, hq, hk)
        elif not causal:
            o_blk, lse_blk = _hop_fwd(q, kt, vt, False, scale,
                                      use_kernel, interpret, hq, hk)
        else:
            # src = (r - t) mod n. src < r  <=>  t <= r:
            #   all local queries attend only the kv pair's FIRST chunk
            #   (fully). src > r: only the local SECOND-half queries
            #   attend, and they see the whole kv pair. Both are single
            #   non-causal end-aligned flash calls of equal cost.
            def _earlier(kt, vt):
                o_b, lse_b = _hop_fwd(q, kt[:, :c], vt[:, :c], False,
                                      scale, use_kernel, interpret,
                                      hq, hk)
                return o_b, lse_b

            def _later(kt, vt):
                o_h, lse_h = _hop_fwd(q[:, c:], kt, vt, False, scale,
                                      use_kernel, interpret, hq, hk)
                o_b = jnp.concatenate(
                    [jnp.zeros((b, c, hq, d), o_h.dtype), o_h], axis=1)
                lse_b = jnp.concatenate(
                    [jnp.full((b, hq, c), NEG_INF, jnp.float32), lse_h],
                    axis=2)
                return o_b, lse_b

            o_blk, lse_blk = jax.lax.cond(t <= ordinal, _earlier, _later,
                                          kt, vt)
        m, l, acc = _merge_state(m, l, acc, o_blk, lse_blk)
        if t != nranks - 1:
            kt, vt = kn, vn

    l_safe = jnp.where(l == 0.0, np.float32(1.0), l)
    out = (acc / jnp.transpose(l_safe, (0, 2, 1))[..., None]).astype(
        q.dtype)
    lse = m + jnp.log(l_safe)
    return out, lse


# ---------------------------------------------------------------- backward

def _ring_bwd_impl(q, k, v, out, lse, do, ordinal, *, axis, nranks,
                   causal, scale, use_kernel, interpret, hq, hk):
    """Hand-written ring backward: replay the kv rotation (forward-
    direction ppermutes only — AD never transposes one); per hop run the
    flash backward against the GLOBAL lse. dq accumulates locally; the
    dk/dv accumulators travel WITH their kv block, so after the loop's
    final rotation every block's grads are home."""
    b, s_loc, h, d = q.shape
    c = s_loc // 2
    perm = [(j, (j + 1) % nranks) for j in range(nranks)]
    ordinal = jnp.asarray(ordinal, jnp.int32)

    dq = jnp.zeros((b, s_loc, hq, d), jnp.float32)
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    kt, vt = k, v
    for t in range(nranks):
        if t == 0:
            dq_b, dk_b, dv_b = _hop_bwd(q, kt, vt, out, lse, do, causal,
                                        scale, use_kernel, interpret,
                                        hq, hk)
        elif not causal:
            dq_b, dk_b, dv_b = _hop_bwd(q, kt, vt, out, lse, do, False,
                                        scale, use_kernel, interpret,
                                        hq, hk)
        else:
            def _earlier(kt, vt):
                dq_b, dk_h, dv_h = _hop_bwd(
                    q, kt[:, :c], vt[:, :c], out, lse, do, False, scale,
                    use_kernel, interpret, hq, hk)
                pad = jnp.zeros((b, c, hk, d), jnp.float32)
                return (dq_b, jnp.concatenate([dk_h, pad], axis=1),
                        jnp.concatenate([dv_h, pad], axis=1))

            def _later(kt, vt):
                dq_h, dk_b, dv_b = _hop_bwd(
                    q[:, c:], kt, vt, out[:, c:], lse[:, :, c:],
                    do[:, c:], False, scale, use_kernel, interpret,
                    hq, hk)
                dq_b = jnp.concatenate(
                    [jnp.zeros((b, c, hq, d), jnp.float32), dq_h],
                    axis=1)
                return dq_b, dk_b, dv_b

            dq_b, dk_b, dv_b = jax.lax.cond(t <= ordinal, _earlier,
                                            _later, kt, vt)
        dq = dq + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        # rotate kv WITH its grad accumulators; the accumulators rotate
        # one extra (final-iteration) hop to come home, but the kv
        # blocks are done being read after the last compute — don't pay
        # a dead collective for them
        if t != nranks - 1:
            kt = jax.lax.ppermute(kt, axis, perm)
            vt = jax.lax.ppermute(vt, axis, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


# ---------------------------------------------------------------- custom_vjp

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9,
                                                    10, 11))
def _ring(q, k, v, ordinal, axis, nranks, causal, scale, use_kernel,
          interpret, hq, hk):
    out, _ = _ring_fwd_impl(q, k, v, ordinal, axis=axis, nranks=nranks,
                            causal=causal, scale=scale,
                            use_kernel=use_kernel, interpret=interpret,
                            hq=hq, hk=hk)
    return out


def _ring_fwd_rule(q, k, v, ordinal, axis, nranks, causal, scale,
                   use_kernel, interpret, hq, hk):
    out, lse = _ring_fwd_impl(q, k, v, ordinal, axis=axis, nranks=nranks,
                              causal=causal, scale=scale,
                              use_kernel=use_kernel, interpret=interpret,
                              hq=hq, hk=hk)
    # the same remat anchors the single-device flash path names: a
    # policy saving attn_res/attn_lse reuses them instead of re-running
    # the whole ring forward in backward (docs/ATTENTION.md)
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "attn_res")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse, ordinal)


def _ring_bwd_rule(axis, nranks, causal, scale, use_kernel, interpret,
                   hq, hk, res, do):
    q, k, v, out, lse, ordinal = res
    dq, dk, dv = _ring_bwd_impl(q, k, v, out, lse, do, ordinal,
                                axis=axis, nranks=nranks, causal=causal,
                                scale=scale, use_kernel=use_kernel,
                                interpret=interpret, hq=hq, hk=hk)
    # the ordinal is an integer operand: its cotangent type is float0
    d_ord = np.zeros(np.shape(ordinal), jax.dtypes.float0)
    return dq, dk, dv, d_ord


_ring.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ring_attention(q, k, v, ctx, causal=True, scale=None):
    """Context-parallel attention over the local zigzag shard
    ``[B, S_loc, H, D]`` — the dispatch target of ``models/gpt.py``
    ``_sdpa_pure`` / ``sdpa_arrays`` inside an engaged ring region.
    Differentiable via the hand-written ring custom_vjp."""
    from ...ops.pallas import log_path_once, on_tpu_device

    b, s_loc, hq, d = q.shape
    hk = k.shape[2]
    if hq % hk != 0:
        raise ValueError(
            f"ring attention: q heads ({hq}) must be a multiple of kv "
            f"heads ({hk})")
    if s_loc % 2 != 0:
        raise ValueError(
            f"ring attention: local seq {s_loc} must be even (zigzag "
            "holds two chunks per rank) — the plan's seq_ok gate should "
            "have declined this shape")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    use_kernel = _hops_use_kernel(s_loc, d)
    interpret = not on_tpu_device()
    log_path_once("ring_attn", "pallas_flash" if use_kernel else "xla")
    ctx.calls += 1
    if ctx.plan is not None:
        ctx.plan.record_trace(q.shape, k.shape,
                              "pallas" if use_kernel else "xla")
    return _ring(q, k, v, ctx.ordinal, ctx.axis, ctx.nranks,
                 bool(causal), float(scale), use_kernel, bool(interpret),
                 hq, hk)


# ---------------------------------------------------------------- oracle

def ring_reference(q, k, v, nranks, causal=True, scale=None,
                   use_kernel=False, interpret=True):
    """Single-device replay of the EXACT ring decomposition over
    NATURAL-order ``[B, S, H, D]`` inputs: zigzag-permute, run every
    rank's hop sequence with concrete ordinals (same hop functions, same
    merge), inverse-permute. The float32-hex parity oracle — any
    difference between this and the shard_map ring is noise introduced
    by the distributed machinery, which the tests assert is zero."""
    b, s, hq, d = q.shape
    hk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    perm = zigzag_perm(s, nranks)
    inv = zigzag_inverse_perm(s, nranks)
    qz = jnp.take(q, perm, axis=1)
    kz = jnp.take(k, perm, axis=1)
    vz = jnp.take(v, perm, axis=1)
    s_loc = s // nranks
    c = s_loc // 2
    shards_q = [qz[:, r * s_loc:(r + 1) * s_loc] for r in range(nranks)]
    shards_k = [kz[:, r * s_loc:(r + 1) * s_loc] for r in range(nranks)]
    shards_v = [vz[:, r * s_loc:(r + 1) * s_loc] for r in range(nranks)]
    outs = []
    for r in range(nranks):
        m = jnp.full((b, hq, s_loc), NEG_INF, jnp.float32)
        l = jnp.zeros((b, hq, s_loc), jnp.float32)
        acc = jnp.zeros((b, s_loc, hq, d), jnp.float32)
        qr = shards_q[r]
        for t in range(nranks):
            src = (r - t) % nranks
            kt, vt = shards_k[src], shards_v[src]
            if t == 0:
                o_b, lse_b = _hop_fwd(qr, kt, vt, causal, scale,
                                      use_kernel, interpret, hq, hk)
            elif not causal:
                o_b, lse_b = _hop_fwd(qr, kt, vt, False, scale,
                                      use_kernel, interpret, hq, hk)
            elif src < r:
                o_b, lse_b = _hop_fwd(qr, kt[:, :c], vt[:, :c], False,
                                      scale, use_kernel, interpret,
                                      hq, hk)
            else:
                o_h, lse_h = _hop_fwd(qr[:, c:], kt, vt, False, scale,
                                      use_kernel, interpret, hq, hk)
                o_b = jnp.concatenate(
                    [jnp.zeros((b, c, hq, d), o_h.dtype), o_h], axis=1)
                lse_b = jnp.concatenate(
                    [jnp.full((b, hq, c), NEG_INF, jnp.float32), lse_h],
                    axis=2)
            m, l, acc = _merge_state(m, l, acc, o_b, lse_b)
        l_safe = jnp.where(l == 0.0, np.float32(1.0), l)
        outs.append(
            (acc / jnp.transpose(l_safe, (0, 2, 1))[..., None]).astype(
                q.dtype))
    return jnp.take(jnp.concatenate(outs, axis=1), inv, axis=1)


# ---------------------------------------------------------------- plan

@dataclasses.dataclass
class RingAttnPlan:
    """Static description of one step's ring-attention engagement,
    resolved ONCE at step build (knobs at BUILD, never per call —
    the GradReducePlan/ZeroPlan discipline). Carries its own composed
    grad-reduce plan (``reduce``, axes = data axes + sep: every grad is
    partial over ``sep`` because each shard back-propagates only its
    local tokens' loss) and the static per-step ring-traffic accounting
    behind ``note_ring_attn``. Mutable only for the trace-time shape
    record (``record_trace``)."""
    axis: str                 # the sep mesh axis name
    sep_degree: int
    data_axes: tuple          # live dp/sharding axes (batch dim 0)
    axes: tuple               # data_axes + (axis,) — pmean/reduce axes
    nranks: int               # product over axes
    reduce: GradReducePlan
    layers: int               # attention layers (traffic multiplier)
    # trace-time records (filled by ring_attention as signatures trace;
    # keyed by local seq so alternating batch lengths each keep their
    # own accounting — _place_batch_ring points seq_local at the batch
    # actually dispatching):
    seq_local: int = 0
    kernel: str = "unresolved"
    calls_traced: int = 0
    trace_records: dict = dataclasses.field(default_factory=dict)

    def record_trace(self, q_shape, k_shape, kernel):
        self.calls_traced += 1
        b, s_loc, _, d = q_shape
        hk = k_shape[2]
        # payload basis (docs/TELEMETRY.md): one rank's resident k+v
        # block at 4B/elem — a fixed dtype-independent basis, like the
        # grad-reduce counters' payload-bytes-entering basis
        self.trace_records[int(s_loc)] = (
            2 * int(b) * int(s_loc) * int(hk) * int(d) * 4, kernel)
        self.seq_local = int(s_loc)
        self.kernel = kernel

    def set_active_seq(self, seq):
        """Point the accounting at the batch signature about to
        dispatch (called from placement) — a cached program for an
        earlier length must not tick the newest trace's bytes."""
        s_loc = int(seq) // self.sep_degree
        rec = self.trace_records.get(s_loc)
        if rec is not None:
            self.seq_local = s_loc
            self.kernel = rec[1]

    @property
    def kv_block_bytes(self):
        rec = self.trace_records.get(self.seq_local)
        return rec[0] if rec else 0

    # per-step rotated bytes (static per plan signature): forward
    # rotates k+v over (n-1) hops; backward rotates k+v over (n-1)
    # hops plus the two f32 dk/dv accumulators (together k+v-shaped)
    # over n hops — the final hop carries only the accumulators home
    @property
    def fwd_rotate_bytes(self):
        return (self.sep_degree - 1) * self.kv_block_bytes * self.layers

    @property
    def bwd_rotate_bytes(self):
        return ((2 * self.sep_degree - 1) * self.kv_block_bytes
                * self.layers)

    def seq_ok(self, seq):
        """Whether this GLOBAL sequence length can ride the ring:
        zigzag needs 2*sep chunks; the kernel path additionally needs
        Mosaic-tileable local and half-local lengths. A failing length
        falls back to the pre-PR program for that batch signature
        (decline matrix, docs/ATTENTION.md)."""
        n = self.sep_degree
        if seq % (2 * n):
            return False
        s_loc = seq // n
        if ring_kernel_mode() == "xla":
            return True
        from ...ops.pallas import on_tpu_device

        if not (on_tpu_device() or ring_kernel_mode() == "interpret"):
            return True  # jnp hops: only the zigzag divisibility matters
        from ...ops.pallas.flash_attention import supported_seq

        return bool(supported_seq(s_loc) and supported_seq(s_loc // 2))

    def summary(self):
        """JSON-able shape for the bench ``"ring"`` block /
        docs/ATTENTION.md contract."""
        return {
            "axis": self.axis, "sep_degree": self.sep_degree,
            "data_axes": list(self.data_axes), "nranks": self.nranks,
            "layers": self.layers, "kernel": self.kernel,
            "seq_local": self.seq_local,
            "fwd_rotate_bytes": int(self.fwd_rotate_bytes),
            "bwd_rotate_bytes": int(self.bwd_rotate_bytes),
            "grad_reduce": self.reduce.summary(),
        }


def _ring_layers(model):
    """Attention-layer count of the model's ring-eligible decoder
    stacks, or 0 when the model has none (engagement requires a stack
    that routes attention through ``_sdpa_pure`` — an arbitrary model
    inside the region would silently compute LOCAL-only attention)."""
    try:
        from ...models.gpt import GPTModel, StackedDecoder
    except Exception:  # pragma: no cover - models optional
        return 0
    layers = 0
    for _, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, StackedDecoder):
            layers += int(sub.config.num_layers)
        elif isinstance(sub, GPTModel):
            # the eager LayerList frontend is ring-eligible exactly when
            # it routes through the shared _block_pure scan body
            if sub._shared_block_eligible(None):
                layers += int(sub.config.num_layers)
            else:
                return 0
    return layers


def build_ring_attn_plan(named_params, mesh, model, reason_out=None):
    """Build the step's ring plan, or None (decline). The decline matrix
    (docs/ATTENTION.md — declined configs keep the pre-PR program
    byte-for-byte):

    - ``PTPU_RING_ATTN=0`` (the escape hatch);
    - no live ``sep`` axis (size >= 2);
    - any live mesh axis outside {dp, sharding, sep}: pipeline / tensor
      / expert kernels open their own manual regions, which cannot nest
      inside ours on this XLA (the PR 6 rule);
    - no ring-eligible decoder stack on the model (attention must
      provably route through the ``_sdpa_pure`` seam);
    - checkify / vocab-sharded head / ZeRO stage >= 2: checked by the
      caller (ShardedTrainStep), which owns those build facts.

    Non-divisible sequence lengths decline PER BATCH SIGNATURE via
    :meth:`RingAttnPlan.seq_ok` — the plan itself stays built.
    """
    from .compose import Reason
    from .compose import note_decline as _note

    if not ring_attn_enabled():
        from . import quant_collectives_enabled

        return _note(reason_out,
                     Reason.MASTER_OFF if not quant_collectives_enabled()
                     else Reason.RING_OFF)
    live = {a: mesh.get_dim_size(a) for a in mesh.dim_names
            if mesh.get_dim_size(a) > 1}
    n = live.get("sep", 1)
    if n < 2:
        return _note(reason_out, Reason.NO_SEP)
    if not set(live) <= {"dp", "sharding", "sep"}:
        return _note(reason_out, Reason.MESH_AXES)
    layers = _ring_layers(model)
    if not layers:
        return _note(reason_out, Reason.MODEL_INELIGIBLE)
    data_axes = tuple(a for a in ("dp", "sharding") if a in live)
    axes = data_axes + ("sep",)
    nranks = 1
    for a in axes:
        nranks *= live[a]
    from . import grads_quantized

    buckets = partition_buckets(named_params, quantized=grads_quantized())
    reduce = GradReducePlan(axes=axes, nranks=nranks, buckets=buckets)
    return RingAttnPlan(axis="sep", sep_degree=n, data_axes=data_axes,
                        axes=axes, nranks=nranks, reduce=reduce,
                        layers=layers)


# ---------------------------------------------------------------- probe

def ring_parity_probe(mesh=None, *, b=1, seq=None, heads=4, kv_heads=2,
                      d=32, seed=0):
    """Ring-vs-dense numeric probe for the bench ``"ring"`` block: run
    the shard_map ring over the live ``sep`` axis on a small causal GQA
    problem and report the max relative error against the dense
    reference. ``tools/bench_gate.py`` fails a ``*_seq32k`` round whose
    probe drifts past the threshold — reference-free, like the comms
    parity gate. Threshold 1e-3: the ring reassociates online-softmax
    accumulation (~1e-6 relative in f32); anything near 1e-3 means the
    merge or a hop mask regressed, not rounding."""
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None:
        from ..fleet import active_mesh

        mesh = active_mesh()
    if (mesh is None or not ring_attn_enabled()
            or "sep" not in mesh.dim_names
            or mesh.get_dim_size("sep") < 2):
        return {"enabled": False}
    n = mesh.get_dim_size("sep")
    # the probe is a NUMERICS gate, not a topology one: run it on a
    # dedicated 1-D sep mesh — a ppermute inside a partial-auto region
    # (live dp axes left automatic) hits an XLA partitioner abort on
    # this backend, and the real train-step region is fully manual
    # anyway (every live axis named)
    from jax.sharding import Mesh

    probe_mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))
    if seq is None:
        seq = 8 * n
    rng = np.random.default_rng(seed)
    mk = lambda h: jnp.asarray(
        rng.standard_normal((b, seq, h, d)).astype(np.float32))
    q, k, v = mk(heads), mk(kv_heads), mk(kv_heads)
    scale = 1.0 / math.sqrt(d)
    perm = zigzag_perm(seq, n)
    inv = zigzag_inverse_perm(seq, n)
    spec = PartitionSpec(None, "sep", None, None)

    def per_shard(qz, kz, vz, sep_id):
        ctx = RingContext("sep", n, sep_id[0])
        return ring_attention(qz, kz, vz, ctx, causal=True, scale=scale)

    sep_ids = jnp.arange(n, dtype=jnp.int32)
    mapped = jax.jit(jax.shard_map(
        per_shard, mesh=probe_mesh,
        in_specs=(spec, spec, spec, PartitionSpec("sep")),
        out_specs=spec, check_vma=False, axis_names={"sep"}))
    sh = NamedSharding(probe_mesh, spec)
    out_z = mapped(jax.device_put(jnp.take(q, perm, 1), sh),
                   jax.device_put(jnp.take(k, perm, 1), sh),
                   jax.device_put(jnp.take(v, perm, 1), sh),
                   jax.device_put(sep_ids,
                                  NamedSharding(probe_mesh,
                                                PartitionSpec("sep"))))
    out = np.asarray(jnp.take(out_z, inv, 1))
    # dense reference (GQA expanded), end-to-end f32
    rep = heads // kv_heads
    kf = np.repeat(np.asarray(k), rep, axis=2)
    vf = np.repeat(np.asarray(v), rep, axis=2)
    s = np.einsum("bshd,bthd->bhst", np.asarray(q) * scale, kf)
    mask = np.tril(np.ones((seq, seq), bool))
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ref = np.einsum("bhst,bthd->bshd", p, vf)
    denom = max(float(np.abs(ref).max()), 1e-6)
    err = float(np.abs(out - ref).max() / denom)
    threshold = 1e-3
    return {"enabled": True, "axis": "sep", "sep_degree": n, "seq": seq,
            "max_rel_err": err, "threshold": threshold,
            "ok": err <= threshold}
